"""PinotFS analog: pluggable filesystem abstraction + segment deep store.

Reference parity: pinot-spi filesystem/PinotFS.java — the deep-store
abstraction behind segment upload/download (s3/gcs/adls/hdfs plugins in
pinot-plugins/pinot-file-system). Committed segments are tarred and
uploaded at commit; a replica told to DISCARD (or a restarted server)
fetches the committed copy back through the same interface, so losing a
server loses no committed data (ref SplitSegmentCommitter + the
peer-download path, SURVEY.md §5 checkpoint/resume).

Filesystems register by URI scheme (the plugin seam — additional schemes
plug in via register_fs, ref PinotFSFactory).
"""
from __future__ import annotations

import os
import shutil
import tarfile
import tempfile
from typing import Callable, Dict, List, Type
from urllib.parse import urlparse


class PinotFS:
    """Scheme-addressed file operations (ref PinotFS.java contract)."""

    def mkdir(self, uri: str) -> None:
        raise NotImplementedError

    def delete(self, uri: str) -> bool:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def length(self, uri: str) -> int:
        raise NotImplementedError

    def listdir(self, uri: str) -> List[str]:
        raise NotImplementedError

    def copy_from_local(self, src_path: str, dst_uri: str) -> None:
        raise NotImplementedError

    def copy_to_local(self, src_uri: str, dst_path: str) -> None:
        raise NotImplementedError


class LocalPinotFS(PinotFS):
    """file:// scheme over the local filesystem (ref LocalPinotFS.java) —
    the first deep-store backing; network-FS schemes register the same
    way."""

    @staticmethod
    def _path(uri: str) -> str:
        p = urlparse(uri)
        if p.scheme not in ("", "file"):
            raise ValueError(f"not a local uri: {uri}")
        return p.path if p.scheme else uri

    def mkdir(self, uri: str) -> None:
        os.makedirs(self._path(uri), exist_ok=True)

    def delete(self, uri: str) -> bool:
        path = self._path(uri)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
            return True
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))

    def length(self, uri: str) -> int:
        return os.path.getsize(self._path(uri))

    def listdir(self, uri: str) -> List[str]:
        return sorted(os.listdir(self._path(uri)))

    def copy_from_local(self, src_path: str, dst_uri: str) -> None:
        dst = self._path(dst_uri)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        shutil.copyfile(src_path, tmp)
        os.replace(tmp, dst)  # atomic publish

    def copy_to_local(self, src_uri: str, dst_path: str) -> None:
        os.makedirs(os.path.dirname(dst_path) or ".", exist_ok=True)
        shutil.copyfile(self._path(src_uri), dst_path)


def register_fs(scheme: str, factory: Callable[[], PinotFS]) -> None:
    """Plugin seam (ref PinotFSFactory.register over PluginManager)."""
    from pinot_tpu.utils import plugins
    plugins.register("fs", scheme or "file", factory)


def get_fs(uri: str) -> PinotFS:
    from pinot_tpu.utils import plugins
    scheme = urlparse(uri).scheme or "file"
    try:
        factory = plugins.get("fs", scheme)
    except KeyError:
        raise ValueError(f"no PinotFS registered for scheme {scheme!r}")
    return factory()


register_fs("file", LocalPinotFS)


# ---------------------------------------------------------------------------
# deep store
# ---------------------------------------------------------------------------

class SegmentDeepStore:
    """Tar-per-segment store under a base URI (ref the controller's
    segment store + SegmentCompletionUtils naming)."""

    def __init__(self, base_uri: str):
        if "://" not in base_uri:
            base_uri = "file://" + os.path.abspath(base_uri)
        self.base_uri = base_uri.rstrip("/")
        self.fs = get_fs(base_uri)

    def segment_uri(self, table: str, segment_name: str) -> str:
        return f"{self.base_uri}/{table}/{segment_name}.tar.gz"

    def upload(self, seg_dir: str, table: str, segment_name: str,
               unique: bool = False) -> str:
        """Tar + push a built segment directory; returns its store URI.

        unique: append a per-attempt token to the stored name (ref
        SegmentCompletionUtils' UUID suffix) — a stale de-elected
        committer finishing late must NOT overwrite the winner's tar at a
        deterministic path."""
        stored = segment_name
        if unique:
            import uuid
            stored = f"{segment_name}.{uuid.uuid4().hex[:8]}"
        uri = self.segment_uri(table, stored)
        with tempfile.NamedTemporaryFile(suffix=".tar.gz",
                                         delete=False) as tmp:
            tmp_path = tmp.name
        try:
            with tarfile.open(tmp_path, "w:gz") as tar:
                # arcname == the stored (possibly attempt-unique) name so
                # the extracted dir matches the tar file and the localize
                # cache can find it again; the TRUE segment name lives in
                # metadata.json, which is what the loader uses
                tar.add(seg_dir, arcname=stored)
            self.fs.copy_from_local(tmp_path, uri)
        finally:
            os.remove(tmp_path)
        return uri

    def download(self, uri: str, dest_dir: str) -> str:
        """Fetch + untar a segment; returns the local segment directory."""
        return download_segment(uri, dest_dir)

    def delete(self, table: str, segment_name: str) -> bool:
        return self.fs.delete(self.segment_uri(table, segment_name))


def download_segment(uri: str, dest_dir: str) -> str:
    """Fetch + untar a stored segment by URI (peer/deep-store download,
    ref BaseTableDataManager.downloadSegment); returns the local dir."""
    fs = get_fs(uri)
    os.makedirs(dest_dir, exist_ok=True)
    with tempfile.NamedTemporaryFile(suffix=".tar.gz", delete=False) as tmp:
        tmp_path = tmp.name
    try:
        fs.copy_to_local(uri, tmp_path)
        with tarfile.open(tmp_path, "r:gz") as tar:
            top = tar.getnames()[0].split("/")[0]
            tar.extractall(dest_dir, filter="data")
    finally:
        os.remove(tmp_path)
    return os.path.join(dest_dir, top)


def is_store_uri(path: str) -> bool:
    """True when a segment 'dir_path' is a deep-store URI (tarball),
    not a directly loadable local directory."""
    return "://" in path and path.endswith(".tar.gz")


def localize_segment(dir_path: str, cache_dir: str) -> str:
    """Resolve a SegmentState dir_path to a loadable local directory:
    plain paths pass through; deep-store URIs download into cache_dir
    (reusing an already-extracted copy). Shared by server reconcile and
    minion task inputs."""
    if not is_store_uri(dir_path):
        return dir_path
    name = os.path.basename(urlparse(dir_path).path)
    if name.endswith(".tar.gz"):
        name = name[: -len(".tar.gz")]
    existing = os.path.join(cache_dir, name)
    if os.path.exists(os.path.join(existing, "metadata.json")):
        return existing
    return download_segment(dir_path, cache_dir)
