"""FST-style regex/prefix index over sorted term dictionaries.

Reference parity: pinot-segment-local
segment/index/readers/LuceneFSTIndexReader.java + the native FST package
(segment/local/utils/nativefst/ImmutableFST.java) — REGEXP_LIKE / LIKE
'pre%' on a dictionary column should not regex-scan the whole dictionary
per query.

Clean-room design: the segment's term dictionary is ALREADY a sorted
array (the Lucene term-dictionary property), so the index is
(a) an anchored-literal-prefix decomposition of the pattern,
(b) O(log n) binary-search candidate ranges over the sorted terms, and
(c) residual regex verification only inside the candidate range,
with a per-segment LRU of resolved (pattern -> dictId set) so repeated
filters cost one lookup. Patterns with no usable anchored prefix fall
back to a full dictionary scan (Lucene pays an automaton walk there too).
"""
from __future__ import annotations

import re
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

#: regex metacharacters that end a literal prefix
_META = set(".^$*+?{}[]()|\\")


def literal_prefix(pattern: str) -> Tuple[Optional[str], bool]:
    """(anchored literal prefix, whole_pattern_is_prefix) of a regex.

    Returns (None, False) when the pattern is not start-anchored (a
    'search' semantics match can begin anywhere, so no range narrowing is
    sound). whole=True means the pattern is exactly '^literal.*'-shaped
    ('pre%' LIKE translations), so candidates need NO regex verification.
    """
    if not pattern.startswith("^"):
        return None, False
    if _has_toplevel_alternation(pattern):
        # '^ab|cd' anchors only the FIRST branch — no sound range exists
        return None, False
    i, n = 1, len(pattern)
    out = []
    while i < n:
        c = pattern[i]
        if c == "\\" and i + 1 < n and pattern[i + 1] in _META:
            out.append(pattern[i + 1])
            i += 2
            continue
        if c in _META:
            break
        out.append(c)
        i += 1
    # a quantifier that can match ZERO occurrences ('*', '?', '{0,..}')
    # makes the last collected literal optional — drop it from the prefix
    # ('^abc*' matches 'ab')
    if i < n and pattern[i] in "*?{" and out:
        out.pop()
    prefix = "".join(out)
    if not prefix:
        return None, False
    rest = pattern[i:]
    # '$' alone is exact-match, NOT prefix-match ('^abc$' must not accept
    # 'abcd'), so it still verifies candidates with the regex
    whole = rest in ("", ".*", ".*$")
    return prefix, whole


def _has_toplevel_alternation(pattern: str) -> bool:
    depth = 0
    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "\\":
            i += 2
            continue
        if c == "[":  # character class: skip to its closing bracket
            i += 1
            if i < n and pattern[i] == "]":
                i += 1
            while i < n and pattern[i] != "]":
                i += 2 if pattern[i] == "\\" else 1
        elif c == "(":
            depth += 1
        elif c == ")":
            depth = max(0, depth - 1)
        elif c == "|" and depth == 0:
            return True
        i += 1
    return False


def prefix_range(sorted_terms: np.ndarray, prefix: str) -> Tuple[int, int]:
    """[lo, hi) dictId range of terms starting with `prefix` — two binary
    searches over the sorted dictionary (the FST arc-walk analog)."""
    lo = int(np.searchsorted(sorted_terms, prefix, side="left"))
    hi = int(np.searchsorted(sorted_terms, prefix + "\U0010FFFF",
                             side="right"))
    return lo, hi


class FstIndex:
    """Per-column regex resolver over the sorted dictionary terms."""

    CACHE_SIZE = 128

    def __init__(self, sorted_terms: np.ndarray):
        #: term dictionary, value-sorted (the segment dictionary invariant)
        self.terms = np.asarray(sorted_terms)
        self._cache: "OrderedDict[str, np.ndarray]" = OrderedDict()

    def matching_dict_ids(self, pattern: str) -> np.ndarray:
        """Sorted int32 dictIds whose term matches the (search-semantics)
        regex pattern."""
        hit = self._cache.get(pattern)
        if hit is not None:
            self._cache.move_to_end(pattern)
            return hit
        ids = self._resolve(pattern)
        self._cache[pattern] = ids
        if len(self._cache) > self.CACHE_SIZE:
            self._cache.popitem(last=False)
        return ids

    def _resolve(self, pattern: str) -> np.ndarray:
        prefix, whole = literal_prefix(pattern)
        if self.terms.dtype.kind not in "OSU" or (
                len(self.terms) and
                not isinstance(self.terms[0], (str, np.str_))):
            prefix = None  # numeric/bytes dictionary: no str prefix order
        if prefix is not None:
            lo, hi = prefix_range(self.terms, prefix)
            if lo >= hi:
                return np.empty(0, np.int32)
            if whole:
                return np.arange(lo, hi, dtype=np.int32)
            rx = re.compile(pattern)
            keep = [i for i in range(lo, hi)
                    if rx.search(str(self.terms[i]))]
            return np.asarray(keep, np.int32)
        # no sound range: full scan (documented fallback)
        rx = re.compile(pattern)
        mask = np.fromiter((bool(rx.search(str(v)))
                            for v in self.terms.tolist()),
                           dtype=bool, count=len(self.terms))
        return np.nonzero(mask)[0].astype(np.int32)
