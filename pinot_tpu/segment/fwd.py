"""Forward indexes: per-column doc->value storage.

Reference parity: pinot-segment-spi index/reader/ForwardIndexReader.java:38
(readDictIds:116 batch API, readValuesSV:156) and the pinot-segment-local
implementations (FixedBitSVForwardIndexReaderV2, FixedBitMVForwardIndexReader,
BaseChunkForwardIndexReader / VarByteChunkForwardIndexReaderV4).

Variants (our own byte formats):
  SV dict-encoded : fixed-bit MSB-first bitstream of dictIds (bitpack.py).
  MV dict-encoded : int32 offsets[n+1] + fixed-bit bitstream of flattened ids.
  SV raw fixed    : chunked values, header + per-chunk compressed blocks.
  SV raw var-byte : chunked (offsets + blob) per chunk, compressed blocks.

Readers decode whole columns into numpy arrays (the batch-only contract — no
per-doc calls; the TPU path consumes the full decoded block, the CPU path
slices it).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from pinot_tpu.models.field_spec import DataType
from pinot_tpu.segment import bitpack, codec

_CHUNK_DOCS = 64 * 1024  # docs per compression chunk (raw columns)
_HDR = struct.Struct("<iiii")  # codec_id, num_chunks, chunk_docs, reserved


# ---------------------------------------------------------------------------
# SV dictionary-encoded (the TPU hot path)
# ---------------------------------------------------------------------------

def write_sv_dict(dict_ids: np.ndarray, bits: int) -> bytes:
    return bitpack.pack(dict_ids, bits)


def read_sv_dict(buf, num_docs: int, bits: int) -> np.ndarray:
    """Bulk-unpack all dictIds to int32 (ref FixedBitIntReaderWriterV2:99-124)."""
    from pinot_tpu.native import lib
    if lib is not None:
        raw = bytes(buf[: bitpack.packed_size(num_docs, bits)]) \
            if not isinstance(buf, (bytes, bytearray)) else buf
        return lib.bitunpack32(raw, num_docs, bits)
    return bitpack.unpack(buf, num_docs, bits)


# ---------------------------------------------------------------------------
# MV dictionary-encoded
# ---------------------------------------------------------------------------

def write_mv_dict(values_per_doc: List[np.ndarray], bits: int) -> bytes:
    lens = np.array([len(v) for v in values_per_doc], dtype=np.int32)
    offsets = np.zeros(len(values_per_doc) + 1, dtype=np.int32)
    np.cumsum(lens, out=offsets[1:])
    flat = (np.concatenate(values_per_doc).astype(np.int32)
            if len(values_per_doc) else np.empty(0, dtype=np.int32))
    return offsets.tobytes() + bitpack.pack(flat, bits)


def read_mv_dict(buf, num_docs: int, bits: int):
    """Returns (offsets int32[n+1], flat dictIds int32[total])."""
    raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
        else np.asarray(buf, dtype=np.uint8)
    off_bytes = (num_docs + 1) * 4
    offsets = raw[:off_bytes].view(np.int32)
    total = int(offsets[-1])
    flat = bitpack.unpack(raw[off_bytes:], total, bits)
    return offsets, flat


# ---------------------------------------------------------------------------
# Raw (no-dictionary) chunked forward index
# ---------------------------------------------------------------------------

def write_raw_fixed(values: np.ndarray, compression: str) -> bytes:
    """Fixed-width raw column, chunk-compressed."""
    cid = codec.codec_id(compression)
    n = len(values)
    chunks = []
    actual = codec.resolve(cid)
    for start in range(0, max(n, 1), _CHUNK_DOCS):
        chunk = np.ascontiguousarray(values[start:start + _CHUNK_DOCS]).tobytes()
        actual, comp = codec.compress(chunk, actual)
        chunks.append(comp)
    return _assemble(actual, chunks, _CHUNK_DOCS)


def read_raw_fixed(buf, num_docs: int, dtype: np.dtype) -> np.ndarray:
    cid, nchunks, chunk_docs, offsets, payload = _disassemble(buf)
    itemsize = np.dtype(dtype).itemsize
    out = np.empty(num_docs, dtype=dtype)
    for i in range(nchunks):
        docs = min(chunk_docs, num_docs - i * chunk_docs)
        raw = codec.decompress(payload[offsets[i]:offsets[i + 1]], cid, docs * itemsize)
        out[i * chunk_docs:i * chunk_docs + docs] = np.frombuffer(raw, dtype=dtype, count=docs)
    return out


def write_raw_var(values: List, compression: str, is_bytes: bool) -> bytes:
    """Var-width raw column (STRING/BYTES/JSON), chunk-compressed.

    Per chunk: int32 count, int32 offsets[count+1], blob.
    """
    cid = codec.resolve(codec.codec_id(compression))
    n = len(values)
    chunks = []
    actual = cid
    for start in range(0, max(n, 1), _CHUNK_DOCS):
        part = values[start:start + _CHUNK_DOCS]
        encoded = [v if is_bytes else str(v).encode("utf-8") for v in part]
        offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
        if encoded:
            np.cumsum([len(b) for b in encoded], out=offsets[1:])
        raw = struct.pack("<i", len(encoded)) + offsets.tobytes() + b"".join(encoded)
        actual, comp = codec.compress(raw, actual)
        chunks.append((comp, len(raw)))
    raw_sizes = np.array([r for _, r in chunks], dtype=np.int64)
    blob_chunks = [c for c, _ in chunks]
    return _assemble(actual, blob_chunks, _CHUNK_DOCS, raw_sizes)


def read_raw_var(buf, num_docs: int, is_bytes: bool) -> np.ndarray:
    cid, nchunks, chunk_docs, offsets, payload, raw_sizes = _disassemble(buf, with_sizes=True)
    out = np.empty(num_docs, dtype=object)
    pos = 0
    for i in range(nchunks):
        raw = codec.decompress(payload[offsets[i]:offsets[i + 1]], cid, int(raw_sizes[i]))
        (count,) = struct.unpack_from("<i", raw, 0)
        offs = np.frombuffer(raw, dtype=np.int32, count=count + 1, offset=4)
        blob = raw[4 + (count + 1) * 4:]
        for j in range(count):
            chunk = blob[offs[j]:offs[j + 1]]
            out[pos] = chunk if is_bytes else chunk.decode("utf-8")
            pos += 1
    return out


# ---------------------------------------------------------------------------
# container format helpers
# ---------------------------------------------------------------------------

def _assemble(cid: int, chunks: List[bytes], chunk_docs: int,
              raw_sizes: Optional[np.ndarray] = None) -> bytes:
    offsets = np.zeros(len(chunks) + 1, dtype=np.int64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    head = _HDR.pack(cid, len(chunks), chunk_docs, 1 if raw_sizes is not None else 0)
    parts = [head, offsets.tobytes()]
    if raw_sizes is not None:
        parts.append(raw_sizes.astype(np.int64).tobytes())
    parts.extend(chunks)
    return b"".join(parts)


def _disassemble(buf, with_sizes: bool = False):
    raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
        else np.asarray(buf, dtype=np.uint8)
    cid, nchunks, chunk_docs, has_sizes = _HDR.unpack(raw[:_HDR.size].tobytes())
    pos = _HDR.size
    offsets = raw[pos:pos + (nchunks + 1) * 8].view(np.int64)
    pos += (nchunks + 1) * 8
    raw_sizes = None
    if has_sizes:
        raw_sizes = raw[pos:pos + nchunks * 8].view(np.int64)
        pos += nchunks * 8
    payload = raw[pos:]
    if with_sizes:
        return cid, nchunks, chunk_docs, offsets, payload, raw_sizes
    return cid, nchunks, chunk_docs, offsets, payload
