"""Geospatial index: hierarchical grid cells with posting lists.

Reference parity: pinot-segment-local
segment/index/readers/geospatial/ + creator/impl/geospatial/ (H3
hex-cell index behind ST_DISTANCE range filters,
core/operator/filter/H3IndexFilterOperator: cover the query circle with
cells at the index resolution, union the postings, exact-verify the
boundary cells).

Clean-room cell scheme (no H3 dependency): a fixed-resolution
equirectangular lat/lng grid — cell id packs (lat_bin, lng_bin) into an
int64. Square cells change the covering geometry but not the algorithm:
candidate = union of postings of all cells intersecting the circle's
bounding box, then exact haversine verification (the reference verifies
boundary cells the same way). Points are (lat, lng) float64 pairs.
"""
from __future__ import annotations

import struct
from typing import Dict, Tuple

import numpy as np

EARTH_RADIUS_M = 6_371_008.8

_HDR = struct.Struct("<IdI")


def haversine_m(lat1, lng1, lat2, lng2) -> np.ndarray:
    """Great-circle distance in meters (vectorized)."""
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lng2) - np.radians(lng1)
    a = np.sin(dp / 2) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def parse_point(v) -> Tuple[float, float]:
    """'lat,lng' -> floats; malformed/null -> (nan, nan) so the row never
    matches a distance query (shared by index build + scan fallback so
    both paths agree on bad data)."""
    try:
        a, b = str(v).split(",")
        return float(a), float(b)
    except (ValueError, AttributeError, TypeError):
        return float("nan"), float("nan")


class GeoIndex:
    """Fixed-resolution grid cells -> doc posting lists."""

    #: default cell edge in degrees (~1.1 km of latitude)
    DEFAULT_RES_DEG = 0.01

    def __init__(self, lats: np.ndarray, lngs: np.ndarray,
                 res_deg: float, cells: Dict[int, np.ndarray]):
        self.lats = lats
        self.lngs = lngs
        self.res_deg = res_deg
        self.cells = cells

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, lats, lngs, res_deg: float = DEFAULT_RES_DEG
              ) -> "GeoIndex":
        lats = np.asarray(lats, np.float64)
        lngs = np.asarray(lngs, np.float64)
        # NaN coordinates (malformed/null points) index into no cell, so
        # they can never spuriously match a distance query
        valid = ~(np.isnan(lats) | np.isnan(lngs))
        ids = cls._cell_ids(np.where(valid, lats, 0.0),
                            np.where(valid, lngs, 0.0), res_deg)
        ids = np.where(valid, ids, np.int64(-1))
        order = np.argsort(ids, kind="stable")
        order = order[ids[order] >= 0]
        if len(order) == 0:
            return cls(lats, lngs, res_deg, {})
        sorted_ids = ids[order]
        bounds = np.flatnonzero(np.r_[True, sorted_ids[1:]
                                      != sorted_ids[:-1]])
        cells: Dict[int, np.ndarray] = {}
        for i, b in enumerate(bounds):
            e = bounds[i + 1] if i + 1 < len(bounds) else len(sorted_ids)
            cells[int(sorted_ids[b])] = np.sort(order[b:e]).astype(np.int32)
        return cls(lats, lngs, res_deg, cells)

    @staticmethod
    def _cell_ids(lats, lngs, res_deg: float) -> np.ndarray:
        la = np.floor((np.asarray(lats) + 90.0) / res_deg).astype(np.int64)
        lo = np.floor((np.asarray(lngs) + 180.0) / res_deg).astype(np.int64)
        return (la << 32) | lo

    # ------------------------------------------------------------------
    def within_distance(self, lat: float, lng: float,
                        meters: float) -> np.ndarray:
        """Sorted doc ids within `meters` of the point (exact — the grid
        only prunes candidates, ref H3IndexFilterOperator's full-match +
        boundary-verify split)."""
        # degree extent of the radius (lng shrinks by cos(lat))
        dlat = np.degrees(meters / EARTH_RADIUS_M)
        coslat = max(np.cos(np.radians(lat)), 1e-6)
        dlng = dlat / coslat
        la_lo = int(np.floor((lat - dlat + 90.0) / self.res_deg))
        la_hi = int(np.floor((lat + dlat + 90.0) / self.res_deg))
        lo_lo = int(np.floor((lng - dlng + 180.0) / self.res_deg))
        lo_hi = int(np.floor((lng + dlng + 180.0) / self.res_deg))
        # longitude wraps at the antimeridian: bins are modulo the globe
        # (a query at lng 179.99 must probe cells stored near -180)
        nlng = max(int(round(360.0 / self.res_deg)), 1)
        cand_parts = []
        for la in range(la_lo, la_hi + 1):
            for lo in range(lo_lo, lo_hi + 1):
                ids = self.cells.get((la << 32) | (lo % nlng))
                if ids is not None:
                    cand_parts.append(ids)
        if not cand_parts:
            return np.empty(0, np.int32)
        cand = np.concatenate(cand_parts)
        d = haversine_m(self.lats[cand], self.lngs[cand], lat, lng)
        return np.sort(cand[d <= meters]).astype(np.int32)

    # ------------------------------------------------------------------
    def to_bytes(self) -> bytes:
        n = len(self.lats)
        out = [_HDR.pack(n, self.res_deg, len(self.cells)),
               self.lats.astype("<f8").tobytes(),
               self.lngs.astype("<f8").tobytes()]
        for cid, ids in self.cells.items():
            out.append(struct.pack("<qI", cid, len(ids)))
            out.append(ids.astype("<i4").tobytes())
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf) -> "GeoIndex":
        buf = bytes(buf)
        n, res, ncells = _HDR.unpack_from(buf, 0)
        pos = _HDR.size
        lats = np.frombuffer(buf, "<f8", n, pos).copy()
        pos += 8 * n
        lngs = np.frombuffer(buf, "<f8", n, pos).copy()
        pos += 8 * n
        cells: Dict[int, np.ndarray] = {}
        for _ in range(ncells):
            cid, cnt = struct.unpack_from("<qI", buf, pos)
            pos += 12
            cells[cid] = np.frombuffer(buf, "<i4", cnt, pos).copy()
            pos += 4 * cnt
        return cls(lats, lngs, res, cells)
