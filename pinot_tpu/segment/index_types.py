"""Index-type registry keys.

Reference parity: pinot-segment-spi index/StandardIndexes.java — the canonical
set of index types a column may carry. Extension indexes register here too
(ref IndexPlugin/IndexService ServiceLoader mechanism).
"""
DICTIONARY = "dictionary"
FORWARD = "forward_index"
INVERTED = "inverted_index"
RANGE = "range_index"
SORTED = "sorted_index"
BLOOM = "bloom_filter"
NULLVECTOR = "nullvalue_vector"
JSON = "json_index"
TEXT = "text_index"
FST = "fst_index"
VECTOR = "vector_index"
GEO = "geo_index"
MAP = "map_index"
STARTREE = "startree_index"
STARTREE_DATA = "startree_data"
CLP = "clp_forward"  # y-scope CLP log-compressed forward index

ALL = [DICTIONARY, FORWARD, INVERTED, RANGE, SORTED, BLOOM, NULLVECTOR,
       JSON, TEXT, FST, VECTOR, GEO, MAP, STARTREE, STARTREE_DATA, CLP]
