"""Auxiliary per-column indexes: inverted, range, sorted, bloom.

Reference parity (pinot-segment-local segment/index/readers/):
  inverted -> BitmapInvertedIndexReader (RoaringBitmap per dictId); here a CSR
              of sorted doc-id lists per dictId, converted to dense Bitmaps or
              doc-id arrays at query time.
  range    -> RangeIndexReaderImpl (bitmap per value bucket, with exact /
              partial match split); here contiguous dictId buckets + CSR.
  sorted   -> sorted/SortedIndexReader (per-dictId [start,end) doc ranges).
  bloom    -> readers/bloom/ (guava-style); here double-hashed FNV/CRC bits.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, Optional, Tuple

import numpy as np

from pinot_tpu.segment.bitmap import Bitmap


# ---------------------------------------------------------------------------
# Inverted index: dictId -> sorted doc ids (CSR)
# ---------------------------------------------------------------------------

class InvertedIndex:
    def __init__(self, offsets: np.ndarray, doc_ids: np.ndarray, num_docs: int):
        self._offsets = offsets  # int64[card+1]
        self._doc_ids = doc_ids  # int32[num_docs] for SV
        self.num_docs = num_docs

    @classmethod
    def build(cls, dict_ids: np.ndarray, cardinality: int, num_docs: int) -> "InvertedIndex":
        order = np.argsort(dict_ids, kind="stable")
        counts = np.bincount(dict_ids, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(offsets, order.astype(np.int32), num_docs)

    @classmethod
    def build_mv(cls, mv_offsets: np.ndarray, flat_ids: np.ndarray, cardinality: int,
                 num_docs: int) -> "InvertedIndex":
        # doc of flat position i = searchsorted(mv_offsets, i, 'right') - 1
        docs = (np.searchsorted(mv_offsets[1:], np.arange(len(flat_ids)), side="right")
                ).astype(np.int32)
        order = np.argsort(flat_ids, kind="stable")
        counts = np.bincount(flat_ids, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(offsets, docs[order], num_docs)

    def doc_ids_for(self, dict_id: int) -> np.ndarray:
        s, e = self._offsets[dict_id], self._offsets[dict_id + 1]
        return self._doc_ids[s:e]

    def doc_ids_for_many(self, dict_ids: np.ndarray) -> np.ndarray:
        parts = [self.doc_ids_for(int(d)) for d in dict_ids]
        if not parts:
            return np.empty(0, dtype=np.int32)
        return np.unique(np.concatenate(parts))

    def bitmap_for(self, dict_id: int) -> Bitmap:
        return Bitmap.from_indices(self.num_docs, self.doc_ids_for(dict_id))

    def to_bytes(self) -> bytes:
        return (struct.pack("<qq", len(self._offsets) - 1, self.num_docs)
                + self._offsets.tobytes() + self._doc_ids.tobytes())

    @classmethod
    def from_bytes(cls, buf) -> "InvertedIndex":
        raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
            else np.asarray(buf, dtype=np.uint8)
        card, num_docs = struct.unpack("<qq", raw[:16].tobytes())
        pos = 16
        offsets = raw[pos:pos + (card + 1) * 8].view(np.int64)
        pos += (card + 1) * 8
        doc_ids = raw[pos:].view(np.int32)
        return cls(offsets, doc_ids, num_docs)


# ---------------------------------------------------------------------------
# Range index: contiguous dictId buckets -> doc lists
# ---------------------------------------------------------------------------

class RangeIndex:
    """Buckets the dictId space into <=64 contiguous ranges; per bucket the
    sorted doc-id list. A range predicate resolves to fully-covered buckets
    (exact docs) plus at most two partial buckets (need scan refinement) —
    mirrors RangeIndexReaderImpl's matching/partially-matching contract."""

    def __init__(self, bucket_starts: np.ndarray, offsets: np.ndarray,
                 doc_ids: np.ndarray, num_docs: int):
        self._bucket_starts = bucket_starts  # int32[nb+1], dictId boundaries
        self._offsets = offsets              # int64[nb+1]
        self._doc_ids = doc_ids
        self.num_docs = num_docs

    @classmethod
    def build(cls, dict_ids: np.ndarray, cardinality: int, num_docs: int,
              num_buckets: int = 64) -> "RangeIndex":
        nb = min(num_buckets, max(cardinality, 1))
        bounds = np.linspace(0, cardinality, nb + 1).astype(np.int32)
        bucket_of = np.searchsorted(bounds[1:], dict_ids, side="right").astype(np.int32)
        order = np.argsort(bucket_of, kind="stable")
        counts = np.bincount(bucket_of, minlength=nb)
        offsets = np.zeros(nb + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(bounds, offsets, order.astype(np.int32), num_docs)

    def query(self, lo_id: int, hi_id: int) -> Tuple[np.ndarray, np.ndarray]:
        """dictId range [lo_id, hi_id] inclusive -> (exact_docs, candidate_docs).

        candidate_docs need per-doc verification against the forward index.
        """
        nb = len(self._bucket_starts) - 1
        b_lo = int(np.searchsorted(self._bucket_starts[1:], lo_id, side="right"))
        b_hi = int(np.searchsorted(self._bucket_starts[1:], hi_id, side="right"))
        b_hi = min(b_hi, nb - 1)
        exact, cand = [], []
        for b in range(b_lo, b_hi + 1):
            docs = self._doc_ids[self._offsets[b]:self._offsets[b + 1]]
            full = (self._bucket_starts[b] >= lo_id
                    and self._bucket_starts[b + 1] - 1 <= hi_id)
            (exact if full else cand).append(docs)
        cat = lambda ps: (np.sort(np.concatenate(ps)).astype(np.int32) if ps
                          else np.empty(0, dtype=np.int32))
        return cat(exact), cat(cand)

    def to_bytes(self) -> bytes:
        nb = len(self._bucket_starts) - 1
        return (struct.pack("<qq", nb, self.num_docs)
                + self._bucket_starts.tobytes() + self._offsets.tobytes()
                + self._doc_ids.tobytes())

    @classmethod
    def from_bytes(cls, buf) -> "RangeIndex":
        raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
            else np.asarray(buf, dtype=np.uint8)
        nb, num_docs = struct.unpack("<qq", raw[:16].tobytes())
        pos = 16
        bucket_starts = raw[pos:pos + (nb + 1) * 4].view(np.int32)
        pos += (nb + 1) * 4
        offsets = raw[pos:pos + (nb + 1) * 8].view(np.int64)
        pos += (nb + 1) * 8
        return cls(bucket_starts, offsets, raw[pos:].view(np.int32), num_docs)


# ---------------------------------------------------------------------------
# Sorted index: per-dictId [start, end) doc ranges for sorted columns
# ---------------------------------------------------------------------------

class SortedIndex:
    def __init__(self, ranges: np.ndarray):
        self._ranges = ranges  # int32[card, 2]

    @classmethod
    def build(cls, dict_ids: np.ndarray, cardinality: int) -> "SortedIndex":
        starts = np.searchsorted(dict_ids, np.arange(cardinality), side="left")
        ends = np.searchsorted(dict_ids, np.arange(cardinality), side="right")
        return cls(np.stack([starts, ends], axis=1).astype(np.int32))

    def range_for(self, dict_id: int) -> Tuple[int, int]:
        return int(self._ranges[dict_id, 0]), int(self._ranges[dict_id, 1])

    def range_for_ids(self, lo_id: int, hi_id: int) -> Tuple[int, int]:
        """[start, end) docs for dictIds in [lo_id, hi_id] inclusive."""
        if hi_id < lo_id:
            return 0, 0
        return int(self._ranges[lo_id, 0]), int(self._ranges[hi_id, 1])

    def to_bytes(self) -> bytes:
        return struct.pack("<q", len(self._ranges)) + self._ranges.tobytes()

    @classmethod
    def from_bytes(cls, buf) -> "SortedIndex":
        raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
            else np.asarray(buf, dtype=np.uint8)
        (card,) = struct.unpack("<q", raw[:8].tobytes())
        return cls(raw[8:8 + card * 8].view(np.int32).reshape(card, 2))


# ---------------------------------------------------------------------------
# Bloom filter (segment pruning on EQ predicates)
# ---------------------------------------------------------------------------

class BloomFilter:
    def __init__(self, bits: np.ndarray, k: int):
        self._bits = bits  # uint8 array
        self._k = k
        self._m = len(bits) * 8

    @classmethod
    def build(cls, values, fpp: float = 0.03, k: int = 5) -> "BloomFilter":
        n = max(len(values), 1)
        m = max(64, int(-n * np.log(fpp) / (np.log(2) ** 2)))
        m = (m + 7) // 8 * 8
        bf = cls(np.zeros(m // 8, dtype=np.uint8), k)
        for v in values:
            bf._add(bf._encode(v))
        return bf

    @staticmethod
    def _encode(value: Any) -> bytes:
        if isinstance(value, bytes):
            return value
        if isinstance(value, str):
            return value.encode("utf-8")
        if isinstance(value, (float, np.floating)):
            return struct.pack("<d", float(value))
        return struct.pack("<q", int(value))

    def _hashes(self, data: bytes) -> np.ndarray:
        h1 = zlib.crc32(data) & 0xFFFFFFFF
        h2 = zlib.adler32(data) | 1
        return (h1 + np.arange(self._k, dtype=np.int64) * h2) % self._m

    def _add(self, data: bytes) -> None:
        for pos in self._hashes(data):
            self._bits[pos >> 3] |= np.uint8(1 << (pos & 7))

    def might_contain(self, value: Any) -> bool:
        data = self._encode(value)
        for pos in self._hashes(data):
            if not (self._bits[pos >> 3] >> (pos & 7)) & 1:
                return False
        return True

    def to_bytes(self) -> bytes:
        return struct.pack("<qq", self._m, self._k) + self._bits.tobytes()

    @classmethod
    def from_bytes(cls, buf) -> "BloomFilter":
        raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, memoryview)) \
            else np.asarray(buf, dtype=np.uint8)
        m, k = struct.unpack("<qq", raw[:16].tobytes())
        return cls(raw[16:16 + m // 8].copy(), k)
