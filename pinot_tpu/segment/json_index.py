"""JSON index: flattened-record posting lists for json_match filters.

Reference parity: pinot-segment-local
segment/index/readers/json/ImmutableJsonIndexReader.java +
creator/impl/json/ — each JSON document flattens into one or more flat
records (nested arrays multiply records, Pinot-style), every (path, value)
pair maps to the flat-record ids containing it, and a flat->doc table maps
matches back to documents. json_match's filter string is SQL-predicate
syntax over double-quoted json paths, evaluated per FLAT RECORD (so
`"$.a.x"='1' AND "$.a.y"='2'` must hold inside one array element, the
reference's exclusive-or-inclusive array semantics in their default form).

Clean-room design: postings are numpy int32 arrays keyed by (path, value)
in plain dicts; serialization is a length-prefixed binary, not a Lucene
artifact.
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

_U32 = struct.Struct("<I")

#: value stored for JSON null (distinct from the string "null")
_NULL = "\x00null"


def _canon(v: Any) -> str:
    """Canonical posting value for a JSON scalar."""
    import math
    if v is None:
        return _NULL
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and math.isfinite(v) and v == int(v) \
            and abs(v) < 2 ** 53:
        return str(int(v))
    return str(v)


def flatten(doc: Any) -> List[Dict[str, str]]:
    """One parsed JSON value -> flat records of path -> canonical value.

    Objects nest with '.', array elements spawn one flat record each (the
    cartesian product across sibling arrays, ref JsonUtils.flatten), and
    each array path also posts under '[*]' so queries may ignore indexes.
    """
    records: List[Dict[str, str]] = [{}]

    # Each value is addressed by a set of alias paths (the indexed path plus
    # its '[*]' forms); a single traversal writes every alias into the same
    # flat record, so array nesting multiplies records only once per element.
    def add(recs: List[Dict[str, str]], paths: List[str], value: Any
            ) -> List[Dict[str, str]]:
        if isinstance(value, dict):
            for k, v in value.items():
                recs = add(
                    recs, [f"{p}.{k}" if p else str(k) for p in paths], v)
            return recs
        if isinstance(value, list):
            if not value:
                return recs
            alias_per_elem = [
                [q for p in paths for q in (f"{p}[{i}]", f"{p}[*]")]
                for i in range(len(value))]
            out: List[Dict[str, str]] = []
            for rec in recs:
                for i, v in enumerate(value):
                    out.extend(add([dict(rec)], alias_per_elem[i], v))
            return out
        for rec in recs:
            for p in paths:
                rec[p] = _canon(value)
        return recs

    return add(records, [""], doc)


class JsonIndex:
    """Posting lists over flattened JSON records."""

    def __init__(self, paths: Dict[str, Dict[str, np.ndarray]],
                 flat2doc: np.ndarray, num_docs: int):
        #: path -> value -> sorted flat-record ids
        self.paths = paths
        self.flat2doc = flat2doc
        self.num_docs = num_docs
        self.num_flats = len(flat2doc)

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, values, num_docs: int) -> "JsonIndex":
        paths: Dict[str, Dict[str, List[int]]] = {}
        flat2doc: List[int] = []
        for doc_id, raw in enumerate(values):
            try:
                parsed = json.loads(raw) if isinstance(raw, (str, bytes)) \
                    else raw
            except (ValueError, TypeError):
                parsed = None
            if parsed is None:
                parsed = {}
            for rec in flatten(parsed):
                fid = len(flat2doc)
                flat2doc.append(doc_id)
                for path, val in rec.items():
                    paths.setdefault(path, {}).setdefault(val, []).append(fid)
        frozen = {p: {v: np.asarray(ids, np.int32)
                      for v, ids in vals.items()}
                  for p, vals in paths.items()}
        return cls(frozen, np.asarray(flat2doc, np.int32), num_docs)

    # ------------------------------------------------------------------
    # flat-record set algebra
    # ------------------------------------------------------------------
    def _eq(self, path: str, value: str) -> np.ndarray:
        return self.paths.get(path, {}).get(value, np.empty(0, np.int32))

    def _exists(self, path: str) -> np.ndarray:
        vals = self.paths.get(path)
        if not vals:
            return np.empty(0, np.int32)
        return np.unique(np.concatenate(list(vals.values())))

    def _range(self, path: str, lo, hi, lo_inc: bool, hi_inc: bool
               ) -> np.ndarray:
        """Numeric range over the path's observed values."""
        vals = self.paths.get(path)
        if not vals:
            return np.empty(0, np.int32)
        hit = []
        for v, ids in vals.items():
            try:
                f = float(v)
            except ValueError:
                continue
            if lo is not None and (f < lo or (f == lo and not lo_inc)):
                continue
            if hi is not None and (f > hi or (f == hi and not hi_inc)):
                continue
            hit.append(ids)
        if not hit:
            return np.empty(0, np.int32)
        return np.unique(np.concatenate(hit))

    # ------------------------------------------------------------------
    def matching_flats(self, expr) -> np.ndarray:
        """Evaluate a parsed predicate tree (query.expressions nodes over
        quoted-path Identifiers) to a sorted flat-record id array."""
        from pinot_tpu.query.expressions import Function, Identifier, Literal

        def path_of(e) -> str:
            assert isinstance(e, Identifier), f"json path expected: {e}"
            p = e.name
            if p.startswith("$."):
                p = p[2:]
            elif p.startswith("$"):
                p = p[1:]
            return p

        def lit(e) -> str:
            assert isinstance(e, Literal), f"literal expected: {e}"
            return _canon(e.value)

        def num(e) -> float:
            assert isinstance(e, Literal)
            return float(e.value)

        def ev(e) -> np.ndarray:
            assert isinstance(e, Function), f"predicate expected: {e}"
            n = e.name
            if n == "and":
                out = ev(e.args[0])
                for a in e.args[1:]:
                    out = np.intersect1d(out, ev(a), assume_unique=False)
                return out
            if n == "or":
                return np.unique(np.concatenate(
                    [ev(a) for a in e.args]))
            if n == "not":
                inner = ev(e.args[0])
                return np.setdiff1d(np.arange(self.num_flats, dtype=np.int32),
                                    inner)
            p = path_of(e.args[0])
            if n == "equals":
                return self._eq(p, lit(e.args[1]))
            if n == "not_equals":
                return np.setdiff1d(self._exists(p),
                                    self._eq(p, lit(e.args[1])))
            if n == "in":
                return np.unique(np.concatenate(
                    [self._eq(p, lit(a)) for a in e.args[1:]] or
                    [np.empty(0, np.int32)]))
            if n == "not_in":
                bad = [self._eq(p, lit(a)) for a in e.args[1:]]
                return np.setdiff1d(
                    self._exists(p),
                    np.concatenate(bad) if bad else np.empty(0, np.int32))
            if n == "between":
                return self._range(p, num(e.args[1]), num(e.args[2]),
                                   True, True)
            if n == "greater_than":
                return self._range(p, num(e.args[1]), None, False, True)
            if n == "greater_than_or_equal":
                return self._range(p, num(e.args[1]), None, True, True)
            if n == "less_than":
                return self._range(p, None, num(e.args[1]), True, False)
            if n == "less_than_or_equal":
                return self._range(p, None, num(e.args[1]), True, True)
            if n == "is_null":
                all_flats = np.arange(self.num_flats, dtype=np.int32)
                return np.setdiff1d(all_flats, self._exists(p))
            if n == "is_not_null":
                return self._exists(p)
            raise ValueError(f"unsupported json_match predicate {n!r}")

        return ev(expr)

    def matching_docs(self, expr) -> np.ndarray:
        flats = self.matching_flats(expr)
        if not len(flats):
            return np.empty(0, np.int32)
        return np.unique(self.flat2doc[flats])

    # ------------------------------------------------------------------
    # serde
    # ------------------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = [_U32.pack(self.num_docs), _U32.pack(self.num_flats)]
        out.append(self.flat2doc.astype("<i4").tobytes())
        out.append(_U32.pack(len(self.paths)))
        for path, vals in self.paths.items():
            pb = path.encode()
            out += [_U32.pack(len(pb)), pb, _U32.pack(len(vals))]
            for v, ids in vals.items():
                vb = v.encode()
                out += [_U32.pack(len(vb)), vb, _U32.pack(len(ids)),
                        ids.astype("<i4").tobytes()]
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf) -> "JsonIndex":
        buf = bytes(buf)
        pos = 0

        def u32():
            nonlocal pos
            v = _U32.unpack_from(buf, pos)[0]
            pos += 4
            return v

        num_docs = u32()
        num_flats = u32()
        flat2doc = np.frombuffer(buf, "<i4", num_flats, pos).copy()
        pos += 4 * num_flats
        paths: Dict[str, Dict[str, np.ndarray]] = {}
        for _ in range(u32()):
            ln = u32()
            path = buf[pos:pos + ln].decode()
            pos += ln
            vals: Dict[str, np.ndarray] = {}
            for _ in range(u32()):
                vn = u32()
                v = buf[pos:pos + vn].decode()
                pos += vn
                n = u32()
                vals[v] = np.frombuffer(buf, "<i4", n, pos).copy()
                pos += 4 * n
            paths[path] = vals
        return cls(paths, flat2doc, num_docs)


# ---------------------------------------------------------------------------
# json path extraction (json_extract_scalar — no index required)
# ---------------------------------------------------------------------------

def extract_path(doc: Any, path: str) -> Any:
    """Navigate '$.a.b[0].c'-style paths through a parsed JSON value."""
    if path.startswith("$"):
        path = path[1:]
    cur = doc
    for part in _path_parts(path):
        if cur is None:
            return None
        if isinstance(part, int):
            if not isinstance(cur, list) or part >= len(cur):
                return None
            cur = cur[part]
        else:
            if not isinstance(cur, dict):
                return None
            cur = cur.get(part)
    return cur


def _path_parts(path: str) -> Iterator:
    for seg in path.split("."):
        if not seg:
            continue
        while "[" in seg:
            head, _, rest = seg.partition("[")
            if head:
                yield head
            idx, _, seg = rest.partition("]")
            yield int(idx)
        if seg:
            yield seg
