"""Immutable segment loading + per-column DataSource.

Reference parity: pinot-segment-local
indexsegment/immutable/ImmutableSegmentLoader.java:57 (mmap load) and
pinot-segment-spi datasource/DataSource.java:41 (per-column access point:
getForwardIndex:58, getDictionary:71, per-index getters:77-132).

The DataSource decodes lazily and caches: `dict_ids()` (the int32 block the
TPU kernels consume) and `values()` (materialized raw values for the CPU
reference path / var-width columns).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.models.field_spec import DataType
from pinot_tpu.segment import fwd, index_types as it
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.indexes import BloomFilter, InvertedIndex, RangeIndex, SortedIndex
from pinot_tpu.segment.meta import ColumnMetadata, SegmentMetadata
from pinot_tpu.segment.store import SegmentDirectory


class DataSource:
    """Per-column access point (ref DataSource.java:41)."""

    def __init__(self, seg: "ImmutableSegment", meta: ColumnMetadata):
        self._seg = seg
        self.metadata = meta
        self._dictionary: Optional[Dictionary] = None
        self._dict_ids: Optional[np.ndarray] = None
        self._values: Optional[np.ndarray] = None
        self._mv_offsets: Optional[np.ndarray] = None
        self._inverted: Optional[InvertedIndex] = None
        self._range: Optional[RangeIndex] = None
        self._sorted: Optional[SortedIndex] = None
        self._bloom: Optional[BloomFilter] = None
        self._nullvec: Optional[Bitmap] = None
        self._json = None
        self._text = None

    # -- dictionary ---------------------------------------------------------
    @property
    def dictionary(self) -> Optional[Dictionary]:
        if self._dictionary is None and self.metadata.has_dictionary:
            buf = self._seg.dir.get_buffer(self.metadata.name, it.DICTIONARY)
            self._dictionary = Dictionary.from_bytes(
                self.metadata.data_type, buf, self.metadata.cardinality)
        return self._dictionary

    # -- forward index ------------------------------------------------------
    def dict_ids(self) -> np.ndarray:
        """Whole-column int32 dictIds (SV dict-encoded columns)."""
        if self._dict_ids is None:
            m = self.metadata
            if not m.has_dictionary:
                raise ValueError(f"column {m.name} is raw-encoded")
            buf = self._seg.dir.get_buffer(m.name, it.FORWARD)
            if m.single_value:
                self._dict_ids = fwd.read_sv_dict(buf, self._seg.num_docs,
                                                  m.bits_per_element)
            else:
                self._mv_offsets, self._dict_ids = fwd.read_mv_dict(
                    buf, self._seg.num_docs, m.bits_per_element)
        return self._dict_ids

    def mv_offsets(self) -> np.ndarray:
        if self._mv_offsets is None:
            self.dict_ids()
        return self._mv_offsets

    @property
    def clp_reader(self):
        """CLP log column sub-reader (ref DataSource CLP getter)."""
        if getattr(self, "_clp", None) is None and self._has(it.CLP):
            from pinot_tpu.utils import plugins
            clp = plugins.get_or_load("index", "clp_forward")
            self._clp = clp.CLPForwardIndexReader(clp.unpack_compressed(
                self._seg.dir.get_buffer(self.metadata.name, it.CLP)))
        return getattr(self, "_clp", None)

    def values(self) -> np.ndarray:
        """Whole-column materialized values (dictionary take or raw decode)."""
        if self._values is None:
            m = self.metadata
            if it.CLP in m.indexes:
                self._values = self.clp_reader.decode_all()
            elif m.has_dictionary:
                self._values = self.dictionary.get_values(self.dict_ids())
            else:
                buf = self._seg.dir.get_buffer(m.name, it.FORWARD)
                st = m.data_type.stored_type
                if st.is_fixed_width:
                    self._values = fwd.read_raw_fixed(
                        buf, self._seg.num_docs, m.data_type.np_dtype)
                else:
                    self._values = fwd.read_raw_var(
                        buf, self._seg.num_docs, st is DataType.BYTES)
        return self._values

    # -- auxiliary indexes (ref DataSource getters :77-132) ------------------
    @property
    def inverted_index(self) -> Optional[InvertedIndex]:
        if self._inverted is None and self._has(it.INVERTED):
            self._inverted = InvertedIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.INVERTED))
        return self._inverted

    @property
    def range_index(self) -> Optional[RangeIndex]:
        if self._range is None and self._has(it.RANGE):
            self._range = RangeIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.RANGE))
        return self._range

    @property
    def sorted_index(self) -> Optional[SortedIndex]:
        if self._sorted is None and self._has(it.SORTED):
            self._sorted = SortedIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.SORTED))
        return self._sorted

    @property
    def bloom_filter(self) -> Optional[BloomFilter]:
        if self._bloom is None and self._has(it.BLOOM):
            self._bloom = BloomFilter.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.BLOOM))
        return self._bloom

    @property
    def json_index(self):
        """Ref DataSource.getJsonIndex (datasource/DataSource.java:77-132)."""
        if self._json is None and self._has(it.JSON):
            from pinot_tpu.segment.json_index import JsonIndex
            self._json = JsonIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.JSON))
        return self._json

    @property
    def text_index(self):
        """Ref DataSource.getTextIndex."""
        if self._text is None and self._has(it.TEXT):
            from pinot_tpu.segment.text_index import TextIndex
            self._text = TextIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.TEXT))
        return self._text

    @property
    def vector_index(self):
        """Ref DataSource.getVectorIndex."""
        if getattr(self, "_vector", None) is None and self._has(it.VECTOR):
            from pinot_tpu.segment.vector_index import VectorIndex
            self._vector = VectorIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.VECTOR))
        return getattr(self, "_vector", None)

    @property
    def geo_index(self):
        """Ref DataSource.getH3Index."""
        if getattr(self, "_geo", None) is None and self._has(it.GEO):
            from pinot_tpu.segment.geo_index import GeoIndex
            self._geo = GeoIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.GEO))
        return getattr(self, "_geo", None)

    @property
    def map_index(self):
        """Ref DataSource.getMapIndex (segment/index/map/)."""
        if getattr(self, "_map", None) is None and self._has(it.MAP):
            from pinot_tpu.segment.map_index import MapIndex
            self._map = MapIndex.from_bytes(
                self._seg.dir.get_buffer(self.metadata.name, it.MAP))
        return getattr(self, "_map", None)

    @property
    def null_value_vector(self) -> Optional[Bitmap]:
        if self._nullvec is None and self._has(it.NULLVECTOR):
            self._nullvec = Bitmap.from_bytes(
                self._seg.num_docs,
                self._seg.dir.get_buffer(self.metadata.name, it.NULLVECTOR))
        return self._nullvec

    def _has(self, index_type: str) -> bool:
        return self._seg.dir.has_index(self.metadata.name, index_type)


class ImmutableSegment:
    """Loaded immutable segment (ref IndexSegment/ImmutableSegmentImpl)."""

    def __init__(self, seg_dir: str):
        self.dir = SegmentDirectory(seg_dir)
        self.metadata: SegmentMetadata = self.dir.metadata
        self._sources: Dict[str, DataSource] = {}
        self._star_tree = None

    @property
    def name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.num_docs

    @property
    def column_names(self) -> List[str]:
        return list(self.metadata.columns.keys())

    def data_source(self, column: str) -> DataSource:
        ds = self._sources.get(column)
        if ds is None:
            cmeta = self.metadata.columns.get(column)
            if cmeta is None:
                raise KeyError(f"column {column!r} not in segment {self.name}")
            ds = DataSource(self, cmeta)
            self._sources[column] = ds
        return ds

    def has_column(self, column: str) -> bool:
        return column in self.metadata.columns

    @property
    def star_tree(self):
        if self._star_tree is None and self.metadata.star_tree:
            from pinot_tpu.segment.startree import StarTreeReader
            self._star_tree = StarTreeReader(self)
        return self._star_tree

    def destroy(self) -> None:
        self._sources.clear()


def load_segment(seg_dir: str) -> ImmutableSegment:
    """Ref ImmutableSegmentLoader.load(indexDir, readMode) — mmap read mode."""
    return ImmutableSegment(seg_dir)
