"""Map index: per-key dense sub-columns for map-typed columns.

Reference parity: pinot-segment-local segment/index/map/ — MAP columns
(string key -> scalar value per row) store each observed key as its own
dense sub-column so `map_value(col, 'key')` reads column-speed instead
of parsing per row (the reference's dense-key mode; rare keys stay in
the fallback path).

Clean-room layout: keys observed at build time each get a value array of
length num_docs (None where absent) serialized as a JSON-lines-free
binary; lookups are O(1) per key.
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional

import numpy as np

_U32 = struct.Struct("<I")


class MapIndex:
    def __init__(self, columns: Dict[str, np.ndarray], num_docs: int):
        #: key -> dense [num_docs] object array (None = absent)
        self.columns = columns
        self.num_docs = num_docs

    @classmethod
    def build(cls, values, num_docs: int) -> "MapIndex":
        """values: per-doc dicts (or JSON object strings)."""
        cols: Dict[str, np.ndarray] = {}
        for doc_id, raw in enumerate(values):
            m = raw
            if isinstance(raw, (str, bytes)):
                try:
                    m = json.loads(raw)
                except ValueError:
                    m = None
            if not isinstance(m, dict):
                continue
            for k, v in m.items():
                col = cols.get(k)
                if col is None:
                    col = cols[k] = np.full(num_docs, None, object)
                col[doc_id] = v
        return cls(cols, num_docs)

    # ------------------------------------------------------------------
    def keys(self) -> List[str]:
        return sorted(self.columns)

    def value_column(self, key: str) -> Optional[np.ndarray]:
        """Dense per-doc values for a key (None where the row's map lacks
        it); None when the key was never observed."""
        return self.columns.get(key)

    def docs_with_key(self, key: str) -> np.ndarray:
        col = self.columns.get(key)
        if col is None:
            return np.empty(0, np.int32)
        return np.flatnonzero(col != None).astype(np.int32)  # noqa: E711

    def docs_with_value(self, key: str, value: Any) -> np.ndarray:
        col = self.columns.get(key)
        if col is None:
            return np.empty(0, np.int32)
        return np.flatnonzero(col == value).astype(np.int32)

    # ------------------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = [_U32.pack(self.num_docs), _U32.pack(len(self.columns))]
        for k in self.keys():
            col = self.columns[k]
            kb = k.encode()
            payload = json.dumps(col.tolist()).encode()
            out += [_U32.pack(len(kb)), kb,
                    _U32.pack(len(payload)), payload]
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf) -> "MapIndex":
        buf = bytes(buf)
        pos = 0

        def u32():
            nonlocal pos
            v = _U32.unpack_from(buf, pos)[0]
            pos += 4
            return v

        num_docs = u32()
        cols: Dict[str, np.ndarray] = {}
        for _ in range(u32()):
            ln = u32()
            k = buf[pos:pos + ln].decode()
            pos += ln
            pn = u32()
            vals = json.loads(buf[pos:pos + pn])
            pos += pn
            cols[k] = np.array(vals, object)
        return cls(cols, num_docs)
