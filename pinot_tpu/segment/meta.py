"""Segment + column metadata.

Reference parity: pinot-segment-spi ColumnMetadata / SegmentMetadata and the
`metadata.properties` file written by SegmentIndexCreationDriverImpl (here a
single metadata.json per segment).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.models.field_spec import DataType, FieldType, _json_safe


@dataclass
class ColumnMetadata:
    name: str
    data_type: DataType
    field_type: FieldType = FieldType.DIMENSION
    single_value: bool = True
    has_dictionary: bool = True
    cardinality: int = 0
    bits_per_element: int = 0
    min_value: Any = None
    max_value: Any = None
    is_sorted: bool = False
    total_entries: int = 0       # == num_docs for SV; total flattened for MV
    max_num_multi_values: int = 0
    has_nulls: bool = False
    partition_function: Optional[str] = None
    num_partitions: int = 0
    partitions: List[int] = field(default_factory=list)
    indexes: List[str] = field(default_factory=list)  # index types present

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "dataType": self.data_type.value,
            "fieldType": self.field_type.value,
            "singleValue": self.single_value,
            "hasDictionary": self.has_dictionary,
            "cardinality": self.cardinality,
            "bitsPerElement": self.bits_per_element,
            "minValue": _json_safe(self.min_value),
            "maxValue": _json_safe(self.max_value),
            "isSorted": self.is_sorted,
            "totalEntries": self.total_entries,
            "maxNumMultiValues": self.max_num_multi_values,
            "hasNulls": self.has_nulls,
            "partitionFunction": self.partition_function,
            "numPartitions": self.num_partitions,
            "partitions": self.partitions,
            "indexes": self.indexes,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ColumnMetadata":
        dt = DataType(d["dataType"])
        mn, mx = d.get("minValue"), d.get("maxValue")
        if dt.stored_type is DataType.BYTES:
            mn = bytes.fromhex(mn) if isinstance(mn, str) else mn
            mx = bytes.fromhex(mx) if isinstance(mx, str) else mx
        return cls(
            name=d["name"], data_type=dt, field_type=FieldType(d["fieldType"]),
            single_value=d["singleValue"], has_dictionary=d["hasDictionary"],
            cardinality=d["cardinality"], bits_per_element=d["bitsPerElement"],
            min_value=mn, max_value=mx, is_sorted=d["isSorted"],
            total_entries=d["totalEntries"],
            max_num_multi_values=d.get("maxNumMultiValues", 0),
            has_nulls=d.get("hasNulls", False),
            partition_function=d.get("partitionFunction"),
            num_partitions=d.get("numPartitions", 0),
            partitions=d.get("partitions", []),
            indexes=d.get("indexes", []),
        )


@dataclass
class SegmentMetadata:
    segment_name: str
    table_name: str
    num_docs: int
    columns: Dict[str, ColumnMetadata] = field(default_factory=dict)
    time_column: Optional[str] = None
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    creation_time_ms: int = 0
    crc: int = 0
    format_version: int = 1
    star_tree: Optional[dict] = None  # star-tree metadata when present

    def to_dict(self) -> dict:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "totalDocs": self.num_docs,
            "timeColumn": self.time_column,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "creationTimeMs": self.creation_time_ms,
            "crc": self.crc,
            "formatVersion": self.format_version,
            "starTree": self.star_tree,
            "columns": {k: v.to_dict() for k, v in self.columns.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SegmentMetadata":
        return cls(
            segment_name=d["segmentName"], table_name=d["tableName"],
            num_docs=d["totalDocs"], time_column=d.get("timeColumn"),
            start_time=d.get("startTime"), end_time=d.get("endTime"),
            creation_time_ms=d.get("creationTimeMs", 0), crc=d.get("crc", 0),
            format_version=d.get("formatVersion", 1),
            star_tree=d.get("starTree"),
            columns={k: ColumnMetadata.from_dict(v)
                     for k, v in d.get("columns", {}).items()},
        )
