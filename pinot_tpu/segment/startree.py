"""Star-tree index: pre-aggregated cube with star (*) wildcards.

Reference parity: pinot-segment-local startree/ —
OffHeapStarTree.java:38 (node array format), v2/builder/
{OnHeap,OffHeap}SingleTreeBuilder + MultipleTreesBuilder (invoked from
SegmentIndexCreationDriverImpl.java:396), StarTreeV2Metadata, and
core/startree/ execution (StarTreeUtils fit-check,
StarTreeFilterOperator.java:90 traversal, StarTreeAggregationExecutor /
StarTreeGroupByExecutor reading pre-agg metric columns).

Build: the base table is the full group-by over the split-order dims
(value-sorted dictIds); each internal node splits on the next dim, and a
star child re-aggregates with that dim wildcarded (-1). Records are laid
out in DFS order so every node covers a contiguous [start, end) range of
the pre-agg table — which is what lets the executor aggregate a node's
residual range as a dense numpy (later: device) slice.

Storage: nodes as one int32 [N, 6] array in the `startree_index` buffer;
pre-agg columns (dim codes int32, metric columns float64) packed in
`startree_data`; shapes/pairs in metadata.star_tree.
"""
from __future__ import annotations

import json
import struct
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.models import Schema, StarTreeIndexConfig, TableConfig
from pinot_tpu.segment import index_types as it
from pinot_tpu.segment.store import index_key

STAR = -1  # wildcard dim value (ref StarTreeNode.ALL)

# node record: dim_id, dim_value, start_doc, end_doc, child_start, num_children
_NODE_FIELDS = 6


class DimFilter:
    """One dim's matching dictId set in compressed form: a dense
    inclusive ``[lo, hi]`` interval (range predicates are never
    materialized into id arrays) or an explicit sorted-unique id array.
    Intersections stay in interval space whenever one side is an
    interval, so arbitrarily wide BETWEEN / comparison predicates cost
    O(1) instead of O(hi-lo) arange + intersect1d."""

    __slots__ = ("lo", "hi", "ids")

    def __init__(self, lo: Optional[int] = None, hi: Optional[int] = None,
                 ids: Optional[np.ndarray] = None):
        self.lo = lo
        self.hi = hi
        self.ids = ids

    @classmethod
    def from_range(cls, lo: int, hi: int) -> "DimFilter":
        return cls(lo=int(lo), hi=int(hi))

    @classmethod
    def from_ids(cls, ids) -> "DimFilter":
        return cls(ids=np.unique(np.asarray(ids, dtype=np.int64)))

    def is_empty(self) -> bool:
        if self.ids is not None:
            return len(self.ids) == 0
        return self.hi < self.lo

    def intersect(self, other: "DimFilter") -> "DimFilter":
        if self.ids is None and other.ids is None:
            return DimFilter(lo=max(self.lo, other.lo),
                             hi=min(self.hi, other.hi))
        if self.ids is None:
            return other.intersect(self)
        if other.ids is None:  # clip the id list to the interval
            ids = self.ids
            return DimFilter(ids=ids[(ids >= other.lo) & (ids <= other.hi)])
        return DimFilter(ids=np.intersect1d(self.ids, other.ids))

    def contains(self, v: int) -> bool:
        if self.ids is None:
            return self.lo <= v <= self.hi
        i = int(np.searchsorted(self.ids, v))
        return i < len(self.ids) and int(self.ids[i]) == v

    def mask(self, codes: np.ndarray) -> np.ndarray:
        """Boolean membership mask over a code array (leaf residual)."""
        if self.ids is None:
            return (codes >= self.lo) & (codes <= self.hi)
        return np.isin(codes, self.ids)

_SUPPORTED_FUNCS = {"SUM", "COUNT", "MIN", "MAX"}


def parse_pair(pair: str) -> Tuple[str, str]:
    """'SUM__revenue' -> ('sum', 'revenue'); 'COUNT__*' -> ('count', '*')."""
    func, col = pair.split("__", 1)
    return func.lower(), col


@dataclass
class StarTreeMeta:
    dims: List[str]
    pairs: List[str]                      # canonical "FUNC__col" strings
    max_leaf_records: int
    num_nodes: int
    num_records: int
    skip_star_dims: List[str]

    def to_dict(self) -> dict:
        return {"dims": self.dims, "pairs": self.pairs,
                "maxLeafRecords": self.max_leaf_records,
                "numNodes": self.num_nodes, "numRecords": self.num_records,
                "skipStarDims": self.skip_star_dims}

    @classmethod
    def from_dict(cls, d: dict) -> "StarTreeMeta":
        return cls(d["dims"], d["pairs"], d["maxLeafRecords"], d["numNodes"],
                   d["numRecords"], d.get("skipStarDims", []))


# ---------------------------------------------------------------------------
# Build
# ---------------------------------------------------------------------------

class _TreeBuilder:
    def __init__(self, num_dims: int, max_leaf_records: int,
                 skip_star: Sequence[bool], pairs: List[Tuple[str, str]]):
        self.num_dims = num_dims
        self.max_leaf = max_leaf_records
        self.skip_star = list(skip_star)
        self.pairs = pairs
        self.nodes: List[List[int]] = []
        self.rec_dims: List[List[np.ndarray]] = []   # chunks per emit
        self.rec_metrics: List[Dict[Tuple[str, str], np.ndarray]] = []
        self.num_records = 0

    def build(self, dim_codes, metrics) -> int:
        root = self._new_node(-1, STAR)
        self._build(root, dim_codes, metrics, 0)
        return root

    def _new_node(self, dim_id: int, dim_value: int) -> int:
        self.nodes.append([dim_id, dim_value, 0, 0, -1, 0])
        return len(self.nodes) - 1

    def _emit(self, node: int, dim_codes, metrics) -> None:
        start = self.num_records
        n = len(dim_codes[0]) if dim_codes else 0
        self.rec_dims.append(dim_codes)
        self.rec_metrics.append(metrics)
        self.num_records += n
        self.nodes[node][2] = start
        self.nodes[node][3] = self.num_records

    def _build(self, node: int, dim_codes, metrics, dim_idx: int) -> None:
        n = len(dim_codes[0]) if dim_codes else 0
        if dim_idx >= self.num_dims or n <= self.max_leaf:
            self._emit(node, dim_codes, metrics)
            return
        # order rows by this dim so each child's rows are contiguous
        order = np.argsort(dim_codes[dim_idx], kind="stable")
        dim_codes = [c[order] for c in dim_codes]
        metrics = {p: v[order] for p, v in metrics.items()}

        self.nodes[node][2] = self.num_records
        children: List[Tuple[int, Any, Any]] = []
        vals, starts = np.unique(dim_codes[dim_idx], return_index=True)
        bounds = list(starts) + [n]
        for i, v in enumerate(vals):
            sl = slice(bounds[i], bounds[i + 1])
            children.append((int(v), [c[sl] for c in dim_codes],
                             {p: m[sl] for p, m in metrics.items()}))
        # star child: wildcard this dim, re-aggregate over remaining dims
        if not self.skip_star[dim_idx]:
            star_codes = [c.copy() for c in dim_codes]
            star_codes[dim_idx] = np.full(n, STAR, dtype=np.int32)
            s_codes, s_metrics = _aggregate_pairs(star_codes, metrics,
                                                  self.pairs)
            children.append((STAR, s_codes, s_metrics))

        child_ids = []
        for v, codes, mets in children:
            child = self._new_node(dim_idx, v)
            child_ids.append(child)
        # children must be contiguous in the node array (ref child_start)
        self.nodes[node][4] = child_ids[0]
        self.nodes[node][5] = len(child_ids)
        for child, (v, codes, mets) in zip(child_ids, children):
            self._build_child(child, codes, mets, dim_idx + 1)
        self.nodes[node][3] = self.num_records

    def _build_child(self, node: int, codes, mets, next_dim: int) -> None:
        # recursion with children created eagerly would interleave node ids;
        # child subtrees are appended after all siblings exist (done above)
        self._build(node, codes, mets, next_dim)

    def records(self):
        if not self.rec_dims:
            return ([np.empty(0, np.int32)] * self.num_dims,
                    {p: np.empty(0) for p in self.pairs})
        dims = [np.concatenate([chunk[i] for chunk in self.rec_dims])
                .astype(np.int32) for i in range(self.num_dims)]
        mets = {p: np.concatenate([m[p] for m in self.rec_metrics])
                for p in self.pairs}
        return dims, mets


def _aggregate_pairs(dim_codes: List[np.ndarray],
                     pair_metrics: Dict[Tuple[str, str], np.ndarray],
                     pairs: List[Tuple[str, str]]):
    if len(dim_codes[0]) == 0:
        return [c[:0] for c in dim_codes], {p: np.empty(0) for p in pairs}
    stacked = np.stack(dim_codes, axis=1)
    uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
    n_groups = len(uniq)
    out_dims = [uniq[:, i].astype(np.int32) for i in range(len(dim_codes))]
    out: Dict[Tuple[str, str], np.ndarray] = {}
    for func, col in pairs:
        vals = pair_metrics[(func, col)]
        if func in ("count", "sum"):
            o = np.bincount(inverse, weights=vals, minlength=n_groups)
        elif func == "min":
            o = np.full(n_groups, np.inf)
            np.minimum.at(o, inverse, vals)
        else:
            o = np.full(n_groups, -np.inf)
            np.maximum.at(o, inverse, vals)
        out[(func, col)] = o.astype(np.float64)
    return out_dims, out


def build_star_trees(table_config: TableConfig, schema: Schema,
                     columns: Dict[str, Any], metadata, buffers: Dict[str, bytes]
                     ) -> None:
    """Creator hook (ref SegmentIndexCreationDriverImpl.java:396)."""
    trees = []
    for ti, cfg in enumerate(table_config.indexing.star_tree_configs):
        tree_meta = _build_one(ti, cfg, columns, metadata, buffers)
        trees.append(tree_meta.to_dict())
    if trees:
        metadata.star_tree = {"trees": trees}


def _build_one(ti: int, cfg: StarTreeIndexConfig, columns, metadata,
               buffers) -> StarTreeMeta:
    dims = cfg.dimensions_split_order
    pairs = [parse_pair(p) for p in cfg.function_column_pairs]
    if ("count", "*") not in pairs:
        pairs.append(("count", "*"))  # always materialized (ref default)
    for func, col in pairs:
        if func not in ("sum", "count", "min", "max"):
            raise ValueError(f"star-tree pair {func}__{col} not supported")

    num_docs = metadata.num_docs
    # dictIds: value-sorted, reproduced with the same np.unique the
    # dictionary creator uses
    dim_codes = []
    for d in dims:
        vals = np.asarray(columns[d])
        uniq, inverse = np.unique(vals, return_inverse=True)
        dim_codes.append(inverse.astype(np.int32))
    pair_metrics: Dict[Tuple[str, str], np.ndarray] = {}
    for func, col in pairs:
        if col == "*":
            pair_metrics[(func, col)] = np.ones(num_docs, dtype=np.float64)
        else:
            pair_metrics[(func, col)] = np.asarray(
                columns[col], dtype=np.float64)

    base_dims, base_metrics = _aggregate_pairs(dim_codes, pair_metrics, pairs)
    skip = [d in cfg.skip_star_node_creation for d in dims]
    builder = _TreeBuilder(len(dims), cfg.max_leaf_records, skip, pairs)
    builder.build(base_dims, base_metrics)
    rec_dims, rec_metrics = builder.records()

    nodes = np.asarray(builder.nodes, dtype=np.int32).reshape(-1, _NODE_FIELDS)
    buffers[index_key(f"__startree_{ti}", it.STARTREE)] = nodes.tobytes()
    blob = bytearray()
    for arr in rec_dims:
        blob += arr.astype(np.int32).tobytes()
    for func, col in pairs:
        blob += rec_metrics[(func, col)].astype(np.float64).tobytes()
    buffers[index_key(f"__startree_{ti}", it.STARTREE_DATA)] = bytes(blob)
    return StarTreeMeta(
        dims=list(dims), pairs=[f"{f.upper()}__{c}" for f, c in pairs],
        max_leaf_records=cfg.max_leaf_records, num_nodes=len(nodes),
        num_records=builder.num_records,
        skip_star_dims=list(cfg.skip_star_node_creation))


# ---------------------------------------------------------------------------
# Read + traverse
# ---------------------------------------------------------------------------

class StarTreeV2:
    def __init__(self, seg, ti: int, meta: StarTreeMeta):
        self.seg = seg
        self.meta = meta
        nodes_buf = seg.dir.get_buffer(f"__startree_{ti}", it.STARTREE)
        self.nodes = np.frombuffer(bytes(nodes_buf), dtype=np.int32) \
            .reshape(-1, _NODE_FIELDS)
        data = bytes(seg.dir.get_buffer(f"__startree_{ti}", it.STARTREE_DATA))
        n = meta.num_records
        off = 0
        self.dim_codes: Dict[str, np.ndarray] = {}
        for d in meta.dims:
            self.dim_codes[d] = np.frombuffer(data, np.int32, n, off)
            off += 4 * n
        self.metrics: Dict[Tuple[str, str], np.ndarray] = {}
        for p in meta.pairs:
            func, col = parse_pair(p)
            self.metrics[(func, col)] = np.frombuffer(data, np.float64, n, off)
            off += 8 * n
        self._pair_bounds: Dict[Tuple[str, str], Tuple[float, float, bool]] = {}

    def pair_bounds(self, pair: Tuple[str, str]) -> Tuple[float, float, bool]:
        """(min, max, integral) over one pre-agg metric column, cached —
        the device staging admission data (ops/startree_device.py picks
        an exact int-plane slot vs a float32 slot from these)."""
        cached = self._pair_bounds.get(pair)
        if cached is None:
            v = self.metrics[pair]
            if len(v) == 0:
                cached = (0.0, 0.0, True)
            else:
                cached = (float(v.min()), float(v.max()),
                          bool(np.all(v == np.floor(v))))
            self._pair_bounds[pair] = cached
        return cached

    def traverse(self, dim_id_sets: Dict[str, Optional["DimFilter"]],
                 group_dims: set) -> np.ndarray:
        """Record mask for the query (ref StarTreeFilterOperator.java:90).

        dim_id_sets: dim -> matching DimFilter (None = no predicate;
        plain dictId arrays are accepted and wrapped).
        group_dims: dims that must keep real values (no star substitution).
        Returns selected record indices into the pre-agg table.
        """
        filters = {d: f if (f is None or isinstance(f, DimFilter))
                   else DimFilter.from_ids(f)
                   for d, f in dim_id_sets.items()}
        selected: List[np.ndarray] = []

        def visit(node: int):
            dim_id, dim_value, start, end, child_start, n_children = \
                self.nodes[node]
            if n_children == 0:
                # leaf: records keep real values for dims never split on
                # this path, so re-applying every predicate is both
                # necessary (residual dims) and harmless (consumed dims
                # already satisfy it); star-substituted dims are never
                # predicated because predicated dims never take the star
                # child below
                idx = np.arange(start, end)
                keep = np.ones(len(idx), dtype=bool)
                for d, f in filters.items():
                    if f is not None:
                        keep &= f.mask(self.dim_codes[d][idx])
                selected.append(idx[keep])
                return
            child_dim = self.nodes[child_start][0]
            dname = self.meta.dims[child_dim]
            f = filters.get(dname)
            children = range(child_start, child_start + n_children)
            if f is None and dname not in group_dims:
                # no predicate, not grouped: take the star child if present
                for c in children:
                    if self.nodes[c][1] == STAR:
                        visit(c)
                        return
                for c in children:  # star skipped: take all real children
                    visit(c)
                return
            for c in children:
                v = self.nodes[c][1]
                if v == STAR:
                    continue
                if f is None or f.contains(int(v)):
                    visit(c)
        visit(0)
        if not selected:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(selected)


class StarTreeReader:
    def __init__(self, seg):
        self.seg = seg
        self.trees: List[StarTreeV2] = []
        st = seg.metadata.star_tree or {}
        for ti, tm in enumerate(st.get("trees", [])):
            self.trees.append(StarTreeV2(seg, ti, StarTreeMeta.from_dict(tm)))
