"""On-disk segment layout: one packed column file + index map + metadata.

Reference parity: the v3 single-file layout of
segment/store/SingleFileIndexDirectory.java:69,218 — all index buffers
concatenated into `columns.psf` with an `index_map` of offsets, plus
`metadata.properties` (here metadata.json) and `creation.meta`.

Buffers are 64-byte aligned so mmap'd slices can be viewed as any numpy dtype
and handed to dlpack/device-put without copies.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Tuple

import numpy as np

from pinot_tpu.segment.meta import SegmentMetadata

COLUMNS_FILE = "columns.psf"
INDEX_MAP_FILE = "index_map.json"
METADATA_FILE = "metadata.json"
CREATION_FILE = "creation.meta"

_ALIGN = 64


def index_key(column: str, index_type: str) -> str:
    return f"{column}.{index_type}"


def write_segment(out_dir: str, metadata: SegmentMetadata,
                  buffers: Dict[str, bytes]) -> None:
    """Write the packed segment directory."""
    os.makedirs(out_dir, exist_ok=True)
    index_map: Dict[str, Tuple[int, int]] = {}
    crc = 0
    with open(os.path.join(out_dir, COLUMNS_FILE), "wb") as f:
        pos = 0
        for key, buf in buffers.items():
            pad = (-pos) % _ALIGN
            if pad:
                f.write(b"\0" * pad)
                pos += pad
            index_map[key] = (pos, len(buf))
            f.write(buf)
            crc = zlib.crc32(buf, crc)
            pos += len(buf)
    metadata.crc = crc
    with open(os.path.join(out_dir, INDEX_MAP_FILE), "w") as f:
        json.dump(index_map, f)
    with open(os.path.join(out_dir, METADATA_FILE), "w") as f:
        json.dump(metadata.to_dict(), f, indent=1)
    with open(os.path.join(out_dir, CREATION_FILE), "w") as f:
        json.dump({"creationTimeMs": metadata.creation_time_ms, "crc": crc}, f)


class SegmentDirectory:
    """Read view over a packed segment dir (mmap'd)."""

    def __init__(self, seg_dir: str):
        self.path = seg_dir
        with open(os.path.join(seg_dir, METADATA_FILE)) as f:
            self.metadata = SegmentMetadata.from_dict(json.load(f))
        with open(os.path.join(seg_dir, INDEX_MAP_FILE)) as f:
            self._index_map = {k: tuple(v) for k, v in json.load(f).items()}
        psf = os.path.join(seg_dir, COLUMNS_FILE)
        size = os.path.getsize(psf)
        self._buf = (np.memmap(psf, dtype=np.uint8, mode="r") if size
                     else np.empty(0, dtype=np.uint8))

    def has_index(self, column: str, index_type: str) -> bool:
        return index_key(column, index_type) in self._index_map

    def get_buffer(self, column: str, index_type: str) -> np.ndarray:
        off, size = self._index_map[index_key(column, index_type)]
        return self._buf[off:off + size]

    def keys(self):
        return self._index_map.keys()
