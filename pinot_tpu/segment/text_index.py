"""Text index: token-inverted postings for text_match filters.

Reference parity: pinot-segment-local
segment/index/readers/text/NativeTextIndexReader.java (and the Lucene
variant, LuceneTextIndexReader.java) — free-text columns tokenize into an
inverted token -> doc-id map; text_match queries support terms, AND/OR/NOT
(Lucene-operator spellings), prefix wildcards ('pre*'), and quoted phrases
(phrase candidates AND-match then verify against raw values).

Clean-room: standard-analyzer-style tokenization (lowercase, split on
non-alphanumerics), numpy doc-id postings, length-prefixed binary serde —
no Lucene artifacts.
"""
from __future__ import annotations

import re
import struct
from typing import Dict, List, Optional

import numpy as np

_U32 = struct.Struct("<I")
_TOKEN_RX = re.compile(r"[0-9a-z_]+")


def tokenize(text: str) -> List[str]:
    return _TOKEN_RX.findall(str(text).lower())


class TextIndex:
    def __init__(self, postings: Dict[str, np.ndarray], num_docs: int):
        #: token -> sorted unique doc ids
        self.postings = postings
        self.num_docs = num_docs
        self._sorted_tokens: Optional[List[str]] = None

    @classmethod
    def build(cls, values, num_docs: int) -> "TextIndex":
        tmp: Dict[str, set] = {}
        for doc_id, v in enumerate(values):
            if v is None:
                continue
            for tok in tokenize(v):
                tmp.setdefault(tok, set()).add(doc_id)
        postings = {t: np.asarray(sorted(ids), np.int32)
                    for t, ids in tmp.items()}
        return cls(postings, num_docs)

    # ------------------------------------------------------------------
    def _term(self, token: str) -> np.ndarray:
        return self.postings.get(token.lower(), np.empty(0, np.int32))

    def _sorted_vocab(self) -> np.ndarray:
        """Sorted token vocabulary (built once; the FST-for-prefixes
        analog — see segment/fst_index.py)."""
        if self._sorted_tokens is None:
            self._sorted_tokens = np.array(sorted(self.postings), object)
        return self._sorted_tokens

    def _prefix(self, prefix: str) -> np.ndarray:
        """O(log V) prefix range over the sorted vocabulary instead of a
        linear scan per 'pre*' query (VERDICT r4 weak #8)."""
        from pinot_tpu.segment.fst_index import prefix_range
        prefix = prefix.lower()
        vocab = self._sorted_vocab()
        lo, hi = prefix_range(vocab, prefix)
        if lo >= hi:
            return np.empty(0, np.int32)
        return np.unique(np.concatenate(
            [self.postings[t] for t in vocab[lo:hi]]))

    def matching_docs(self, query: str, raw_values=None) -> np.ndarray:
        """Evaluate a text_match query -> sorted doc ids.

        Grammar: term | 'pre*' | "a phrase" | expr AND expr | expr OR expr
        | NOT expr | (expr). Bare adjacent terms OR together (Lucene's
        default operator). Phrases need raw_values to verify adjacency.
        """
        tokens = _lex(query)
        pos = 0

        def peek():
            return tokens[pos] if pos < len(tokens) else None

        def take():
            nonlocal pos
            t = tokens[pos]
            pos += 1
            return t

        def parse_or() -> np.ndarray:
            # Lucene boolean semantics: clauses at one level combine as
            # SHOULD (implicit/explicit OR) except NOT-clauses, which are
            # MUST_NOT — subtracted from the union of the positive clauses
            # ('apple NOT pie' = apple minus pie, never apple OR not-pie).
            chains = []
            while True:
                t = peek()
                if t is None or t == ("op", ")"):
                    break
                if t == ("op", "OR"):
                    take()
                    continue
                chains.append(parse_and())
            positives = [p for p, _ in chains if p is not None]
            prohibited = [n for p, n in chains if p is None and n is not None]
            if positives:
                out = positives[0]
                for s in positives[1:]:
                    out = np.union1d(out, s)
            elif prohibited:  # pure-negative query: complement
                out = np.arange(self.num_docs, dtype=np.int32)
            else:
                out = np.empty(0, np.int32)
            for s in prohibited:
                out = np.setdiff1d(out, s)
            return out

        def parse_and():
            """One AND-chain -> (positive_result|None, prohibited|None)."""
            positive = None
            has_positive = False
            prohibited = None
            while True:
                neg = False
                while peek() == ("op", "NOT"):
                    take()
                    neg = not neg
                opnd = parse_atom()
                if neg:
                    prohibited = opnd if prohibited is None \
                        else np.union1d(prohibited, opnd)
                else:
                    positive = opnd if not has_positive \
                        else np.intersect1d(positive, opnd)
                    has_positive = True
                if peek() == ("op", "AND"):
                    take()
                    continue
                break
            if has_positive:
                if prohibited is not None:
                    positive = np.setdiff1d(positive, prohibited)
                return positive, None
            return None, prohibited

        def parse_atom() -> np.ndarray:
            t = peek()
            if t is None:  # trailing operator ('a AND'): nothing matches
                return np.empty(0, np.int32)
            if t == ("op", "("):
                take()
                inner = parse_or()
                if peek() == ("op", ")"):
                    take()
                return inner
            kind, text = take()
            if kind == "phrase":
                return self._phrase(text, raw_values)
            if text.endswith("*"):
                return self._prefix(text[:-1])
            return self._term(text)

        if not tokens:
            return np.empty(0, np.int32)
        return parse_or()

    def _phrase(self, phrase: str, raw_values) -> np.ndarray:
        terms = tokenize(phrase)
        if not terms:
            return np.empty(0, np.int32)
        cand = self._term(terms[0])
        for t in terms[1:]:
            cand = np.intersect1d(cand, self._term(t))
        if raw_values is None or len(cand) == 0:
            return cand  # postings-only approximation without raw values
        # verify token adjacency against the raw text
        want = terms
        keep = []
        for d in cand:
            toks = tokenize(raw_values[int(d)])
            for i in range(len(toks) - len(want) + 1):
                if toks[i:i + len(want)] == want:
                    keep.append(d)
                    break
        return np.asarray(keep, np.int32)

    # ------------------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = [_U32.pack(self.num_docs), _U32.pack(len(self.postings))]
        for t, ids in self.postings.items():
            tb = t.encode()
            out += [_U32.pack(len(tb)), tb, _U32.pack(len(ids)),
                    ids.astype("<i4").tobytes()]
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf) -> "TextIndex":
        buf = bytes(buf)
        pos = 0

        def u32():
            nonlocal pos
            v = _U32.unpack_from(buf, pos)[0]
            pos += 4
            return v

        num_docs = u32()
        postings: Dict[str, np.ndarray] = {}
        for _ in range(u32()):
            ln = u32()
            t = buf[pos:pos + ln].decode()
            pos += ln
            n = u32()
            postings[t] = np.frombuffer(buf, "<i4", n, pos).copy()
            pos += 4 * n
        return cls(postings, num_docs)


def _lex(query: str):
    """text_match query -> [(kind, text)] tokens."""
    out = []
    i = 0
    n = len(query)
    while i < n:
        c = query[i]
        if c.isspace():
            i += 1
            continue
        if c == '"':
            j = query.find('"', i + 1)
            if j < 0:
                j = n
            out.append(("phrase", query[i + 1:j]))
            i = j + 1
            continue
        if c in "()":
            out.append(("op", c))
            i += 1
            continue
        j = i
        while j < n and not query[j].isspace() and query[j] not in '()"':
            j += 1
        word = query[i:j]
        if word in ("AND", "OR", "NOT", "&&", "||"):
            out.append(("op", {"&&": "AND", "||": "OR"}.get(word, word)))
        else:
            out.append(("term", word))
        i = j
    return out
