"""Upsert & dedup metadata managers.

Reference parity: pinot-segment-local upsert/
ConcurrentMapPartitionUpsertMetadataManager.java:48 — a per-partition
primary-key map to (segment, docId, comparisonValue); per-segment
validDocIds bitmaps that queries AND into their filter mask; later
(or equal, last-wins) comparison values replace earlier rows. Partial
upsert merge strategies live in merger functions (ref upsert/merger/).
Dedup analog: ConcurrentMapPartitionDedupMetadataManager (dedup/).

Query integration: segments gain a `valid_doc_ids` attribute; the host
executor ANDs it into the filter mask, and the device engine excludes
upsert segments (they are realtime-sized; SURVEY.md §2.3).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.segment.bitmap import Bitmap


@dataclass
class _RecordLocation:
    segment: Any          # object with .name and .valid_doc_ids
    doc_id: int
    comparison_value: Any


def _pk_of(record_or_row, pk_columns: Sequence[str]) -> tuple:
    return tuple(record_or_row[c] for c in pk_columns)


class PartitionUpsertMetadataManager:
    """One stream partition's upsert state (ref :48)."""

    def __init__(self, pk_columns: Sequence[str], comparison_column: str,
                 partial_merger: Optional[Callable[[dict, dict], dict]] = None):
        self.pk_columns = list(pk_columns)
        self.comparison_column = comparison_column
        self.partial_merger = partial_merger
        self._map: Dict[tuple, _RecordLocation] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def add_segment(self, segment) -> None:
        """Register an (im)mutable segment's rows; later comparison values
        win, losers are invalidated in their owning segment's bitmap."""
        n = segment.num_docs
        valid = Bitmap.all_set(n)
        segment.valid_doc_ids = valid
        pk_cols = [np.asarray(segment.data_source(c).values())
                   for c in self.pk_columns]
        cmp_col = np.asarray(segment.data_source(self.comparison_column).values())
        with self._lock:
            for doc_id in range(n):
                pk = tuple(_py(col[doc_id]) for col in pk_cols)
                self._upsert_locked(segment, doc_id, _py(cmp_col[doc_id]), pk,
                                    valid)

    def add_row(self, segment, doc_id: int, record: Dict[str, Any]) -> None:
        """Realtime path: account one newly indexed row (ref addRecord)."""
        if getattr(segment, "valid_doc_ids", None) is None:
            segment.valid_doc_ids = Bitmap(0)
        valid = segment.valid_doc_ids
        if valid.num_docs <= doc_id:
            valid.resize(doc_id + 1)
        valid.set(doc_id)
        pk = _pk_of(record, self.pk_columns)
        cmp_value = record[self.comparison_column]
        with self._lock:
            self._upsert_locked(segment, doc_id, cmp_value, pk, valid)

    def _upsert_locked(self, segment, doc_id, cmp_value, pk, valid) -> None:
        cur = self._map.get(pk)
        if cur is not None:
            if _cmp_ge(cmp_value, cur.comparison_value):
                cur.segment.valid_doc_ids.clear(cur.doc_id)
                self._map[pk] = _RecordLocation(segment, doc_id, cmp_value)
            else:
                valid.clear(doc_id)
        else:
            self._map[pk] = _RecordLocation(segment, doc_id, cmp_value)

    def merge_record(self, previous: Optional[dict], record: dict) -> dict:
        """Partial-upsert merge (ref upsert/merger/): with no merger
        configured the new record fully replaces the old."""
        if self.partial_merger is None or previous is None:
            return record
        return self.partial_merger(previous, record)

    def remove_segment(self, segment) -> None:
        """Ref removeSegment: drop map entries still pointing at it."""
        with self._lock:
            dead = [pk for pk, loc in self._map.items()
                    if loc.segment is segment]
            for pk in dead:
                del self._map[pk]

    def replace_segment(self, old, new) -> None:
        """Ref replaceSegment (seal handoff): `new` is a row-for-row
        rebuild of `old`, so its validity IS old's bitmap — share the
        object and redirect map entries in place. No recompute, so there
        is no window where either copy's valid bits are cleared
        (ADVICE r1: add+remove cleared the sealed mutable's bits while
        queries could still see it)."""
        new.valid_doc_ids = getattr(old, "valid_doc_ids", None)
        with self._lock:
            for loc in self._map.values():
                if loc.segment is old:
                    loc.segment = new

    def lookup(self, pk: tuple) -> Optional[Tuple[Any, int]]:
        with self._lock:
            loc = self._map.get(pk)
            return (loc.segment, loc.doc_id) if loc else None

    @property
    def num_primary_keys(self) -> int:
        with self._lock:
            return len(self._map)


class PartitionDedupMetadataManager:
    """Drop exact-duplicate primary keys at ingestion time
    (ref dedup/ConcurrentMapPartitionDedupMetadataManager)."""

    def __init__(self, pk_columns: Sequence[str]):
        self.pk_columns = list(pk_columns)
        self._seen: set = set()
        self._lock = threading.Lock()

    def check_and_add(self, record: Dict[str, Any]) -> bool:
        """True when the record is new (should be ingested)."""
        pk = _pk_of(record, self.pk_columns)
        with self._lock:
            if pk in self._seen:
                return False
            self._seen.add(pk)
            return True

    @property
    def num_primary_keys(self) -> int:
        with self._lock:
            return len(self._seen)


# partial-upsert merge strategies (ref upsert/merger/)
def overwrite_merger(previous: dict, record: dict) -> dict:
    return record


def ignore_nulls_merger(previous: dict, record: dict) -> dict:
    """OVERWRITE per column but keep previous value where new is null."""
    out = dict(previous)
    for k, v in record.items():
        if v is not None:
            out[k] = v
    return out


def increment_merger(columns: Sequence[str]):
    """INCREMENT for listed columns, overwrite otherwise."""
    cols = set(columns)

    def merge(previous: dict, record: dict) -> dict:
        out = dict(record)
        for c in cols:
            if previous.get(c) is not None and record.get(c) is not None:
                out[c] = previous[c] + record[c]
        return out
    return merge


def _cmp_ge(a, b) -> bool:
    try:
        return a >= b
    except TypeError:
        return str(a) >= str(b)


def _py(v):
    return v.item() if isinstance(v, np.generic) else v
