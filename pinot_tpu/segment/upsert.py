"""Upsert & dedup metadata managers.

Reference parity: pinot-segment-local upsert/
ConcurrentMapPartitionUpsertMetadataManager.java:48 — a per-partition
primary-key map to (segment, docId, comparisonValue); per-segment
validDocIds bitmaps that queries AND into their filter mask; later
(or equal, last-wins) comparison values replace earlier rows. Partial
upsert merge strategies live in merger functions (ref upsert/merger/).
Dedup analog: ConcurrentMapPartitionDedupMetadataManager (dedup/).

Query integration: segments gain a `valid_doc_ids` attribute; the host
executor ANDs it into the filter mask, and the device engine excludes
upsert segments (they are realtime-sized; SURVEY.md §2.3).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.segment.bitmap import Bitmap


@dataclass
class _RecordLocation:
    segment: Any          # object with .name and .valid_doc_ids
    doc_id: int
    comparison_value: Any


def _pk_of(record_or_row, pk_columns: Sequence[str]) -> tuple:
    return tuple(record_or_row[c] for c in pk_columns)


class PartitionUpsertMetadataManager:
    """One stream partition's upsert state (ref :48)."""

    def __init__(self, pk_columns: Sequence[str], comparison_column: str,
                 partial_merger: Optional[Callable[[dict, dict], dict]] = None):
        self.pk_columns = list(pk_columns)
        self.comparison_column = comparison_column
        self.partial_merger = partial_merger
        self._map: Dict[tuple, _RecordLocation] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def add_segment(self, segment, use_snapshot: bool = True) -> None:
        """Register an (im)mutable segment's rows; later comparison values
        win, losers are invalidated in their owning segment's bitmap.

        use_snapshot: when the segment directory carries a persisted
        validDocIds snapshot (ref upsert/ snapshot logic), start from it —
        docs already invalidated before the snapshot lost their upsert
        battle and are skipped, making restart O(valid) not O(total)."""
        n = segment.num_docs
        snap = load_valid_doc_ids(segment) if use_snapshot else None
        valid = snap if snap is not None else Bitmap.all_set(n)
        segment.valid_doc_ids = valid
        pk_cols = [np.asarray(segment.data_source(c).values())
                   for c in self.pk_columns]
        cmp_col = np.asarray(segment.data_source(self.comparison_column).values())
        mask = valid.to_mask() if snap is not None else None
        with self._lock:
            for doc_id in range(n):
                if mask is not None and not mask[doc_id]:
                    continue
                pk = tuple(_py(col[doc_id]) for col in pk_cols)
                self._upsert_locked(segment, doc_id, _py(cmp_col[doc_id]), pk,
                                    valid)

    def add_row(self, segment, doc_id: int, record: Dict[str, Any]) -> None:
        """Realtime path: account one newly indexed row (ref addRecord)."""
        if getattr(segment, "valid_doc_ids", None) is None:
            segment.valid_doc_ids = Bitmap(0)
        valid = segment.valid_doc_ids
        if valid.num_docs <= doc_id:
            valid.resize(doc_id + 1)
        valid.set(doc_id)
        pk = _pk_of(record, self.pk_columns)
        cmp_value = record[self.comparison_column]
        with self._lock:
            self._upsert_locked(segment, doc_id, cmp_value, pk, valid)

    def _upsert_locked(self, segment, doc_id, cmp_value, pk, valid) -> None:
        cur = self._map.get(pk)
        if cur is not None:
            if _cmp_ge(cmp_value, cur.comparison_value):
                cur.segment.valid_doc_ids.clear(cur.doc_id)
                self._map[pk] = _RecordLocation(segment, doc_id, cmp_value)
            else:
                valid.clear(doc_id)
        else:
            self._map[pk] = _RecordLocation(segment, doc_id, cmp_value)

    def merge_record(self, previous: Optional[dict], record: dict) -> dict:
        """Partial-upsert merge (ref upsert/merger/): with no merger
        configured the new record fully replaces the old."""
        if self.partial_merger is None or previous is None:
            return record
        return self.partial_merger(previous, record)

    def remove_segment(self, segment) -> None:
        """Ref removeSegment: drop map entries still pointing at it."""
        with self._lock:
            dead = [pk for pk, loc in self._map.items()
                    if loc.segment is segment]
            for pk in dead:
                del self._map[pk]

    def replace_segment(self, old, new) -> None:
        """Ref replaceSegment (seal handoff): `new` is a row-for-row
        rebuild of `old`, so its validity IS old's bitmap — share the
        object and redirect map entries in place. No recompute, so there
        is no window where either copy's valid bits are cleared
        (ADVICE r1: add+remove cleared the sealed mutable's bits while
        queries could still see it)."""
        new.valid_doc_ids = getattr(old, "valid_doc_ids", None)
        with self._lock:
            for loc in self._map.values():
                if loc.segment is old:
                    loc.segment = new

    def lookup(self, pk: tuple) -> Optional[Tuple[Any, int]]:
        with self._lock:
            loc = self._map.get(pk)
            return (loc.segment, loc.doc_id) if loc else None

    @property
    def num_primary_keys(self) -> int:
        with self._lock:
            return len(self._map)


class PartitionDedupMetadataManager:
    """Drop exact-duplicate primary keys at ingestion time
    (ref dedup/ConcurrentMapPartitionDedupMetadataManager)."""

    def __init__(self, pk_columns: Sequence[str]):
        self.pk_columns = list(pk_columns)
        self._seen: set = set()
        self._lock = threading.Lock()

    def check_and_add(self, record: Dict[str, Any]) -> bool:
        """True when the record is new (should be ingested)."""
        pk = _pk_of(record, self.pk_columns)
        with self._lock:
            if pk in self._seen:
                return False
            self._seen.add(pk)
            return True

    def add_segment(self, segment) -> None:
        """Restart recovery: re-register a committed segment's primary
        keys so a resumed consumer drops duplicates of rows it already
        persisted (ref dedup metadata rebuild on server restart)."""
        pk_cols = [np.asarray(segment.data_source(c).values())
                   for c in self.pk_columns]
        n = segment.num_docs
        with self._lock:
            for i in range(n):
                self._seen.add(tuple(_py(c[i]) for c in pk_cols))

    @property
    def num_primary_keys(self) -> int:
        with self._lock:
            return len(self._seen)


# partial-upsert merge strategies (ref upsert/merger/)
def overwrite_merger(previous: dict, record: dict) -> dict:
    return record


def ignore_nulls_merger(previous: dict, record: dict) -> dict:
    """OVERWRITE per column but keep previous value where new is null."""
    out = dict(previous)
    for k, v in record.items():
        if v is not None:
            out[k] = v
    return out


def increment_merger(columns: Sequence[str]):
    """INCREMENT for listed columns, overwrite otherwise."""
    cols = set(columns)

    def merge(previous: dict, record: dict) -> dict:
        out = dict(record)
        for c in cols:
            if previous.get(c) is not None and record.get(c) is not None:
                out[c] = previous[c] + record[c]
        return out
    return merge


def _cmp_ge(a, b) -> bool:
    try:
        return a >= b
    except TypeError:
        return str(a) >= str(b)


def _py(v):
    return v.item() if isinstance(v, np.generic) else v


# ---------------------------------------------------------------------------
# validDocIds snapshots (ref pinot-segment-local upsert/ snapshot logic:
# persisted per segment so a restarted server resumes upsert state without
# replaying every row)
# ---------------------------------------------------------------------------

VALID_DOC_IDS_SNAPSHOT = "validdocids.snapshot"


def write_valid_doc_ids(seg_dir: str, valid: Bitmap, crc: int = 0) -> None:
    """Write a validDocIds snapshot into a segment directory. The header
    carries (num_docs, crc) so a rebuilt segment of the SAME size does not
    silently adopt a stale bitmap (ref Pinot's snapshot crc check)."""
    import os
    import struct
    path = os.path.join(seg_dir, VALID_DOC_IDS_SNAPSHOT)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<IQ", valid.num_docs, crc & (2**64 - 1)))
        f.write(valid.to_bytes())
    os.replace(tmp, path)


def persist_valid_doc_ids(segment) -> bool:
    """Write the segment's current validDocIds bitmap next to its data
    files. Returns False when the segment has no bitmap or no directory."""
    valid = getattr(segment, "valid_doc_ids", None)
    seg_dir = getattr(getattr(segment, "dir", None), "path", None)
    if valid is None or seg_dir is None:
        return False
    crc = getattr(getattr(segment, "metadata", None), "crc", 0) or 0
    write_valid_doc_ids(seg_dir, valid, crc)
    return True


def load_valid_doc_ids(segment) -> Optional[Bitmap]:
    """Read a persisted snapshot if present and matching this segment
    build (num_docs AND crc when both sides carry one)."""
    import os
    import struct
    seg_dir = getattr(getattr(segment, "dir", None), "path", None)
    if seg_dir is None:
        return None
    path = os.path.join(seg_dir, VALID_DOC_IDS_SNAPSHOT)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        num_docs, snap_crc = struct.unpack("<IQ", f.read(12))
        data = f.read()
    if num_docs != segment.num_docs:
        return None  # stale snapshot from a different build
    seg_crc = getattr(getattr(segment, "metadata", None), "crc", 0) or 0
    if snap_crc and seg_crc and snap_crc != (seg_crc & (2**64 - 1)):
        return None  # same size, different build
    return Bitmap.from_bytes(num_docs, data)
