"""Vector similarity index: dense blocks + IVF-style coarse cells.

Reference parity: pinot-segment-local
segment/creator/impl/vector/HnswVectorIndexCreator.java +
readers/vector/ (Lucene99 HNSW) and
core/operator/filter/VectorSimilarityFilterOperator — VECTOR_SIMILARITY
(vec_col, query_vec, topK) filters to the K nearest docs.

TPU-first clean-room design: graph walks (HNSW) are pointer-chasing and
hostile to the MXU; dense similarity IS a matmul. Vectors store as one
[n, d] float32 block (unit-normalized for cosine); search is
score = V @ q with top-k — the exact-search path the MXU eats, batched
over segments by the engine. An IVF-style coarse layer (k-means-lite
cells, sampled init + a few Lloyd iterations at build) prunes to
nprobe cells for large n, trading recall for speed the same way HNSW's
ef parameter does. Serialization is a flat little-endian layout.
"""
from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

_HDR = struct.Struct("<IIIf")  # n, dim, n_cells, pad


class VectorIndexCorruption(ValueError):
    """A serialized vector index whose declared sizes disagree with the
    payload actually present (torn write, truncated download, bit rot).
    Typed so loaders can distinguish 'this segment file is damaged' from
    a plain bad-argument ValueError."""


def _normalize(v: np.ndarray) -> np.ndarray:
    n = np.linalg.norm(v, axis=-1, keepdims=True)
    return v / np.maximum(n, 1e-30)


class VectorIndex:
    """[n, d] float32 block + optional coarse cells."""

    #: build a coarse layer above this row count
    IVF_THRESHOLD = 4096

    def __init__(self, vectors: np.ndarray,
                 centroids: Optional[np.ndarray] = None,
                 assignments: Optional[np.ndarray] = None,
                 metric: str = "cosine"):
        self.vectors = vectors  # unit-normalized when metric == cosine
        self.centroids = centroids
        self.assignments = assignments
        self.metric = metric

    # ------------------------------------------------------------------
    @classmethod
    def build(cls, vectors, metric: str = "cosine",
              n_cells: Optional[int] = None) -> "VectorIndex":
        v = np.asarray(vectors, dtype=np.float32)
        if v.ndim != 2:
            raise ValueError("vector index needs [n, d] input")
        if metric == "cosine":
            v = _normalize(v).astype(np.float32)
        n = len(v)
        if n_cells is None:
            n_cells = int(np.sqrt(n)) if n >= cls.IVF_THRESHOLD else 0
        centroids = assignments = None
        if n_cells >= 2:
            centroids, assignments = cls._kmeans_lite(v, n_cells)
        return cls(v, centroids, assignments, metric)

    @staticmethod
    def _kmeans_lite(v: np.ndarray, k: int,
                     iters: int = 4) -> Tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(0)  # deterministic builds
        centroids = v[rng.choice(len(v), size=k, replace=False)]
        assign = np.zeros(len(v), np.int32)
        for _ in range(iters):
            # cosine/L2 on normalized vectors share the argmax
            sims = v @ centroids.T
            assign = np.argmax(sims, axis=1).astype(np.int32)
            for c in range(k):
                members = v[assign == c]
                if len(members):
                    m = members.mean(axis=0)
                    centroids[c] = m / max(np.linalg.norm(m), 1e-30)
        return centroids.astype(np.float32), assign

    # ------------------------------------------------------------------
    def top_k(self, query, k: int, nprobe: int = 8) -> np.ndarray:
        """Doc ids of the K most similar vectors (exact when no coarse
        layer; nprobe cells otherwise — the recall/latency dial)."""
        if k <= 0 or len(self.vectors) == 0:
            return np.empty(0, np.int32)
        q = np.asarray(query, dtype=np.float32).ravel()
        if self.metric == "cosine":
            q = _normalize(q[None, :])[0].astype(np.float32)
        if self.centroids is None:
            scores = self.vectors @ q
            cand = np.arange(len(scores))
        else:
            probe = self.probe_cells(q, nprobe)
            if probe is None:
                cand = np.arange(len(self.vectors))
            else:
                cand = np.nonzero(np.isin(self.assignments, probe))[0]
            scores = self.vectors[cand] @ q
        k = min(k, len(cand))
        # score-descending, ties toward the LOWER doc id: deterministic
        # regardless of partition order, and bit-identical to the device
        # kernel's jax.lax.top_k tie-break
        order = np.lexsort((cand, -scores))
        return cand[order[:k]].astype(np.int32)

    def probe_cells(self, query, nprobe: int = 8) -> Optional[np.ndarray]:
        """The coarse cells an IVF search would scan for this query
        (score-descending argsort over the centroids), or None when the
        probe set would be empty-candidate and search falls back to ALL
        cells — shared by top_k and the device leg's staged cell mask so
        probe selection is host-parity by construction."""
        if self.centroids is None:
            return None
        q = np.asarray(query, dtype=np.float32).ravel()
        cell_scores = self.centroids @ q
        probe = np.argsort(cell_scores)[::-1][:nprobe]
        if not np.isin(self.assignments, probe).any():
            return None
        return probe

    # ------------------------------------------------------------------
    def to_bytes(self) -> bytes:
        n, d = self.vectors.shape
        ncells = 0 if self.centroids is None else len(self.centroids)
        out = [_HDR.pack(n, d, ncells, 0.0),
               (b"C" if self.metric == "cosine" else b"L"),
               self.vectors.astype("<f4").tobytes()]
        if ncells:
            out.append(self.centroids.astype("<f4").tobytes())
            out.append(self.assignments.astype("<i4").tobytes())
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf) -> "VectorIndex":
        buf = bytes(buf)
        if len(buf) < _HDR.size + 1:
            raise VectorIndexCorruption(
                f"vector index payload truncated: {len(buf)} bytes is "
                f"shorter than the {_HDR.size + 1}-byte header")
        n, d, ncells, _ = _HDR.unpack_from(buf, 0)
        # the header is DECLARED sizes — validate against the bytes
        # actually present before any frombuffer slices past the end
        # (np would raise an opaque ValueError on a torn payload, or
        # silently mis-shape on a short-but-aligned one)
        need = _HDR.size + 1 + 4 * n * d
        if ncells:
            need += 4 * ncells * d + 4 * n
        if len(buf) < need:
            raise VectorIndexCorruption(
                f"vector index payload truncated: header declares "
                f"n={n} d={d} n_cells={ncells} ({need} bytes), got "
                f"{len(buf)}")
        pos = _HDR.size
        metric = "cosine" if buf[pos:pos + 1] == b"C" else "l2"
        pos += 1
        vecs = np.frombuffer(buf, "<f4", n * d, pos).reshape(n, d).copy()
        pos += 4 * n * d
        centroids = assignments = None
        if ncells:
            centroids = np.frombuffer(buf, "<f4", ncells * d, pos) \
                .reshape(ncells, d).copy()
            pos += 4 * ncells * d
            assignments = np.frombuffer(buf, "<i4", n, pos).copy()
        return cls(vecs, centroids, assignments, metric)
