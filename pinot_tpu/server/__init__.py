"""Server role: segment data managers, query scheduler, transport.

Reference parity: pinot-server + the server-side parts of pinot-core L4/L5
(SURVEY.md): InstanceRequestHandler (core/transport/
InstanceRequestHandler.java:122), QueryScheduler (query/scheduler/
QueryScheduler.java:93), InstanceDataManager/TableDataManager
(core/data/manager/).
"""
