"""Server admission control: reject early, reject cheap.

The overload-protection policy layer at the query transport edge
("The Tail at Scale", Dean & Barroso, CACM 2013; DAGOR in "Overload
Control for Scaling WeChat Microservices", SOSP 2018). Before a request
enters the scheduler, :meth:`AdmissionController.admit` decides whether
the server can plausibly answer it inside its deadline budget; a
rejection costs one dict of work and surfaces as a typed errorCode-211
entry with a ``retryAfterMs=`` drain hint, instead of the query
queueing toward a guaranteed errorCode-250 after consuming a worker
thread.

Decision order (first hit wins), all O(1):

1. **chaos** — the ``server.admission.reject`` failpoint (seeded,
   journal-replayable) may force a rejection;
2. **workload** — under full brownout (health/brownout.py rung
   ``shed_secondary``) secondary workloads are shed whole;
3. **memory** — HBM/host memory pressure (the residency tier's bytes
   against its budget plus any registered source, e.g. realtime-ingest
   bytes against ``pinot.server.ingest.memory.bytes``) at/over the
   threshold sheds new work before the allocators do it the hard way;
4. **queue** — the bounded queue is full (the schedulers enforce the
   same bound internally as a race backstop);
5. **deadline** — the query's remaining budget is below the
   EWMA-estimated queue wait + execution time: it WILL miss, so fail it
   now in O(1) (deadline-aware admission, the PR-3 pick-up guard moved
   to the front door);
6. **tenant** — past ``shed.start`` queue occupancy, tenants shed
   lowest-weight-first: the occupancy-scaled weight cutoff rises toward
   the heaviest tenant's weight as the queue fills (DAGOR's
   business-priority shedding over the existing
   TokenPriorityScheduler weights).

Estimates feed from :class:`_Ticket` hooks the transport wraps around
every admitted query (queue wait observed at pick-up, execution wall
time at completion), so the controller needs no scheduler internals.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from pinot_tpu.utils.accounting import ServerOverloadedError
from pinot_tpu.utils.failpoints import FailpointError, fire

#: retry-after hint clamps: never tell a client "now" (it would tight-
#: loop) and never park it for more than 5s (the fleet drains faster)
_MIN_RETRY_AFTER_MS = 10.0
_MAX_RETRY_AFTER_MS = 5000.0


def _clamp_hint(ms: float) -> float:
    return min(_MAX_RETRY_AFTER_MS, max(_MIN_RETRY_AFTER_MS, ms))


class _Ticket:
    """In-flight accounting handle for ONE admitted query: registered at
    submit, released exactly once when its future resolves (done
    callback), with queue-wait/exec observations recorded from the
    worker thread in between. ``run`` is the worker-side wrapper the
    transport submits."""

    __slots__ = ("_ctrl", "_submit_t", "_start_t", "_released")

    def __init__(self, ctrl: "AdmissionController"):
        self._ctrl = ctrl
        self._submit_t = time.monotonic()
        self._start_t: Optional[float] = None
        self._released = False

    def run(self, fn):
        """Execute fn on the worker thread, recording the observed queue
        wait (submit -> pick-up) and execution wall time. Runs only for
        queries that survived the deadline guard, so the EWMAs are fed
        by genuine executions, not by O(1) pick-up kills."""
        self._start_t = time.monotonic()
        self._ctrl._note_wait(self._start_t - self._submit_t)
        try:
            return fn()
        finally:
            self._ctrl._note_exec(time.monotonic() - self._start_t)

    def release(self) -> None:
        """Idempotent in-flight decrement — wired as the future's done
        callback so cancelled/never-run submissions can't leak the
        count."""
        ctrl = self._ctrl
        with ctrl._lock:
            if self._released:
                return
            self._released = True
            ctrl._inflight -= 1


class AdmissionController:
    def __init__(self, num_threads: int = 8, enabled: bool = True,
                 queue_limit: int = 128, shed_start: float = 0.5,
                 memory_threshold: float = 0.95, ewma_alpha: float = 0.2,
                 tenant_weights_fn: Optional[Callable[[], Dict[str, float]]]
                 = None,
                 memory_pressure_fn: Optional[Callable[[], float]] = None,
                 metrics=None, labels: Optional[dict] = None):
        self.enabled = bool(enabled)
        self.num_threads = max(1, int(num_threads))
        self.queue_limit = max(0, int(queue_limit))
        self.shed_start = min(1.0, max(0.0, float(shed_start)))
        self.memory_threshold = float(memory_threshold)
        self.alpha = min(1.0, max(0.01, float(ewma_alpha)))
        self._tenant_weights_fn = tenant_weights_fn
        self._memory_pressure_fn = memory_pressure_fn
        self._metrics = metrics
        self._labels = labels
        self._lock = threading.Lock()
        self._inflight = 0
        self._exec_ewma_s: Optional[float] = None
        self._wait_ewma_s: Optional[float] = None
        #: memoized memory pressure (the fn may sum per-partition ingest
        #: bytes — cheap, but not per-request cheap at 10k qps)
        self._pressure = 0.0
        self._pressure_at = 0.0

    PRESSURE_TTL_S = 0.1

    @classmethod
    def from_config(cls, config, num_threads: int = 8,
                    **kwargs) -> "AdmissionController":
        if config is None:
            return cls(num_threads=num_threads, **kwargs)
        return cls(
            num_threads=num_threads,
            enabled=config.get_bool("pinot.server.admission.enabled", True),
            queue_limit=config.get_int("pinot.server.admission.queue.limit"),
            shed_start=config.get_float("pinot.server.admission.shed.start"),
            memory_threshold=config.get_float(
                "pinot.server.admission.memory.threshold"),
            ewma_alpha=config.get_float(
                "pinot.server.admission.exec.ewma.alpha"),
            **kwargs)

    # -- estimate feeds -------------------------------------------------
    def _note_wait(self, wait_s: float) -> None:
        with self._lock:
            cur = self._wait_ewma_s
            self._wait_ewma_s = wait_s if cur is None else \
                (1 - self.alpha) * cur + self.alpha * wait_s

    def _note_exec(self, exec_s: float) -> None:
        with self._lock:
            cur = self._exec_ewma_s
            self._exec_ewma_s = exec_s if cur is None else \
                (1 - self.alpha) * cur + self.alpha * exec_s

    def register(self) -> _Ticket:
        t = _Ticket(self)
        with self._lock:
            self._inflight += 1
        return t

    # -- introspection (tests + /debug) --------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            queued = max(0, self._inflight - self.num_threads)
            return {"inflight": self._inflight, "queued": queued,
                    "execEwmaMs": (None if self._exec_ewma_s is None
                                   else round(self._exec_ewma_s * 1e3, 3)),
                    "waitEwmaMs": (None if self._wait_ewma_s is None
                                   else round(self._wait_ewma_s * 1e3, 3))}

    # -- the decision ---------------------------------------------------
    def _reject(self, reason_label: str, message: str,
                retry_after_ms: float) -> ServerOverloadedError:
        if self._metrics is not None:
            labels = dict(self._labels or {})
            labels["reason"] = reason_label
            self._metrics.add_meter("server_admission_rejected",
                                    labels=labels)
        return ServerOverloadedError(message,
                                     retry_after_ms=_clamp_hint(
                                         retry_after_ms))

    def memory_pressure(self) -> float:
        """Memoized worst-of pressure fraction from the wired source."""
        fn = self._memory_pressure_fn
        if fn is None:
            return 0.0
        now = time.monotonic()
        with self._lock:
            if now - self._pressure_at < self.PRESSURE_TTL_S:
                return self._pressure
        try:
            p = float(fn())
        except Exception:  # noqa: BLE001 — a broken gauge must not
            p = 0.0        # take admission (and with it the server) down
        with self._lock:
            self._pressure = p
            self._pressure_at = now
        return p

    def admit(self, table: str = "", tenant: Optional[str] = None,
              workload: str = "primary",
              deadline: Optional[float] = None,
              now: Optional[float] = None
              ) -> Optional[ServerOverloadedError]:
        """None = admitted; otherwise the typed rejection to answer
        with. Never raises — chaos-forced rejections are returned like
        policy ones so the transport has exactly one rejection path."""
        try:
            fire("server.admission.reject", table=table,
                 tenant=tenant or "", workload=workload)
        except (ServerOverloadedError, FailpointError) as e:
            retry = getattr(e, "retry_after_ms", 0.0)
            return self._reject("chaos", f"chaos rejection: {e}", retry)
        if not self.enabled:
            return None
        if workload == "secondary":
            from pinot_tpu.health.brownout import engaged
            if engaged("server", "shed_secondary"):
                return self._reject(
                    "workload",
                    "secondary workloads shed under brownout", 1000.0)
        pressure = self.memory_pressure()
        if self.memory_threshold > 0 and pressure >= self.memory_threshold:
            return self._reject(
                "memory",
                f"memory pressure {pressure:.2f} >= "
                f"{self.memory_threshold:.2f}", 250.0)
        with self._lock:
            queued = max(0, self._inflight - self.num_threads)
            exec_s = self._exec_ewma_s
            wait_s = self._wait_ewma_s
        # estimated wait ahead of a NEW arrival: everything queued, one
        # service time at a time across the worker pool — blended with
        # the observed-wait EWMA so a mis-modeled scheduler (priority
        # buckets, binary pools) still converges on reality. The blend
        # applies ONLY while a queue exists: the EWMAs are fed by
        # executed queries, so if the observed wait froze high and kept
        # rejecting everything, nothing would ever run to pull it back
        # down — an empty queue means zero wait, whatever history says.
        est_wait_s = 0.0
        if exec_s is not None and queued > 0:
            est_wait_s = queued * exec_s / self.num_threads
            if wait_s is not None:
                est_wait_s = max(est_wait_s, wait_s)
        if self.queue_limit and queued >= self.queue_limit:
            return self._reject(
                "queue",
                f"admission queue full ({queued} >= {self.queue_limit})",
                est_wait_s * 1e3 or 100.0)
        if deadline is not None and exec_s is not None:
            remaining_s = deadline - (now if now is not None
                                      else time.time())
            need_s = est_wait_s + exec_s
            if remaining_s < need_s:
                return self._reject(
                    "deadline",
                    f"remaining budget {remaining_s * 1e3:.0f}ms < "
                    f"estimated wait+exec {need_s * 1e3:.0f}ms",
                    (need_s - remaining_s) * 1e3)
        if self.queue_limit and queued / self.queue_limit > self.shed_start \
                and self._tenant_weights_fn is not None:
            weights = self._tenant_weights_fn()
            if weights:
                occupancy = min(1.0, queued / self.queue_limit)
                frac = (occupancy - self.shed_start) \
                    / max(1e-9, 1.0 - self.shed_start)
                cutoff = frac * max(weights.values())
                w = weights.get(tenant or "", 1.0) if tenant else 1.0
                if w < cutoff:
                    return self._reject(
                        "tenant",
                        f"tenant weight {w:g} below shed cutoff "
                        f"{cutoff:.2f} at {occupancy:.0%} occupancy",
                        est_wait_s * 1e3 or 250.0)
        return None
