"""Segment data managers: server-side table/segment lifecycle.

Reference parity: pinot-core data/manager/ — InstanceDataManager ->
TableDataManager -> SegmentDataManager with acquire/release reference
counting (BaseTableDataManager.acquireSegments / releaseSegment), so a
segment directory is never deleted under a running query.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional, Sequence

from pinot_tpu.segment.loader import ImmutableSegment, load_segment


class SegmentDataManager:
    def __init__(self, segment: ImmutableSegment):
        self.segment = segment
        self._refs = 1  # the manager's own reference
        self._lock = threading.Lock()
        self._destroyed = False

    @property
    def name(self) -> str:
        return self.segment.name

    def acquire(self) -> bool:
        with self._lock:
            if self._destroyed:
                return False
            self._refs += 1
            return True

    def release(self) -> None:
        destroy = False
        with self._lock:
            self._refs -= 1
            destroy = self._refs == 0 and self._destroyed
        if destroy:
            self.segment.destroy()

    def offload(self) -> None:
        """Drop the manager's own reference; destroys once queries drain."""
        destroy = False
        with self._lock:
            if not self._destroyed:
                self._destroyed = True
                self._refs -= 1
                destroy = self._refs == 0
        if destroy:
            self.segment.destroy()


class TableDataManager:
    """Ref BaseTableDataManager — one per table on a server."""

    def __init__(self, table_name: str):
        self.table_name = table_name
        self._segments: Dict[str, SegmentDataManager] = {}
        self._lock = threading.Lock()

    def add_segment(self, segment: ImmutableSegment) -> None:
        sdm = SegmentDataManager(segment)
        with self._lock:
            old = self._segments.get(segment.name)
            self._segments[segment.name] = sdm
        if old is not None:
            old.offload()

    def add_segment_from_dir(self, seg_dir: str) -> None:
        self.add_segment(load_segment(seg_dir))

    def remove_segment(self, name: str) -> None:
        with self._lock:
            sdm = self._segments.pop(name, None)
        if sdm is not None:
            sdm.offload()

    def acquire_segments(self, names: Optional[Sequence[str]] = None
                         ) -> List[SegmentDataManager]:
        """Acquire the named segments (or all); caller must release_all.
        Missing names are silently skipped (ref returns missing list for
        the broker to count as partial results)."""
        out = []
        with self._lock:
            targets = (self._segments.values() if names is None else
                       [self._segments[n] for n in names if n in self._segments])
            for sdm in list(targets):
                if sdm.acquire():
                    out.append(sdm)
        return out

    @staticmethod
    def release_all(sdms: List[SegmentDataManager]) -> None:
        for sdm in sdms:
            sdm.release()

    @property
    def segment_names(self) -> List[str]:
        with self._lock:
            return list(self._segments.keys())

    def shutdown(self) -> None:
        with self._lock:
            sdms = list(self._segments.values())
            self._segments.clear()
        for sdm in sdms:
            sdm.offload()


class InstanceDataManager:
    """Ref InstanceDataManager — all tables on one server instance."""

    def __init__(self, instance_id: str = "server_0"):
        self.instance_id = instance_id
        self._tables: Dict[str, TableDataManager] = {}
        self._lock = threading.Lock()

    def table(self, table_name: str, create: bool = True) -> Optional[TableDataManager]:
        with self._lock:
            tdm = self._tables.get(table_name)
            if tdm is None and create:
                tdm = TableDataManager(table_name)
                self._tables[table_name] = tdm
            return tdm

    @property
    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())

    def shutdown(self) -> None:
        with self._lock:
            tables = list(self._tables.values())
            self._tables.clear()
        for t in tables:
            t.shutdown()
