"""Segment data managers: server-side table/segment lifecycle.

Reference parity: pinot-core data/manager/ — InstanceDataManager ->
TableDataManager -> SegmentDataManager with acquire/release reference
counting (BaseTableDataManager.acquireSegments / releaseSegment), so a
segment directory is never deleted under a running query.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional, Sequence

from pinot_tpu.segment.loader import ImmutableSegment, load_segment


class SegmentDataManager:
    def __init__(self, segment: ImmutableSegment):
        self.segment = segment
        self._refs = 1  # the manager's own reference
        self._lock = threading.Lock()
        self._destroyed = False

    @property
    def name(self) -> str:
        return self.segment.name

    def acquire(self) -> bool:
        with self._lock:
            if self._destroyed:
                return False
            self._refs += 1
            return True

    def release(self) -> None:
        destroy = False
        with self._lock:
            self._refs -= 1
            destroy = self._refs == 0 and self._destroyed
        if destroy:
            self.segment.destroy()

    def offload(self) -> None:
        """Drop the manager's own reference; destroys once queries drain."""
        destroy = False
        with self._lock:
            if not self._destroyed:
                self._destroyed = True
                self._refs -= 1
                destroy = self._refs == 0
        if destroy:
            self.segment.destroy()


class TableDataManager:
    """Ref BaseTableDataManager — one per table on a server."""

    def __init__(self, table_name: str, listener=None, warmup=None):
        self.table_name = table_name
        self._segments: Dict[str, SegmentDataManager] = {}
        self._lock = threading.Lock()
        #: monotonically increasing segment-set version, bumped on every
        #: add/replace/remove — cache tiers key/invalidate on it
        self._version = 0
        #: optional callback(event, table_name, segment_name) fired AFTER
        #: the mutation commits; events: "add" | "replace" | "remove"
        self._listener = listener
        #: optional callback(table_name, segment) run BEFORE a segment is
        #: published to queries — the cache-warmup replay hook
        #: (cache/warmup.py): the first routed query on a fresh immutable
        #: segment should hit tier 2, not scan. Must never raise into the
        #: load path; failures only cost cold-start.
        self._warmup = warmup

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def _notify(self, event: str, segment_name: str) -> None:
        if self._listener is not None:
            self._listener(event, self.table_name, segment_name)

    def add_segment(self, segment: ImmutableSegment) -> None:
        if self._warmup is not None:
            # replay logged plans BEFORE the segment enters the serving
            # map — its first query then hits warm cache tiers
            try:
                self._warmup(self.table_name, segment)
            except Exception:  # noqa: BLE001 — warmup must not block load
                pass
        sdm = SegmentDataManager(segment)
        with self._lock:
            old = self._segments.get(segment.name)
            self._segments[segment.name] = sdm
            self._version += 1
        if old is not None:
            old.offload()
        self._notify("replace" if old is not None else "add", segment.name)

    def add_segment_from_dir(self, seg_dir: str) -> None:
        self.add_segment(load_segment(seg_dir))

    def remove_segment(self, name: str) -> None:
        with self._lock:
            sdm = self._segments.pop(name, None)
            if sdm is not None:
                self._version += 1
        if sdm is not None:
            sdm.offload()
            self._notify("remove", name)

    def current_segment(self, name: str) -> Optional[ImmutableSegment]:
        """The LIVE segment object for a name (or None) — a lock-held
        peek, no refcount taken: callers use it transiently for identity
        comparisons (cache invalidation sparing the just-swapped-in
        version), not for query execution."""
        with self._lock:
            sdm = self._segments.get(name)
            return sdm.segment if sdm is not None else None

    def acquire_segments(self, names: Optional[Sequence[str]] = None
                         ) -> List[SegmentDataManager]:
        """Acquire the named segments (or all); caller must release_all.
        Missing names are silently skipped (ref returns missing list for
        the broker to count as partial results)."""
        out = []
        with self._lock:
            targets = (self._segments.values() if names is None else
                       [self._segments[n] for n in names if n in self._segments])
            for sdm in list(targets):
                if sdm.acquire():
                    out.append(sdm)
        return out

    @staticmethod
    def release_all(sdms: List[SegmentDataManager]) -> None:
        for sdm in sdms:
            sdm.release()

    @property
    def segment_names(self) -> List[str]:
        with self._lock:
            return list(self._segments.keys())

    def shutdown(self) -> None:
        with self._lock:
            sdms = list(self._segments.values())
            self._segments.clear()
            self._version += 1
        for sdm in sdms:
            sdm.offload()
            self._notify("remove", sdm.name)


class InstanceDataManager:
    """Ref InstanceDataManager — all tables on one server instance."""

    def __init__(self, instance_id: str = "server_0"):
        self.instance_id = instance_id
        self._tables: Dict[str, TableDataManager] = {}
        self._lock = threading.Lock()
        self._segment_listeners: List = []
        self._warmup_hook = None

    def add_segment_listener(self, fn) -> None:
        """Register callback(event, table_name, segment_name) fired on
        every table's segment add/replace/remove (covers tables created
        after registration too)."""
        with self._lock:
            self._segment_listeners.append(fn)

    def set_warmup_hook(self, fn) -> None:
        """callback(table_name, segment) run before each segment add on
        EVERY table (existing and future) — the cache-warmup replay.
        Tables always route through _dispatch_warmup, so registration
        order vs. table creation order doesn't matter."""
        with self._lock:
            self._warmup_hook = fn

    def _dispatch_warmup(self, table_name: str, segment) -> None:
        with self._lock:
            fn = self._warmup_hook
        if fn is not None:
            fn(table_name, segment)

    def _dispatch_segment_event(self, event: str, table_name: str,
                                segment_name: str) -> None:
        with self._lock:
            listeners = list(self._segment_listeners)
        for fn in listeners:
            fn(event, table_name, segment_name)

    def table(self, table_name: str, create: bool = True) -> Optional[TableDataManager]:
        with self._lock:
            tdm = self._tables.get(table_name)
            if tdm is None and create:
                tdm = TableDataManager(table_name,
                                       listener=self._dispatch_segment_event,
                                       warmup=self._dispatch_warmup)
                self._tables[table_name] = tdm
            return tdm

    @property
    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())

    def shutdown(self) -> None:
        with self._lock:
            tables = list(self._tables.values())
            self._tables.clear()
        for t in tables:
            t.shutdown()
