"""DataTable: the server->broker wire format.

Reference parity: pinot-common datatable/DataTableImplV4.java:82 — the
binary container a server returns per query: result payload + metadata
(stats) + exceptions. The reference serializes aggregation intermediates
with a typed ObjectSerDe registry; same approach here (tag byte + typed
payload, numpy-backed), deliberately NOT pickle: the broker must never
execute payload-controlled code.

Layout: 4-byte magic 'PDT1', then a tagged value tree:
  N null | i int64 | f float64 | s utf-8 str | b bytes | T/F bool
  D Decimal(str)  | t tuple | l list | S set | M dict
  A numpy array (dtype str, ndim, shape, raw bytes)
  H HyperLogLog (log2m + registers) | G TDigest (compression, means, weights)
  R result container (shape tag + fields)
"""
from __future__ import annotations

import struct
from decimal import Decimal
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.utils import errorcodes
from pinot_tpu.query.aggregation.sketches import (
    HyperLogLog, KLLSketch, TDigest, ThetaSketch)
from pinot_tpu.query.results import (
    AggregationResult, DistinctResult, ExecutionStats, GroupByResult,
    SelectionResult)

MAGIC = b"PDT1"

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")


class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def u32(self, v: int):
        self.parts.append(_U32.pack(v))

    def raw(self, b: bytes):
        self.parts.append(b)

    def tag(self, t: str):
        self.parts.append(t.encode())

    def value(self, v: Any):
        if v is None:
            self.tag("N")
        elif isinstance(v, bool):
            self.tag("T" if v else "F")
        elif isinstance(v, (int, np.integer)):
            self.tag("i")
            self.raw(_I64.pack(int(v)))
        elif isinstance(v, (float, np.floating)):
            self.tag("f")
            self.raw(_F64.pack(float(v)))
        elif isinstance(v, str):
            b = v.encode()
            self.tag("s")
            self.u32(len(b))
            self.raw(b)
        elif isinstance(v, bytes):
            self.tag("b")
            self.u32(len(v))
            self.raw(v)
        elif isinstance(v, Decimal):
            b = str(v).encode()
            self.tag("D")
            self.u32(len(b))
            self.raw(b)
        elif isinstance(v, tuple):
            self.tag("t")
            self.u32(len(v))
            for x in v:
                self.value(x)
        elif isinstance(v, list):
            self.tag("l")
            self.u32(len(v))
            for x in v:
                self.value(x)
        elif isinstance(v, (set, frozenset)):
            self.tag("S")
            self.u32(len(v))
            for x in v:
                self.value(x)
        elif isinstance(v, dict):
            self.tag("M")
            self.u32(len(v))
            for k, x in v.items():
                self.value(k)
                self.value(x)
        elif isinstance(v, np.ndarray):
            self.tag("A")
            if v.dtype.kind in "UO":  # store as list of strings
                self.value([str(x) for x in v.tolist()])
            else:
                dt = v.dtype.str.encode()
                self.u32(len(dt))
                self.raw(dt)
                self.u32(v.ndim)
                for d in v.shape:
                    self.u32(d)
                self.raw(np.ascontiguousarray(v).tobytes())
        elif isinstance(v, HyperLogLog):
            self.tag("H")
            self.u32(v.log2m)
            self.raw(v.registers.tobytes())
        elif isinstance(v, TDigest):
            v._compress(force=True)
            self.tag("G")
            self.raw(_F64.pack(v.compression))
            self.raw(_F64.pack(v.total))
            self.value(v.means)
            self.value(v.weights)
        elif isinstance(v, ThetaSketch):
            self.tag("E")
            self.u32(v.k)
            self.raw(struct.pack("<Q", int(v.theta)))
            self.value(v.hashes)
        elif isinstance(v, KLLSketch):
            self.tag("K")
            self.u32(v.k)
            self.raw(_I64.pack(v.n))
            self.value([lvl for lvl in v.levels])
        else:
            raise TypeError(f"unserializable value type {type(v)}")

    def bytes(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def u32(self) -> int:
        v = _U32.unpack_from(self.buf, self.pos)[0]
        self.pos += 4
        return v

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def value(self) -> Any:
        t = chr(self.buf[self.pos])
        self.pos += 1
        if t == "N":
            return None
        if t == "T":
            return True
        if t == "F":
            return False
        if t == "i":
            v = _I64.unpack_from(self.buf, self.pos)[0]
            self.pos += 8
            return v
        if t == "f":
            v = _F64.unpack_from(self.buf, self.pos)[0]
            self.pos += 8
            return v
        if t == "s":
            return self.take(self.u32()).decode()
        if t == "b":
            return self.take(self.u32())
        if t == "D":
            return Decimal(self.take(self.u32()).decode())
        if t == "t":
            return tuple(self.value() for _ in range(self.u32()))
        if t == "l":
            return [self.value() for _ in range(self.u32())]
        if t == "S":
            return {self.value() for _ in range(self.u32())}
        if t == "M":
            return {self.value(): self.value() for _ in range(self.u32())}
        if t == "A":
            if chr(self.buf[self.pos]) == "l":  # string array stored as list
                return np.array(self.value(), dtype=object)
            dt = np.dtype(self.take(self.u32()).decode())
            ndim = self.u32()
            shape = tuple(self.u32() for _ in range(ndim))
            n = int(np.prod(shape)) if shape else 1
            arr = np.frombuffer(self.take(n * dt.itemsize), dtype=dt)
            return arr.reshape(shape).copy()
        if t == "H":
            h = HyperLogLog(self.u32())
            h.registers = np.frombuffer(self.take(h.m), dtype=np.uint8).copy()
            return h
        if t == "G":
            comp = _F64.unpack_from(self.buf, self.pos)[0]
            self.pos += 8
            total = _F64.unpack_from(self.buf, self.pos)[0]
            self.pos += 8
            td = TDigest(comp)
            td.total = total
            td.means = self.value()
            td.weights = self.value()
            return td
        if t == "E":
            sk = ThetaSketch(self.u32())
            sk.theta = np.uint64(
                struct.unpack_from("<Q", self.buf, self.pos)[0])
            self.pos += 8
            sk.hashes = self.value().astype(np.uint64)
            return sk
        if t == "K":
            k = self.u32()
            sk = KLLSketch(k)
            sk.n = _I64.unpack_from(self.buf, self.pos)[0]
            self.pos += 8
            sk.levels = [np.asarray(lvl, dtype=np.float64)
                         for lvl in self.value()]
            return sk
        raise ValueError(f"bad tag {t!r} at {self.pos - 1}")


def _stats_tuple(s: ExecutionStats) -> tuple:
    return (s.num_docs_scanned, s.num_entries_scanned_in_filter,
            s.num_entries_scanned_post_filter, s.num_segments_processed,
            s.num_segments_matched, s.total_docs, s.num_segments_pruned)


def _stats_from(t: tuple) -> ExecutionStats:
    return ExecutionStats(*t)


def serialize_value(v: Any) -> bytes:
    """One typed value (incl. sketches) -> bytes. Used for aggregation
    intermediates crossing the MSE mailbox plane as opaque block cells
    (ref DataBlock variable-size payloads)."""
    w = _Writer()
    w.value(v)
    return w.bytes()


def deserialize_value(buf: bytes) -> Any:
    return _Reader(buf).value()


def serialize_results(results: List[Any], exceptions: List[dict] = (),
                      extra_stats: Optional[ExecutionStats] = None) -> bytes:
    """Server response: list of shape-tagged SegmentResults + exceptions +
    server-level stats (pruning counts survive even with zero results —
    the reference carries these in DataTable metadata).

    Layout note: a server-side span tree may be APPENDED to the returned
    bytes as one extra tagged value (ServerQueryExecutor.execute does
    `payload + serialize_value(tree)`); readers that stop at the result
    count skip it, `deserialize_results_ex` picks it up."""
    w = _Writer()
    w.raw(MAGIC)
    w.value([_exc_tuple(e) for e in exceptions])
    w.value(_stats_tuple(extra_stats) if extra_stats is not None else None)
    w.u32(len(results))
    for r in results:
        if isinstance(r, AggregationResult):
            w.tag("1")
            w.value(r.intermediates)
            w.value(_stats_tuple(r.stats))
        elif isinstance(r, GroupByResult):
            w.tag("2")
            w.value(r.groups)
            w.value(_stats_tuple(r.stats))
            w.value(r.num_groups_limit_reached)
        elif isinstance(r, SelectionResult):
            w.tag("3")
            w.value(r.rows)
            w.value(r.order_values)
            w.value(r.columns)
            w.value(_stats_tuple(r.stats))
        elif isinstance(r, DistinctResult):
            w.tag("4")
            w.value(r.rows)
            w.value(_stats_tuple(r.stats))
        else:
            raise TypeError(f"unserializable result {type(r)}")
    return w.bytes()


def deserialize_results(buf: bytes
                        ) -> Tuple[List[Any], List[dict], Optional[ExecutionStats]]:
    results, exceptions, extra_stats, _trace = deserialize_results_ex(buf)
    return results, exceptions, extra_stats


def deserialize_results_ex(buf: bytes) -> Tuple[
        List[Any], List[dict], Optional[ExecutionStats], Optional[dict]]:
    """deserialize_results + the optional trailing trace tree (None when
    the payload carries none — e.g. tracing disabled on the server)."""
    if buf[:4] != MAGIC:
        raise ValueError("bad DataTable magic")
    r = _Reader(buf, 4)
    exceptions = [_exc_from(t) for t in r.value()]
    st = r.value()
    extra_stats = _stats_from(st) if st is not None else None
    n = r.u32()
    out: List[Any] = []
    for _ in range(n):
        tag = chr(r.buf[r.pos])
        r.pos += 1
        if tag == "1":
            inters = r.value()
            out.append(AggregationResult(inters, _stats_from(r.value())))
        elif tag == "2":
            groups = r.value()
            stats = _stats_from(r.value())
            out.append(GroupByResult(groups, stats,
                                     num_groups_limit_reached=r.value()))
        elif tag == "3":
            rows = r.value()
            order_values = r.value()
            columns = r.value()
            out.append(SelectionResult(rows, order_values=order_values,
                                       columns=columns,
                                       stats=_stats_from(r.value())))
        elif tag == "4":
            rows = r.value()
            out.append(DistinctResult(rows, _stats_from(r.value())))
        else:
            raise ValueError(f"bad result tag {tag!r}")
    trace = None
    if r.pos < len(r.buf):
        t = r.value()
        if isinstance(t, dict):
            trace = t
    return out, exceptions, extra_stats, trace


def _exc_tuple(e: dict) -> tuple:
    return (int(e.get("errorCode", errorcodes.QUERY_EXECUTION)),
            str(e.get("message", "")))


def _exc_from(t: tuple) -> dict:
    return {"errorCode": t[0], "message": t[1]}
