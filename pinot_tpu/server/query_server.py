"""Server transport + per-server query execution.

Reference parity: pinot-core transport — QueryServer (Netty) +
InstanceRequestHandler.channelRead0 (transport/InstanceRequestHandler.java:122)
+ QueryScheduler.submit (query/scheduler/QueryScheduler.java:93). Here:
an asyncio TCP server speaking length-prefixed frames:

  request : u32 len | JSON {requestId, tableName, sql, segments?: [...]}
  response: u32 len | DataTable bytes (server/datatable.py)

Execution itself reuses QueryExecutor (pruning + device engine + host
fallback) over the acquired segments; a thread pool keeps the event loop
free (FCFS scheduling, the QuerySchedulerFactory default).
"""
from __future__ import annotations

import asyncio
import json
import socket
import struct
import threading
import time
from typing import List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.server import datatable
from pinot_tpu.server.data_manager import InstanceDataManager, TableDataManager

_LEN = struct.Struct("<I")


class ServerQueryExecutor:
    """Ref ServerQueryExecutorV1Impl: executes one query over this server's
    segments for a table."""

    def __init__(self, data_manager: InstanceDataManager, use_tpu: bool = True,
                 config=None):
        self.data_manager = data_manager
        self.use_tpu = use_tpu
        #: instance config (PinotConfiguration); threads through to the
        #: device engine's cache budgets and the streaming chunk size
        self.config = config
        if config is not None:
            # the catalog default applies whenever a config is present
            # (the class attribute only backs config-less construction)
            self.STREAM_CHUNK_SEGMENTS = config.get_int(
                "pinot.server.stream.chunk.segments")
        #: ONE engine for the server's lifetime — it owns the HBM block
        #: cache, which must survive across requests
        self._engine = None
        self._engine_lock = threading.Lock()
        #: tier-2 per-segment partial-result cache — shared across requests
        #: for the same reason as the engine. Version-keyed entries go
        #: stale-unaddressable on replace; the data-manager hook below
        #: additionally reclaims their bytes promptly.
        from pinot_tpu.cache.segment_cache import SegmentResultCache
        from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup
        from pinot_tpu.utils.metrics import get_registry
        labels = {"instance": data_manager.instance_id}
        if config is not None:
            self.segment_cache = SegmentResultCache.from_config(
                config, metrics=get_registry("server"), labels=labels)
        else:
            self.segment_cache = SegmentResultCache(
                metrics=get_registry("server"), labels=labels)
        data_manager.add_segment_listener(self._on_segment_event)
        # warmup fabric: log cacheable plans per table; replay them on
        # every fresh immutable segment BEFORE it serves queries, so a
        # rollout's first routed query hits tier 2 (cache/warmup.py)
        warm_on = (config is None or config.get_bool(
            "pinot.server.segment.warmup.enabled", True))
        log_size = (config.get_int(
            "pinot.server.segment.warmup.log.plans.per.table")
            if config is not None else 64)
        max_plans = (config.get_int("pinot.server.segment.warmup.max.plans")
                     if config is not None else 32)
        # a knob explicitly set to 0 means OFF (the classes themselves
        # clamp to >=1, so 0 must be honored here, not passed through)
        warm_on = warm_on and log_size > 0 and max_plans > 0
        self._plan_log_enabled = warm_on
        self.fingerprint_log = FingerprintLog(max(1, log_size))
        self.warmup = SegmentWarmup(
            self.fingerprint_log, self.segment_cache,
            max_plans=max(1, max_plans), use_tpu=use_tpu,
            engine_fn=self._shared_engine,
            metrics=get_registry("server"), labels=labels)
        if warm_on:
            data_manager.set_warmup_hook(self.warmup.warm)

    def _on_segment_event(self, event: str, table_name: str,
                          segment_name: str) -> None:
        """TableDataManager version-bump hook: drop cached partials for a
        replaced/removed segment immediately (version keying already makes
        them unreachable; this reclaims the bytes). On replace, entries
        for the LIVE version are spared — warmup just populated them
        (add_segment warms before the swap commits), and wiping them
        would re-introduce the rollout cold start warmup exists to
        remove."""
        if event not in ("replace", "remove"):
            return
        keep = None
        if event == "replace":
            from pinot_tpu.cache.segment_cache import segment_version
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is not None:
                sdms = tdm.acquire_segments([segment_name])
                try:
                    if sdms:
                        keep = segment_version(sdms[0].segment)
                finally:
                    type(tdm).release_all(sdms)
        self.segment_cache.invalidate_segment(segment_name,
                                              except_version=keep)

    def _record_plan(self, table_name: str, ctx, sql_or_ctx,
                     extra_filter) -> None:
        """Feed the warmup fingerprint log: cacheable-shape queries only
        (the replay would be a no-op otherwise), and only when the raw
        SQL is available to replay. extra_filter (the hybrid
        time-boundary predicate) is logged alongside — the fingerprint
        covers the MERGED filter tree, so replay must merge it too."""
        if not self._plan_log_enabled or not isinstance(sql_or_ctx, str):
            return
        from pinot_tpu.cache.core import cache_bypassed
        from pinot_tpu.cache.segment_cache import is_cacheable_shape
        if is_cacheable_shape(ctx) and not cache_bypassed(ctx.options):
            self.fingerprint_log.record(table_name, ctx.fingerprint(),
                                        sql_or_ctx,
                                        extra_filter=extra_filter)

    def _shared_engine(self):
        if not self.use_tpu:
            return None
        with self._engine_lock:
            if self._engine is None:
                from pinot_tpu.ops.engine import TpuOperatorExecutor
                self._engine = TpuOperatorExecutor(config=self.config)
            return self._engine

    def execute(self, table_name: str, sql_or_ctx,
                segments: Optional[List[str]] = None,
                extra_filter: Optional[str] = None):
        """Returns serialized DataTable bytes. extra_filter (an expression
        string, e.g. the hybrid time-boundary predicate) is ANDed into the
        filter tree — the reference rewrites the BrokerRequest the same way."""
        from pinot_tpu.utils.metrics import get_registry
        metrics = get_registry("server")
        metrics.add_meter("queries", labels={"table": table_name})
        timer = metrics.time("query_execution", labels={"table": table_name})
        timer.__enter__()
        try:
            ctx = (sql_or_ctx if isinstance(sql_or_ctx, QueryContext)
                   else QueryContext.from_sql(sql_or_ctx))
            from pinot_tpu.query.context import merge_extra_filter
            merge_extra_filter(ctx, extra_filter)
            self._record_plan(table_name, ctx, sql_or_ctx, extra_filter)
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is None:
                return datatable.serialize_results(
                    [], [{"errorCode": 190, "message": f"table {table_name} not found"}])
            sdms = tdm.acquire_segments(segments)
            try:
                ex = QueryExecutor([s.segment for s in sdms],
                                   use_tpu=self.use_tpu,
                                   engine=self._shared_engine(),
                                   segment_cache=self.segment_cache)
                results, prune_stats = ex.execute_context(ctx)
                return datatable.serialize_results(results,
                                                   extra_stats=prune_stats)
            finally:
                TableDataManager.release_all(sdms)
        except Exception as e:  # noqa: BLE001 — server must answer, not die
            metrics.add_meter("query_exceptions", labels={"table": table_name})
            return datatable.serialize_results(
                [], [{"errorCode": 200, "message": f"{type(e).__name__}: {e}"}])
        finally:
            timer.__exit__(None, None, None)

    #: segments per streamed response frame
    STREAM_CHUNK_SEGMENTS = 4

    def execute_streaming(self, table_name: str, sql_or_ctx,
                          segments: Optional[List[str]] = None,
                          extra_filter: Optional[str] = None):
        """Per-block response frames for large results (ref
        GrpcQueryServer's streaming Submit + StreamingInstanceResponse
        PlanNode): a GENERATOR — each segment chunk executes and
        serializes lazily as the transport consumes it, so the server
        never materializes the full result and the first frame ships
        while later chunks still compute."""
        try:
            ctx = (sql_or_ctx if isinstance(sql_or_ctx, QueryContext)
                   else QueryContext.from_sql(sql_or_ctx))
            from pinot_tpu.query.context import merge_extra_filter
            merge_extra_filter(ctx, extra_filter)
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is None:
                yield datatable.serialize_results(
                    [], [{"errorCode": 190,
                          "message": f"table {table_name} not found"}])
                return
            sdms = tdm.acquire_segments(segments)
            try:
                chunk = self.STREAM_CHUNK_SEGMENTS
                segs = [s.segment for s in sdms]
                for i in range(0, max(len(segs), 1), chunk):
                    ex = QueryExecutor(segs[i:i + chunk],
                                       use_tpu=self.use_tpu,
                                       engine=self._shared_engine(),
                                       segment_cache=self.segment_cache)
                    results, prune_stats = ex.execute_context(ctx)
                    yield datatable.serialize_results(
                        results, extra_stats=prune_stats)
            finally:
                TableDataManager.release_all(sdms)
        except Exception as e:  # noqa: BLE001
            yield datatable.serialize_results(
                [], [{"errorCode": 200,
                      "message": f"{type(e).__name__}: {e}"}])


class QueryServer:
    """Asyncio TCP server (the Netty QueryServer analog)."""

    def __init__(self, executor: ServerQueryExecutor, host: str = "127.0.0.1",
                 port: int = 0, num_threads: int = 8,
                 scheduler: str = "fcfs"):
        from pinot_tpu.server.scheduler import make_scheduler
        self.executor = executor
        self.host = host
        self.port = port
        #: pluggable query scheduler (ref QuerySchedulerFactory.java:45 —
        #: fcfs | priority | binary); owns the query worker threads
        self.scheduler = make_scheduler(scheduler, num_threads)
        self.scheduler.start()
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                n = _LEN.unpack(hdr)[0]
                payload = await reader.readexactly(n)
                req = json.loads(payload)
                if req.get("streaming"):
                    # per-block response stream (ref GrpcQueryServer.Submit
                    # server-stream): generator creation is cheap; EACH
                    # frame's execution is its own scheduler submission so
                    # priority/binary-workload accounting still throttles
                    # streaming work, and frames ship as they compute
                    gen = self.executor.execute_streaming(
                        req["tableName"], req["sql"], req.get("segments"),
                        req.get("extraFilter"))
                    while True:
                        fut = self.scheduler.submit(
                            lambda g=gen: next(g, None),
                            table=req.get("tableName", ""),
                            workload=req.get("workload", "primary"))
                        frame = await asyncio.wrap_future(fut)
                        if frame is None:
                            break
                        writer.write(_LEN.pack(len(frame)) + frame)
                        await writer.drain()
                    writer.write(_LEN.pack(0))  # EOS
                    await writer.drain()
                    continue
                fut = self.scheduler.submit(
                    lambda r=req: self.executor.execute(
                        r["tableName"], r["sql"], r.get("segments"),
                        r.get("extraFilter")),
                    table=req.get("tableName", ""),
                    workload=req.get("workload", "primary"))
                resp = await asyncio.wrap_future(fut)
                writer.write(_LEN.pack(len(resp)) + resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def start(self) -> None:
        """Start serving on a background thread; sets self.port."""
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def main():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()
                async with self._server:
                    await self._server.serve_forever()

            try:
                loop.run_until_complete(main())
            except asyncio.CancelledError:
                pass
            finally:
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"query-server-{self.port}")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("query server failed to start")

    def stop(self) -> None:
        """Idempotent: a failover test (or ops) may stop a server that was
        already killed."""
        if self._loop is not None and not self._loop.is_closed():
            def shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()
            try:
                self._loop.call_soon_threadsafe(shutdown)
            except RuntimeError:
                pass  # loop closed between the check and the call
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.scheduler.stop()


class ServerConnection:
    """Broker-side long-lived channel to one server (ref ServerChannels:65)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port), timeout=30)
        return self._sock

    def request(self, table_name: str, sql: str,
                segments: Optional[List[str]] = None,
                request_id: int = 0,
                extra_filter: Optional[str] = None) -> bytes:
        payload = json.dumps({
            "requestId": request_id, "tableName": table_name, "sql": sql,
            "segments": segments, "extraFilter": extra_filter}).encode()
        with self._lock:
            try:
                sock = self._connect()
                sock.sendall(_LEN.pack(len(payload)) + payload)
                return self._read_frame(sock)
            except socket.timeout:
                # a slow query, NOT a dead channel: retransmitting would run
                # it twice server-side; drop the channel and surface the
                # timeout (ref: the reference fails the query, the failure
                # detector handles the server)
                self.close()
                raise
            except ConnectionError:
                # one reconnect attempt (ref channel re-establish)
                self.close()
                sock = self._connect()
                sock.sendall(_LEN.pack(len(payload)) + payload)
                return self._read_frame(sock)

    def request_streaming(self, table_name: str, sql: str,
                          segments: Optional[List[str]] = None,
                          request_id: int = 0,
                          extra_filter: Optional[str] = None):
        """Generator of per-block DataTable payloads until the server's
        zero-length EOS frame (ref GrpcQueryServer server-stream). The
        channel lock is held for the whole stream — frames of one query
        must not interleave with another request's."""
        payload = json.dumps({
            "requestId": request_id, "tableName": table_name, "sql": sql,
            "segments": segments, "extraFilter": extra_filter,
            "streaming": True}).encode()
        with self._lock:
            completed = False
            try:
                sock = self._connect()
                sock.sendall(_LEN.pack(len(payload)) + payload)
                while True:
                    frame = self._read_frame(sock, allow_empty=True)
                    if not frame:
                        completed = True
                        return  # EOS
                    yield frame
            finally:
                if not completed:
                    # consumer aborted (or the read failed) mid-stream:
                    # unread frames would poison the next request on this
                    # channel — drop it and let request() re-dial
                    self.close()

    @staticmethod
    def _read_frame(sock: socket.socket, allow_empty: bool = False) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionError("server closed connection")
            hdr += chunk
        n = _LEN.unpack(hdr)[0]
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(min(1 << 20, n - len(buf)))
            if not chunk:
                raise ConnectionError("server closed connection mid-frame")
            buf += chunk
        return bytes(buf)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
