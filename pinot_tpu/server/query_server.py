"""Server transport + per-server query execution.

Reference parity: pinot-core transport — QueryServer (Netty) +
InstanceRequestHandler.channelRead0 (transport/InstanceRequestHandler.java:122)
+ QueryScheduler.submit (query/scheduler/QueryScheduler.java:93). Here:
an asyncio TCP server speaking length-prefixed frames:

  request : u32 len | JSON {requestId, tableName, sql, segments?: [...]}
  response: u32 len | DataTable bytes (server/datatable.py)

Execution itself reuses QueryExecutor (pruning + device engine + host
fallback) over the acquired segments; a thread pool keeps the event loop
free (FCFS scheduling, the QuerySchedulerFactory default).
"""
from __future__ import annotations

import asyncio
import json
import socket
import struct
import threading
import time
from typing import List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.server import datatable
from pinot_tpu.server.data_manager import InstanceDataManager, TableDataManager
from pinot_tpu.utils import errorcodes
from pinot_tpu.utils.accounting import (BrokerTimeoutError,
                                        QueryCancelledError,
                                        ResourceAccountant,
                                        ServerOverloadedError)
from pinot_tpu.utils.failpoints import fire

_LEN = struct.Struct("<I")

#: extra seconds a broker-side socket read waits past the shipped budget —
#: covers the server's own deadline grace + scheduling jitter, so the
#: server's typed 250 response (not a raw socket timeout) is the normal
#: way a deadline surfaces
_SOCKET_GRACE_S = 2.0


def _timeout_response(e: BaseException) -> bytes:
    """The typed deadline-miss payload (ref QueryException
    EXECUTION_TIMEOUT_ERROR_CODE): empty results + an errorCode-250
    entry; the broker merges it as a partial, never a hang."""
    return datatable.serialize_results(
        [], [{"errorCode": BrokerTimeoutError.ERROR_CODE,
              "message": f"BrokerTimeoutError: {e}"}])


def _overload_response(e: ServerOverloadedError) -> bytes:
    """The typed admission-rejection payload: errorCode-211, no
    results, the drain hint embedded in the message (the exception wire
    format is (code, message) tuples — see datatable._exc_tuple — so
    the hint travels in-band, formatted/parsed through the shared
    errorcodes helpers). The hint is floored even for scheduler-
    backstop rejections that carry retry_after_ms=0 — a client told
    "retry now" would tight-loop against the saturated server."""
    hint = errorcodes.format_retry_after(max(10.0, e.retry_after_ms))
    return datatable.serialize_results(
        [], [{"errorCode": ServerOverloadedError.ERROR_CODE,
              "message": f"ServerOverloadedError: {e.reason or e} "
                         f"{hint}"}])


class ServerQueryExecutor:
    """Ref ServerQueryExecutorV1Impl: executes one query over this server's
    segments for a table."""

    def __init__(self, data_manager: InstanceDataManager, use_tpu: bool = True,
                 config=None):
        self.data_manager = data_manager
        self.use_tpu = use_tpu
        #: instance config (PinotConfiguration); threads through to the
        #: device engine's cache budgets and the streaming chunk size
        self.config = config
        #: per-query deadline/cancel registry: the broker ships the
        #: REMAINING budget with each request, and a broker-side expiry
        #: sends an explicit cancel keyed by the query id — either way
        #: the segment loop's cooperative checks stop abandoned work
        self.accountant = ResourceAccountant()
        self.deadline_grace_s = (
            config.get_int("pinot.server.query.deadline.grace.ms") / 1000.0
            if config is not None else 0.05)
        #: distributed tracing: open a server-side span tree per traced
        #: request and ship it back in the response (utils/tracing.py)
        if config is not None:
            self._trace_enabled = config.get_bool(
                "pinot.trace.enabled", True)
            self._slow_threshold_ms = config.get_float(
                "pinot.server.slow.query.threshold.ms")
            self._trace_capacity = config.get_int(
                "pinot.trace.store.capacity")
        else:
            self._trace_enabled = True
            self._slow_threshold_ms = 0.0
            self._trace_capacity = None
        #: latency-SLO target — queries over it bump the slo_latency_bad
        #: counter the burn-rate watchdog reads as windowed deltas
        self._slo_p99_ms = (config.get_float("pinot.slo.query.p99.ms")
                            if config is not None else 0.0)
        if config is not None:
            # the catalog default applies whenever a config is present
            # (the class attribute only backs config-less construction)
            self.STREAM_CHUNK_SEGMENTS = config.get_int(
                "pinot.server.stream.chunk.segments")
        #: per-query workload accounting (ChargeSlip + WorkloadStats
        #: rollup); off = the bench --health A-side
        self._accounting_enabled = (
            config is None or config.get_bool(
                "pinot.workload.accounting.enabled", True))
        #: ONE engine for the server's lifetime — it owns the HBM block
        #: cache, which must survive across requests
        self._engine = None
        self._engine_lock = threading.Lock()
        #: extra memory-pressure inputs for admission (0..1 fractions):
        #: ServerRole registers realtime-ingest bytes vs budget here;
        #: the residency tier is consulted built-in (memory_pressure)
        self._pressure_sources = []
        #: tier-2 per-segment partial-result cache — shared across requests
        #: for the same reason as the engine. Version-keyed entries go
        #: stale-unaddressable on replace; the data-manager hook below
        #: additionally reclaims their bytes promptly.
        from pinot_tpu.cache.segment_cache import SegmentResultCache
        from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup
        from pinot_tpu.utils.metrics import get_registry
        labels = {"instance": data_manager.instance_id}
        if config is not None:
            self.segment_cache = SegmentResultCache.from_config(
                config, metrics=get_registry("server"), labels=labels)
        else:
            self.segment_cache = SegmentResultCache(
                metrics=get_registry("server"), labels=labels)
        data_manager.add_segment_listener(self._on_segment_event)
        # warmup fabric: log cacheable plans per table; replay them on
        # every fresh immutable segment BEFORE it serves queries, so a
        # rollout's first routed query hits tier 2 (cache/warmup.py)
        warm_on = (config is None or config.get_bool(
            "pinot.server.segment.warmup.enabled", True))
        log_size = (config.get_int(
            "pinot.server.segment.warmup.log.plans.per.table")
            if config is not None else 64)
        max_plans = (config.get_int("pinot.server.segment.warmup.max.plans")
                     if config is not None else 32)
        # a knob explicitly set to 0 means OFF (the classes themselves
        # clamp to >=1, so 0 must be honored here, not passed through)
        warm_on = warm_on and log_size > 0 and max_plans > 0
        self._plan_log_enabled = warm_on
        # journal (ROADMAP): persist the plan log so a restart warms from
        # pre-restart traffic; one file per instance, off when dir unset
        journal_path = None
        journal_max = 1 << 20
        if config is not None:
            journal_dir = config.get_str(
                "pinot.server.segment.warmup.journal.dir")
            if journal_dir:
                import os
                os.makedirs(journal_dir, exist_ok=True)
                journal_path = os.path.join(
                    journal_dir, f"{data_manager.instance_id}.fplog.jsonl")
                journal_max = config.get_int(
                    "pinot.server.segment.warmup.journal.max.bytes")
        self.fingerprint_log = FingerprintLog(max(1, log_size),
                                              journal_path=journal_path,
                                              journal_max_bytes=journal_max)
        self.warmup = SegmentWarmup(
            self.fingerprint_log, self.segment_cache,
            max_plans=max(1, max_plans), use_tpu=use_tpu,
            engine_fn=self._shared_engine,
            metrics=get_registry("server"), labels=labels)
        if warm_on:
            data_manager.set_warmup_hook(self.warmup.warm)

    def _on_segment_event(self, event: str, table_name: str,
                          segment_name: str) -> None:
        """TableDataManager version-bump hook: drop cached partials for a
        replaced/removed segment immediately (version keying already makes
        them unreachable; this reclaims the bytes). On replace, entries
        for the LIVE version are spared — warmup just populated them
        (add_segment warms before the swap commits), and wiping them
        would re-introduce the rollout cold start warmup exists to
        remove."""
        if event not in ("replace", "remove"):
            return
        keep = keep_obj = None
        if event == "replace":
            from pinot_tpu.cache.segment_cache import segment_version
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is not None:
                keep_obj = tdm.current_segment(segment_name)
                if keep_obj is not None:
                    keep = segment_version(keep_obj)
        self.segment_cache.invalidate_segment(segment_name,
                                              except_version=keep)
        # device tier rides the same epoch-moving event: drop the old
        # version's resident rows / assembled blocks / params promptly
        # (identity keys already make them unreachable), sparing the
        # just-warmed live object's entries
        engine = self._engine
        if engine is not None:
            engine.invalidate_segment(segment_name, keep=keep_obj)

    def _record_plan(self, table_name: str, ctx, sql_or_ctx,
                     extra_filter) -> None:
        """Feed the warmup fingerprint log: cacheable-shape queries only
        (the replay would be a no-op otherwise), and only when the raw
        SQL is available to replay. extra_filter (the hybrid
        time-boundary predicate) is logged alongside — the fingerprint
        covers the MERGED filter tree, so replay must merge it too."""
        if not self._plan_log_enabled or not isinstance(sql_or_ctx, str):
            return
        from pinot_tpu.cache.core import cache_bypassed
        from pinot_tpu.cache.segment_cache import is_cacheable_shape
        if is_cacheable_shape(ctx) and not cache_bypassed(ctx.options):
            self.fingerprint_log.record(table_name, ctx.fingerprint(),
                                        sql_or_ctx,
                                        extra_filter=extra_filter)

    def _shared_engine(self):
        if not self.use_tpu:
            return None
        with self._engine_lock:
            if self._engine is None:
                from pinot_tpu.ops.engine import TpuOperatorExecutor
                # instance labels thread through to the dispatch-ring
                # metrics (dispatch_queue_depth / dispatch_batch_size /
                # kernel_retrace / staging_overlap_ms)
                self._engine = TpuOperatorExecutor(
                    config=self.config,
                    metrics_labels={
                        "instance": self.data_manager.instance_id})
            return self._engine

    def residency_report(self) -> dict:
        """Per-physical-table HBM-resident bytes this server can
        advertise in its heartbeat (the instance-sweep residency
        payload): brokers break replica-choice ties toward servers whose
        device memory already holds the table's columns. Empty when no
        device engine/resident tier exists — the hint is best-effort."""
        engine = self._engine
        res = getattr(engine, "_residency", None) \
            if engine is not None else None
        if res is None or not getattr(res, "enabled", False):
            return {}
        by_seg = res.resident_bytes_by_segment()
        if not by_seg:
            return {}
        out: dict = {}
        for table in self.data_manager.table_names:
            tdm = self.data_manager.table(table, create=False)
            if tdm is None:
                continue
            total = sum(by_seg.get(n, 0) for n in tdm.segment_names)
            if total:
                out[table] = total
        return out

    def add_memory_pressure_source(self, fn) -> None:
        """Register a () -> 0..1 fraction the admission controller folds
        into its memory-pressure decision (worst-of across sources)."""
        self._pressure_sources.append(fn)

    def memory_pressure(self) -> float:
        """Worst-of memory-pressure fraction across this server's
        accountings: the HBM residency tier's fill — on a multi-chip
        mesh the MOST-LOADED chip against its per-chip share, not the
        pooled total (ResidencyManager.pressure) — plus every registered
        source (realtime-ingest bytes against the ingest memory budget,
        wired by ServerRole). 0.0 when nothing is budgeted — an
        unbudgeted server never sheds on memory."""
        worst = 0.0
        # lint: unlocked(reference snapshot; _shared_engine publishes the engine once under its lock and never unsets it)
        engine = self._engine
        res = getattr(engine, "_residency", None) \
            if engine is not None else None
        if res is not None and getattr(res, "enabled", False):
            worst = max(worst, res.pressure())
        for fn in list(self._pressure_sources):
            try:
                worst = max(worst, float(fn()))
            except Exception:  # noqa: BLE001 — a broken source must not
                pass           # take admission down with it
        return worst

    def cancel(self, query_id) -> bool:
        """Broker-initiated cancel (rides ResourceAccountant.cancel): the
        next cooperative check in the query's segment loop raises and the
        worker thread frees. A cancel for a query still sitting in the
        scheduler queue is a no-op here — the shipped deadline kills it
        at pick-up instead."""
        return self.accountant.cancel(str(query_id))

    def execute(self, table_name: str, sql_or_ctx,
                segments: Optional[List[str]] = None,
                extra_filter: Optional[str] = None,
                query_id=None, timeout_ms: Optional[float] = None,
                deadline: Optional[float] = None,
                trace_ctx: Optional[dict] = None,
                arrival_s: Optional[float] = None,
                tenant: Optional[str] = None):
        """Returns serialized DataTable bytes (see _execute_inner for the
        execution semantics). trace_ctx: the broker-shipped TraceContext
        wire dict — when present (and tracing is enabled) this server
        opens its OWN span tree rooted at ServerRequest, records
        scheduler queue wait (arrival_s = transport read time), runs the
        query under it so engine/cache instrumentation lands in it, and
        appends the tree to the response bytes so the broker stitches
        one cross-process trace. Slow requests (and sampled ones) are
        retained in the server's trace store."""
        from pinot_tpu.utils import tracing
        from pinot_tpu.utils import trace_store
        tc = tracing.TraceContext.from_wire(trace_ctx)
        if tc is None or not self._trace_enabled:
            return self._execute_inner(table_name, sql_or_ctx, segments,
                                       extra_filter, query_id, timeout_ms,
                                       deadline, tenant=tenant)
        rt = tracing.RequestTrace(
            request_id=str(query_id or ""), operator="ServerRequest",
            trace_id=tc.trace_id, sampled=tc.sampled,
            instance=self.data_manager.instance_id, table=table_name)
        if arrival_s is not None:
            rt.handle().set(queueWaitMs=round(
                max(0.0, time.time() - arrival_s) * 1000.0, 3))
        inflight = trace_store.get_inflight("server")
        key = f"{tc.trace_id}:{query_id}"
        sql_text = sql_or_ctx if isinstance(sql_or_ctx, str) else ""
        inflight.begin(key, sql=sql_text, trace_id=tc.trace_id,
                       detail=table_name, tenant=tenant, deadline=deadline)
        inflight.phase(key, "execute", table_name)
        try:
            with rt:
                payload = self._execute_inner(
                    table_name, sql_or_ctx, segments, extra_filter,
                    query_id, timeout_ms, deadline, tenant=tenant)
        finally:
            inflight.end(key)
        dur = rt.root.duration_ms
        tree = rt.to_dict()
        slow = (self._slow_threshold_ms > 0
                and dur >= self._slow_threshold_ms)
        if tc.sampled or slow:
            # key carries the instance: two embedded servers sharing a
            # process (and therefore the role store) both record the
            # same trace id for one scattered query — they must not
            # overwrite each other (TraceStore.get scans by traceId)
            trace_store.get_store(
                "server", self._trace_capacity).record(
                f"{tc.trace_id}@{self.data_manager.instance_id}",
                tree, sql=sql_text, duration_ms=dur, slow=slow,
                extra={"traceId": tc.trace_id,
                       "instance": self.data_manager.instance_id})
            if slow:
                trace_store.log_slow_query(
                    "server", tc.trace_id, sql_text, dur,
                    self._slow_threshold_ms, table=table_name,
                    instance=self.data_manager.instance_id)
        from pinot_tpu.utils.metrics import get_registry
        get_registry("server").set_exemplar(
            "query_execution", {"table": table_name}, tc.trace_id)
        # the tree rides AFTER the result payload — append-compatible
        # with every reader (deserialize_results_ex picks it up)
        return payload + datatable.serialize_value(tree)

    def _execute_inner(self, table_name: str, sql_or_ctx,
                       segments: Optional[List[str]] = None,
                       extra_filter: Optional[str] = None,
                       query_id=None, timeout_ms: Optional[float] = None,
                       deadline: Optional[float] = None,
                       tenant: Optional[str] = None):
        """Returns serialized DataTable bytes. extra_filter (an expression
        string, e.g. the hybrid time-boundary predicate) is ANDed into the
        filter tree — the reference rewrites the BrokerRequest the same way.
        timeout_ms: REMAINING broker budget; the local deadline (plus a
        small grace for clock skew) cancels the segment loop
        cooperatively and answers with an errorCode-250 partial.
        deadline: ARRIVAL-anchored absolute deadline (the transport
        handler computes it when the request is read) — it wins over
        timeout_ms, which anchored here would silently extend the budget
        by however long the request waited in the scheduler queue."""
        from pinot_tpu.utils.metrics import get_registry
        metrics = get_registry("server")
        metrics.add_meter("queries", labels={"table": table_name})
        timer = metrics.time("query_execution", labels={"table": table_name})
        timer.__enter__()
        slo_t0 = time.perf_counter()
        from pinot_tpu.utils.accounting import charging
        qid = None if query_id is None else str(query_id)
        cancel_check = None
        slip = None
        if qid is not None:
            if deadline is not None:
                timeout_s = deadline - time.time()
            else:
                timeout_s = (float(timeout_ms) / 1000.0
                             + self.deadline_grace_s if timeout_ms else None)
            self.accountant.begin_query(qid, timeout_s)
            cancel_check = self.accountant.checker(qid)
            if self._accounting_enabled:
                slip = self.accountant.slip(qid)
        error = False
        try:
            fire("server.execute.before",
                 instance=self.data_manager.instance_id, table=table_name)
            ctx = (sql_or_ctx if isinstance(sql_or_ctx, QueryContext)
                   else QueryContext.from_sql(sql_or_ctx))
            from pinot_tpu.query.context import merge_extra_filter
            merge_extra_filter(ctx, extra_filter)
            if slip is not None:
                # attribution dimensions the per-(tenant, table, plan)
                # workload rollup keys on
                self.accountant.annotate(
                    qid, tenant=tenant or "", table=table_name,
                    plan_fingerprint=ctx.fingerprint())
            self._record_plan(table_name, ctx, sql_or_ctx, extra_filter)
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is None:
                return datatable.serialize_results(
                    [], [{"errorCode": errorcodes.TABLE_DOES_NOT_EXIST,
                          "message": f"table {table_name} not found"}])
            sdms = tdm.acquire_segments(segments)
            try:
                ex = QueryExecutor([s.segment for s in sdms],
                                   use_tpu=self.use_tpu,
                                   engine=self._shared_engine(),
                                   segment_cache=self.segment_cache,
                                   cancel_check=cancel_check)
                # the slip rides the thread-local for the execution scope:
                # engine staging (transfer bytes), the dispatch ring
                # (kernel ms, batch-split), and the tier-2 cache
                # (hit/miss bytes) all charge this query through it
                with charging(slip):
                    results, prune_stats = ex.execute_context(ctx)
                if slip is not None:
                    rows = sum(r.stats.num_docs_scanned for r in results)
                    entries = sum(r.stats.num_entries_scanned_in_filter
                                  + r.stats.num_entries_scanned_post_filter
                                  for r in results)
                    # bytes: dict-encoded scan entries are int32 ids —
                    # 4 bytes per entry is the storage-traffic cost
                    slip.add(rows_scanned=rows, bytes_scanned=4 * entries)
                return datatable.serialize_results(results,
                                                   extra_stats=prune_stats)
            finally:
                TableDataManager.release_all(sdms)
        except (QueryCancelledError, BrokerTimeoutError) as e:
            # late work is CANCELLED, not silently finished: drop any
            # half-built partials (merging them would risk double counts
            # against a hedged replica) and answer with the typed 250
            error = True
            metrics.add_meter("queries_killed", labels={"table": table_name})
            return _timeout_response(e)
        except Exception as e:  # noqa: BLE001 — server must answer, not die
            error = True
            metrics.add_meter("query_exceptions", labels={"table": table_name})
            return datatable.serialize_results(
                [], [{"errorCode": errorcodes.QUERY_EXECUTION,
                      "message": f"{type(e).__name__}: {e}"}])
        finally:
            if qid is not None:
                usage = self.accountant.finish_query(qid)
                if usage is not None and slip is not None:
                    # fold the finished query's bill into the
                    # per-(tenant, table, plan) workload rollup
                    from pinot_tpu.health.workload import get_workload
                    get_workload("server").record_usage(usage, error=error)
            timer.__exit__(None, None, None)
            if self._slo_p99_ms and (time.perf_counter() - slo_t0) \
                    * 1000.0 > self._slo_p99_ms:
                metrics.add_meter("slo_latency_bad",
                                  labels={"table": table_name})

    #: segments per streamed response frame
    STREAM_CHUNK_SEGMENTS = 4

    def execute_streaming(self, table_name: str, sql_or_ctx,
                          segments: Optional[List[str]] = None,
                          extra_filter: Optional[str] = None):
        """Per-block response frames for large results (ref
        GrpcQueryServer's streaming Submit + StreamingInstanceResponse
        PlanNode): a GENERATOR — each segment chunk executes and
        serializes lazily as the transport consumes it, so the server
        never materializes the full result and the first frame ships
        while later chunks still compute."""
        try:
            ctx = (sql_or_ctx if isinstance(sql_or_ctx, QueryContext)
                   else QueryContext.from_sql(sql_or_ctx))
            from pinot_tpu.query.context import merge_extra_filter
            merge_extra_filter(ctx, extra_filter)
            tdm = self.data_manager.table(table_name, create=False)
            if tdm is None:
                yield datatable.serialize_results(
                    [], [{"errorCode": errorcodes.TABLE_DOES_NOT_EXIST,
                          "message": f"table {table_name} not found"}])
                return
            sdms = tdm.acquire_segments(segments)
            try:
                chunk = self.STREAM_CHUNK_SEGMENTS
                segs = [s.segment for s in sdms]
                for i in range(0, max(len(segs), 1), chunk):
                    ex = QueryExecutor(segs[i:i + chunk],
                                       use_tpu=self.use_tpu,
                                       engine=self._shared_engine(),
                                       segment_cache=self.segment_cache)
                    results, prune_stats = ex.execute_context(ctx)
                    yield datatable.serialize_results(
                        results, extra_stats=prune_stats)
            finally:
                TableDataManager.release_all(sdms)
        except Exception as e:  # noqa: BLE001
            yield datatable.serialize_results(
                [], [{"errorCode": errorcodes.QUERY_EXECUTION,
                      "message": f"{type(e).__name__}: {e}"}])


class QueryServer:
    """Asyncio TCP server (the Netty QueryServer analog)."""

    def __init__(self, executor: ServerQueryExecutor, host: str = "127.0.0.1",
                 port: int = 0, num_threads: int = 8,
                 scheduler: str = "fcfs"):
        from pinot_tpu.server.admission import AdmissionController
        from pinot_tpu.server.scheduler import make_scheduler
        from pinot_tpu.utils.metrics import get_registry
        self.executor = executor
        self.host = host
        self.port = port
        #: pluggable query scheduler (ref QuerySchedulerFactory.java:45 —
        #: fcfs | priority | binary); owns the query worker threads
        self.scheduler = make_scheduler(
            scheduler, num_threads, metrics=get_registry("server"),
            labels={"instance": executor.data_manager.instance_id})
        self.scheduler.start()
        #: overload protection at the transport edge (server/admission.py):
        #: deadline-aware, memory-aware, tenant-weighted rejection BEFORE
        #: the scheduler queue; the scheduler's own bounded queue is the
        #: backstop for submissions racing the controller's estimate
        self.admission = AdmissionController.from_config(
            executor.config, num_threads=num_threads,
            tenant_weights_fn=self.scheduler.tenant_weights,
            memory_pressure_fn=executor.memory_pressure,
            metrics=get_registry("server"),
            labels={"instance": executor.data_manager.instance_id})
        self.scheduler.set_queue_limit(
            self.admission.queue_limit if self.admission.enabled else 0)
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                n = _LEN.unpack(hdr)[0]
                payload = await reader.readexactly(n)
                req = json.loads(payload)
                if "cancel" in req:
                    # out-of-band cancel (ref InstanceRequestHandler's
                    # CANCEL_QUERY): arrives on its OWN short-lived
                    # connection because the originating channel is
                    # blocked waiting for the very response being
                    # cancelled
                    ok = self.executor.cancel(req["cancel"])
                    ack = json.dumps({"cancelled": bool(ok)}).encode()
                    writer.write(_LEN.pack(len(ack)) + ack)
                    await writer.drain()
                    continue
                # REMAINING broker budget -> local absolute deadline; the
                # scheduler refuses to start work whose whole budget was
                # spent in its queue, the executor's cooperative checks
                # stop work that expires mid-scan
                timeout_ms = req.get("timeoutMs")
                deadline = (time.time() + float(timeout_ms) / 1000.0
                            + self.executor.deadline_grace_s
                            if timeout_ms else None)
                # -- admission: reject in O(1) BEFORE the scheduler when
                # the query cannot plausibly answer inside its budget
                # (queue full / deadline unservable / memory pressure /
                # shed priority class) — a typed 211 with a retry-after
                # hint, having consumed no worker thread
                rejection = self.admission.admit(
                    table=req.get("tableName", ""),
                    tenant=req.get("tenant"),
                    workload=req.get("workload", "primary"),
                    deadline=deadline)
                if rejection is not None:
                    resp = _overload_response(rejection)
                    writer.write(_LEN.pack(len(resp)) + resp)
                    if req.get("streaming"):
                        writer.write(_LEN.pack(0))  # EOS
                    await writer.drain()
                    continue
                if req.get("streaming"):
                    # per-block response stream (ref GrpcQueryServer.Submit
                    # server-stream): generator creation is cheap; EACH
                    # frame's execution is its own scheduler submission so
                    # priority/binary-workload accounting still throttles
                    # streaming work, and frames ship as they compute
                    gen = self.executor.execute_streaming(
                        req["tableName"], req["sql"], req.get("segments"),
                        req.get("extraFilter"))
                    while True:
                        ticket = self.admission.register()
                        try:
                            fut = self.scheduler.submit(
                                lambda g=gen, t=ticket:
                                t.run(lambda: next(g, None)),
                                table=req.get("tableName", ""),
                                workload=req.get("workload", "primary"),
                                deadline=deadline,
                                tenant=req.get("tenant"))
                        except ServerOverloadedError as e:
                            # the scheduler's bounded-queue backstop won
                            # the race against the admission estimate
                            ticket.release()
                            frame = _overload_response(e)
                            writer.write(_LEN.pack(len(frame)) + frame)
                            break
                        fut.add_done_callback(
                            lambda _f, t=ticket: t.release())
                        try:
                            frame = await asyncio.wrap_future(fut)
                        except (QueryCancelledError, BrokerTimeoutError) as e:
                            frame = _timeout_response(e)
                            writer.write(_LEN.pack(len(frame)) + frame)
                            frame = None
                        if frame is None:
                            break
                        writer.write(_LEN.pack(len(frame)) + frame)
                        await writer.drain()
                    writer.write(_LEN.pack(0))  # EOS
                    await writer.drain()
                    continue
                arrival = time.time()
                ticket = self.admission.register()
                try:
                    fut = self.scheduler.submit(
                        lambda r=req, d=deadline, a=arrival, t=ticket:
                        t.run(lambda: self.executor.execute(
                            r["tableName"], r["sql"], r.get("segments"),
                            r.get("extraFilter"),
                            query_id=r.get("queryId") or r.get("requestId"),
                            timeout_ms=r.get("timeoutMs"), deadline=d,
                            trace_ctx=r.get("traceContext"), arrival_s=a,
                            tenant=r.get("tenant"))),
                        table=req.get("tableName", ""),
                        workload=req.get("workload", "primary"),
                        deadline=deadline,
                        tenant=req.get("tenant"))
                except ServerOverloadedError as e:
                    ticket.release()
                    resp = _overload_response(e)
                    writer.write(_LEN.pack(len(resp)) + resp)
                    await writer.drain()
                    continue
                fut.add_done_callback(lambda _f, t=ticket: t.release())
                try:
                    resp = await asyncio.wrap_future(fut)
                except (QueryCancelledError, BrokerTimeoutError) as e:
                    # reap any cancel tombstone for this id NOW — the
                    # guard killed the query before execute()'s own
                    # begin/finish pair could run, so nothing else will
                    qid = req.get("queryId") or req.get("requestId")
                    if qid is not None:
                        self.executor.accountant.finish_query(str(qid))
                    resp = _timeout_response(e)
                writer.write(_LEN.pack(len(resp)) + resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def start(self) -> None:
        """Start serving on a background thread; sets self.port."""
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def main():
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
                self._started.set()
                async with self._server:
                    await self._server.serve_forever()

            try:
                loop.run_until_complete(main())
            except asyncio.CancelledError:
                pass
            finally:
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"query-server-{self.port}")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("query server failed to start")

    def stop(self) -> None:
        """Idempotent: a failover test (or ops) may stop a server that was
        already killed."""
        if self._loop is not None and not self._loop.is_closed():
            def shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()
            try:
                self._loop.call_soon_threadsafe(shutdown)
            except RuntimeError:
                pass  # loop closed between the check and the call
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.scheduler.stop()


class ServerConnection:
    """Broker-side channel POOL to one server (ref ServerChannels:65).

    The original single-socket channel held its lock for the whole
    request round trip, which silently serialized scatter concurrency
    to ONE in-flight request per (broker, server) pair — the server's
    scheduler queue (where admission control watches) could never form,
    and the real overload queue hid inside a broker-side lock nobody
    measures. Now each request takes its own socket: up to
    ``pool_size`` idle sockets are retained for reuse, an empty pool
    dials fresh, so per-server concurrency is bounded by the fan-out
    pool (the intended bound), not by channel serialization."""

    #: idle sockets retained per server (concurrency itself is bounded
    #: by the broker's fan-out pool, not by this)
    POOL_SIZE = 4

    def __init__(self, host: str, port: int,
                 pool_size: Optional[int] = None):
        self.host, self.port = host, port
        self._idle: List[socket.socket] = []
        self._lock = threading.Lock()
        self.pool_size = pool_size if pool_size is not None \
            else self.POOL_SIZE

    def _take(self) -> tuple:
        """(socket, was_pooled). A pooled socket may be stale (server
        restarted since); callers retry once on a fresh dial."""
        with self._lock:
            if self._idle:
                return self._idle.pop(), True
        return socket.create_connection((self.host, self.port),
                                        timeout=30), False

    def _give(self, sock: socket.socket) -> None:
        with self._lock:
            if len(self._idle) < self.pool_size:
                self._idle.append(sock)
                return
        _close_quietly(sock)

    def request(self, table_name: str, sql: str,
                segments: Optional[List[str]] = None,
                request_id: int = 0,
                extra_filter: Optional[str] = None,
                timeout_ms: Optional[float] = None,
                query_id=None, tenant: Optional[str] = None,
                trace_ctx: Optional[dict] = None) -> bytes:
        """timeout_ms: remaining query budget, shipped to the server AND
        used as this socket's read timeout (+grace) so a dead server
        can't pin a broker fan-out thread past the deadline. tenant:
        the weighted-fair scheduling group the server charges this
        query's wall time to (from TableConfig tenant tags). trace_ctx:
        the TraceContext wire dict — the server joins the trace and
        ships its span tree back in the response metadata."""
        payload = json.dumps({
            "requestId": request_id, "tableName": table_name, "sql": sql,
            "segments": segments, "extraFilter": extra_filter,
            "timeoutMs": timeout_ms, "tenant": tenant,
            "queryId": query_id, "traceContext": trace_ctx}).encode()
        sock, pooled = self._take()
        try:
            self._set_timeout(sock, timeout_ms)
            sock.sendall(_LEN.pack(len(payload)) + payload)
            resp = self._read_frame(sock)
        except socket.timeout:
            # a slow query, NOT a dead channel: retransmitting would run
            # it twice server-side; drop the socket and surface the
            # timeout (ref: the reference fails the query, the failure
            # detector handles the server)
            _close_quietly(sock)
            raise
        except ConnectionError:
            # one retry on a FRESH dial (ref channel re-establish) —
            # pooled sockets go stale across server restarts, and even
            # a fresh socket gets the one reconnect the old channel had
            _close_quietly(sock)
            sock = socket.create_connection((self.host, self.port),
                                            timeout=30)
            try:
                self._set_timeout(sock, timeout_ms)
                sock.sendall(_LEN.pack(len(payload)) + payload)
                resp = self._read_frame(sock)
            except (socket.timeout, ConnectionError):
                _close_quietly(sock)
                raise
        # return the (clean — full frame read) socket BEFORE the chaos
        # hook: an armed torn/error policy must not leak the socket
        self._give(sock)
        return self._fire_response(resp)

    def _fire_response(self, payload: bytes) -> bytes:
        """Chaos site on the response payload: torn bytes here exercise
        the broker's deserialize-failure -> retry path."""
        return fire("connection.request", payload=payload,
                    server=f"{self.host}:{self.port}")

    @staticmethod
    def _set_timeout(sock: socket.socket,
                     timeout_ms: Optional[float]) -> None:
        sock.settimeout(float(timeout_ms) / 1000.0 + _SOCKET_GRACE_S
                        if timeout_ms else 30.0)

    def cancel(self, query_id) -> bool:
        """Best-effort out-of-band cancel on a FRESH socket — the pooled
        channel is blocked waiting for the response being cancelled.
        Never raises: cancellation is advisory; the server's own deadline
        is the backstop."""
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=2.0) as sock:
                msg = json.dumps({"cancel": str(query_id)}).encode()
                sock.sendall(_LEN.pack(len(msg)) + msg)
                ack = json.loads(self._read_frame(sock))
                return bool(ack.get("cancelled"))
        except (OSError, ValueError):
            return False

    def request_streaming(self, table_name: str, sql: str,
                          segments: Optional[List[str]] = None,
                          request_id: int = 0,
                          extra_filter: Optional[str] = None):
        """Generator of per-block DataTable payloads until the server's
        zero-length EOS frame (ref GrpcQueryServer server-stream). The
        stream owns its socket exclusively — frames of one query cannot
        interleave with another request's."""
        payload = json.dumps({
            "requestId": request_id, "tableName": table_name, "sql": sql,
            "segments": segments, "extraFilter": extra_filter,
            "streaming": True}).encode()
        sock, _pooled = self._take()
        completed = False
        try:
            sock.sendall(_LEN.pack(len(payload)) + payload)
            while True:
                frame = self._read_frame(sock, allow_empty=True)
                if not frame:
                    completed = True
                    return  # EOS
                yield frame
        finally:
            if completed:
                self._give(sock)
            else:
                # consumer aborted (or the read failed) mid-stream:
                # unread frames would poison the next request on this
                # socket — drop it, the pool dials fresh
                _close_quietly(sock)

    @staticmethod
    def _read_frame(sock: socket.socket, allow_empty: bool = False) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = sock.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionError("server closed connection")
            hdr += chunk
        n = _LEN.unpack(hdr)[0]
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(min(1 << 20, n - len(buf)))
            if not chunk:
                raise ConnectionError("server closed connection mid-frame")
            buf += chunk
        return bytes(buf)

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for sock in idle:
            _close_quietly(sock)


def _close_quietly(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass
