"""Server query schedulers: FCFS, token-bucket priority, binary workload.

Reference parity: pinot-core query/scheduler/ —
FCFSQueryScheduler.java (default, straight pool),
PriorityScheduler.java + MultiLevelPriorityQueue/TokenSchedulerGroup
(per-table token buckets: groups spend tokens proportional to the wall
time their queries hold worker threads, refill every interval, and the
group with the most tokens runs next — a flooding table cannot starve a
light one), and BinaryWorkloadScheduler.java (secondary workloads confined
to a small thread share so primary traffic keeps dedicated capacity).
Selected by QuerySchedulerFactory (QuerySchedulerFactory.java:45-50); here
`make_scheduler(name)`.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Deque, Dict, Optional

from pinot_tpu.utils.accounting import (BrokerTimeoutError,
                                        ServerOverloadedError)


class QueryScheduler:
    """submit(fn, table=..., workload=..., deadline=...) -> Future running
    fn(). deadline is an absolute time.time() timestamp: work that is
    STILL QUEUED when its deadline passes must not occupy a worker thread
    — the future completes with BrokerTimeoutError instead (ref
    QueryScheduler.java's timeout handling around the query runners).

    Every scheduler's queue is BOUNDED when ``max_pending`` > 0 (wired
    from ``pinot.server.admission.queue.limit``): a submit past the
    bound raises :class:`ServerOverloadedError` instead of queueing work
    the deadline will kill anyway. This is the hard backstop under the
    policy-level admission controller (server/admission.py), which
    rejects earlier and with better reasons — the scheduler bound only
    fires when submissions race the controller's estimate."""

    #: bounded-queue backstop: > 0 = max queued (submitted, not yet
    #: picked up) submissions before submit() raises; 0 = unbounded
    #: (the pre-overload-protection behavior)
    max_pending = 0

    def set_queue_limit(self, n: int) -> None:
        self.max_pending = max(0, int(n))

    def pending_count(self) -> int:
        """Submissions queued but not yet picked up by a worker."""
        return 0

    # -- tenant weights (TokenPriorityScheduler overrides) -------------
    def tenant_weight(self, tenant: Optional[str]) -> float:
        return 1.0

    def tenant_weights(self) -> Dict[str, float]:
        """Known tenant -> weight map; empty for tenant-blind
        schedulers (admission then skips weighted shedding)."""
        return {}

    #: optional metrics hookup (attach_metrics): scheduler_inflight gauge
    #: tracks submitted-but-unfinished queries — with the dispatch ring
    #: downstream, queue wait HERE vs wait IN THE RING separates "server
    #: saturated" from "device saturated" when diagnosing tail latency
    _metrics = None
    _labels = None

    def attach_metrics(self, metrics, labels=None) -> "QueryScheduler":
        """Idempotent + re-attach-safe: the counter and its lock are
        created exactly once per instance. The old version rebuilt BOTH
        on every call — a re-attach while queries were in flight (role
        rebuild, tests) reset the unguarded counter AND swapped the
        lock object out from under concurrent done-callbacks, leaving
        the scheduler_inflight gauge negative forever (lock-discipline
        race found by the `locks` static analyzer)."""
        if not hasattr(self, "_mlock"):
            self._mlock = threading.Lock()
            # lint: unlocked(first-attach init on the constructing thread before the scheduler is shared; re-attaches skip)
            self._inflight = 0
        with self._mlock:
            self._metrics = metrics
            self._labels = labels
        return self

    def _track(self, fut: Future) -> Future:
        # lint: unlocked(reference snapshot; attach_metrics publishes the pair under the lock and never unsets it)
        m = self._metrics
        if m is None:
            return fut
        with self._mlock:
            self._inflight += 1
            m.set_gauge("scheduler_inflight", self._inflight,
                        labels=self._labels)

        def done(_f):
            with self._mlock:
                self._inflight -= 1
                m.set_gauge("scheduler_inflight", self._inflight,
                            labels=self._labels)

        fut.add_done_callback(done)
        return fut

    def submit(self, fn: Callable[[], bytes], table: str = "",
               workload: str = "primary",
               deadline: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """tenant: the weighted-fair accounting group the query's wall
        time is charged to (shipped by the broker from TableConfig
        tenant tags; None folds into the default tenant). Schedulers
        without tenant awareness accept and ignore it."""
        raise NotImplementedError

    # -- bounded-queue helper for pool-backed schedulers ----------------
    def _bounded(self, fn: Callable[[], bytes]) -> Callable[[], bytes]:
        """Count fn as queued from submit until pick-up and refuse at
        the bound. Pool-backed schedulers (FCFS, binary) call this with
        a ``self._qlock``/``self._queued`` pair initialized in their
        constructors; the token scheduler enforces the bound inline
        under its own condition lock instead."""
        if not self.max_pending:
            return fn
        with self._qlock:
            if self._queued >= self.max_pending:
                m = self._metrics
                if m is not None:
                    m.add_meter("scheduler_queue_rejected",
                                labels=self._labels)
                raise ServerOverloadedError(
                    f"scheduler queue full ({self._queued} pending >= "
                    f"limit {self.max_pending})")
            self._queued += 1

        def run():
            with self._qlock:
                self._queued -= 1
            return fn()
        return run

    @staticmethod
    def _guard(fn: Callable[[], bytes],
               deadline: Optional[float]) -> Callable[[], bytes]:
        """Wrap fn with a pick-up-time deadline check. The check runs on
        the worker thread at execution start, so a request that sat in
        the queue past its whole budget fails in O(1) instead of burning
        a thread on an answer the broker already abandoned."""
        if deadline is None:
            return fn

        def run():
            if time.time() > deadline:
                raise BrokerTimeoutError(
                    "query deadline expired before execution started")
            return fn()
        return run

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class FCFSQueryScheduler(QueryScheduler):
    """Ref FCFSQueryScheduler — a plain pool in arrival order."""

    def __init__(self, num_threads: int = 8):
        self.num_threads = num_threads
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="query-fcfs")
        self._qlock = threading.Lock()
        self._queued = 0

    def pending_count(self) -> int:
        with self._qlock:
            return self._queued

    def submit(self, fn, table: str = "", workload: str = "primary",
               deadline: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        run = self._bounded(self._guard(fn, deadline))
        return self._track(self._pool.submit(run))

    def stop(self) -> None:
        self._pool.shutdown(wait=False)


#: tenant label queries fold into when the broker ships none
DEFAULT_TENANT = "DefaultTenant"


class _Group:
    __slots__ = ("tokens", "pending", "last_refill")

    def __init__(self, tokens: float):
        self.tokens = tokens
        self.pending: Deque = deque()
        self.last_refill = time.monotonic()


class _TenantGroup(_Group):
    """One tenant's bucket + its per-table sub-groups. The tenant bucket
    gates WHICH tenant runs next (weighted-fair: refill and cap scale
    with the tenant's weight); the table buckets preserve the original
    per-table fairness INSIDE the tenant, so a tenant flooding through
    one table still can't starve its own other tables."""

    __slots__ = ("weight", "tables")

    def __init__(self, tokens: float, weight: float = 1.0):
        super().__init__(tokens * weight)
        self.weight = max(1e-6, float(weight))
        self.tables: Dict[str, _Group] = {}

    @property
    def pending_count(self) -> int:
        return sum(len(g.pending) for g in self.tables.values())


class TokenPriorityScheduler(QueryScheduler):
    """Ref PriorityScheduler + TokenSchedulerGroup, extended to two
    levels: per-TENANT weighted token buckets over per-table buckets.
    Workers serve the non-empty tenant with the most tokens, then that
    tenant's richest table group; a query's wall time is charged against
    BOTH its table and its tenant, so a flooding table cannot starve a
    light one and a flooding tenant degrades only itself (its refill is
    weight-bounded while other tenants' buckets stay full)."""

    def __init__(self, num_threads: int = 8,
                 tokens_per_interval: float = 100.0,
                 interval_s: float = 1.0):
        self.num_threads = num_threads
        self.tokens_per_interval = tokens_per_interval
        self.interval_s = interval_s
        self._tenants: Dict[str, _TenantGroup] = {}
        self._weights: Dict[str, float] = {}
        self._lock = threading.Condition()
        self._stopped = False
        self._threads = []
        #: queued-but-unpicked submissions across every tenant/table
        #: bucket (kept incrementally — the bound check must not walk
        #: all deques per submit)
        self._pending_total = 0

    def tenant_weight(self, tenant: Optional[str]) -> float:
        with self._lock:
            return self._weights.get(tenant or DEFAULT_TENANT, 1.0)

    def tenant_weights(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def pending_count(self) -> int:
        with self._lock:
            return self._pending_total

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Fed from TableConfig tenant weights (broker/controller push):
        a tenant with weight w refills (and caps) at w x the per-interval
        budget. Takes effect on the live bucket immediately."""
        with self._lock:
            self._weights[tenant] = float(weight)
            tg = self._tenants.get(tenant)
            if tg is not None:
                tg.weight = max(1e-6, float(weight))
                tg.tokens = min(tg.tokens,
                                self.tokens_per_interval * tg.weight)
            self._lock.notify_all()

    def start(self) -> None:
        for i in range(self.num_threads):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"query-prio-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._lock.notify_all()

    def submit(self, fn, table: str = "", workload: str = "primary",
               deadline: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        fut: Future = Future()
        tenant = tenant or DEFAULT_TENANT
        with self._lock:
            if self.max_pending and self._pending_total >= self.max_pending:
                if self._metrics is not None:
                    self._metrics.add_meter("scheduler_queue_rejected",
                                            labels=self._labels)
                raise ServerOverloadedError(
                    f"scheduler queue full ({self._pending_total} pending "
                    f">= limit {self.max_pending})")
            tg = self._tenants.get(tenant)
            if tg is None:
                tg = self._tenants[tenant] = _TenantGroup(
                    self.tokens_per_interval,
                    self._weights.get(tenant, 1.0))
            g = tg.tables.get(table)
            if g is None:
                g = tg.tables[table] = _Group(self.tokens_per_interval)
            g.pending.append((fut, self._guard(fn, deadline)))
            self._pending_total += 1
            self._lock.notify()
        return self._track(fut)

    # ------------------------------------------------------------------
    def _refill_locked(self, now: float) -> None:
        for tg in self._tenants.values():
            cap = self.tokens_per_interval * tg.weight
            intervals = (now - tg.last_refill) / self.interval_s
            if intervals >= 1.0:
                # decayed refill toward the per-interval budget
                # (ref TokenSchedulerGroup incrementTokens)
                tg.tokens = min(cap, tg.tokens + intervals * cap)
                tg.last_refill = now
            for g in tg.tables.values():
                intervals = (now - g.last_refill) / self.interval_s
                if intervals >= 1.0:
                    g.tokens = min(
                        self.tokens_per_interval,
                        g.tokens + intervals * self.tokens_per_interval)
                    g.last_refill = now

    def _pick_locked(self) -> Optional[tuple]:
        best_tenant = None
        for tg in self._tenants.values():
            if tg.pending_count == 0:
                continue
            if best_tenant is None or tg.tokens > best_tenant.tokens:
                best_tenant = tg
        if best_tenant is None:
            return None
        best = None
        for g in best_tenant.tables.values():
            if not g.pending:
                continue
            if best is None or g.tokens > best.tokens:
                best = g
        fut, fn = best.pending.popleft()
        self._pending_total -= 1
        return best_tenant, best, fut, fn

    def _worker(self) -> None:
        while True:
            with self._lock:
                while True:
                    if self._stopped:
                        return
                    self._refill_locked(time.monotonic())
                    picked = self._pick_locked()
                    if picked is not None:
                        break
                    self._lock.wait(timeout=0.1)
            tenant_group, group, fut, fn = picked
            if not fut.set_running_or_notify_cancel():
                continue
            t0 = time.monotonic()
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)
            finally:
                spent = (time.monotonic() - t0) / self.interval_s \
                    * self.tokens_per_interval
                with self._lock:
                    group.tokens -= spent
                    tenant_group.tokens -= spent
                    self._lock.notify()


class BinaryWorkloadScheduler(QueryScheduler):
    """Ref BinaryWorkloadScheduler: primary queries get the full pool;
    secondary workloads are confined to a bounded slice so they can never
    crowd out production traffic."""

    def __init__(self, num_threads: int = 8, secondary_threads: int = 1):
        self.num_threads = num_threads
        self._primary = ThreadPoolExecutor(
            max_workers=num_threads, thread_name_prefix="query-primary")
        self._secondary = ThreadPoolExecutor(
            max_workers=max(secondary_threads, 1),
            thread_name_prefix="query-secondary")
        self._qlock = threading.Lock()
        self._queued = 0

    def pending_count(self) -> int:
        with self._qlock:
            return self._queued

    def submit(self, fn, table: str = "", workload: str = "primary",
               deadline: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        pool = self._primary if workload != "secondary" else self._secondary
        run = self._bounded(self._guard(fn, deadline))
        return self._track(pool.submit(run))

    def stop(self) -> None:
        self._primary.shutdown(wait=False)
        self._secondary.shutdown(wait=False)


def make_scheduler(name: str = "fcfs", num_threads: int = 8,
                   metrics=None, labels=None, **kwargs) -> QueryScheduler:
    """Ref QuerySchedulerFactory.create (QuerySchedulerFactory.java:45)."""
    name = (name or "fcfs").lower()
    if name == "fcfs":
        sched: QueryScheduler = FCFSQueryScheduler(num_threads)
    elif name in ("priority", "token"):
        sched = TokenPriorityScheduler(num_threads, **kwargs)
    elif name in ("binary", "binary_workload", "binaryworkload"):
        sched = BinaryWorkloadScheduler(num_threads, **kwargs)
    else:
        raise ValueError(f"unknown scheduler {name!r}")
    if metrics is not None:
        sched.attach_metrics(metrics, labels)
    return sched
