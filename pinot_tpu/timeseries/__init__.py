"""Time-series query engine SPI + the builtin 'simpleql' language.

Reference parity: pinot-timeseries (pinot-timeseries-spi: TimeBuckets,
TimeSeriesBlock, BaseTimeSeriesPlanNode, TimeSeriesLogicalPlanner;
pinot-timeseries-planner; language plugins under
pinot-plugins/pinot-timeseries-lang, e.g. the m3ql pipe language).
Languages register through the plugin registry (kind 'timeseries_lang')
and plan into the shared node tree executed by engine.execute_plan.
"""
from pinot_tpu.timeseries.spi import (BaseTimeSeriesPlanNode,
                                      LeafTimeSeriesPlanNode,
                                      TimeBuckets, TimeSeries,
                                      TimeSeriesBlock,
                                      TimeSeriesAggregationNode,
                                      TimeSeriesTransformNode,
                                      get_language, register_language)
from pinot_tpu.timeseries.engine import execute_plan, query

__all__ = ["TimeBuckets", "TimeSeries", "TimeSeriesBlock",
           "BaseTimeSeriesPlanNode", "LeafTimeSeriesPlanNode",
           "TimeSeriesAggregationNode", "TimeSeriesTransformNode",
           "register_language", "get_language", "execute_plan", "query"]
