"""Time-series plan execution + the builtin 'simpleql' pipe language.

Reference parity: pinot-timeseries-planner executing the SPI plan tree
over the leaf engine, and pinot-plugins/pinot-timeseries-lang/
pinot-timeseries-m3ql's pipe syntax. The builtin language:

    fetch(table, metric, time_col, start, end, step)
      [ | where(<sql predicate>) ]
      [ | groupby(tag1, tag2) ]
      [ | sum() | avg() | min() | max() ]        # cross-series, drop tags
      [ | sum(tag) ... ]                          # cross-series, keep tags
      [ | keep_last_value() | scale(x) | rate() ] # per-series transforms
      [ | gapfill(c) | interpolate() ]            # NaN-bucket fills

Leaf fetches ride the regular query engine (SQL GROUP BY over the time
bucket + tags — device offload included when the engine supports the
shape), so the TSDB layer adds no second storage path.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.timeseries import gapfill
from pinot_tpu.timeseries.spi import (BaseTimeSeriesPlanNode,
                                      LeafTimeSeriesPlanNode, TimeBuckets,
                                      TimeSeries, TimeSeriesAggregationNode,
                                      TimeSeriesBlock,
                                      TimeSeriesTransformNode,
                                      register_language)
from pinot_tpu.utils.failpoints import fire


def execute_plan(node: BaseTimeSeriesPlanNode, executor) -> TimeSeriesBlock:
    """executor: a query executor with .execute(sql) (QueryExecutor or a
    broker handler) — the leaf bridge (ref LeafTimeSeriesPlanNode)."""
    if isinstance(node, LeafTimeSeriesPlanNode):
        return _execute_leaf(node, executor)
    if isinstance(node, TimeSeriesAggregationNode):
        return _aggregate(execute_plan(node.child, executor), node)
    if isinstance(node, TimeSeriesTransformNode):
        return _transform(execute_plan(node.child, executor), node)
    raise ValueError(f"unknown plan node {type(node).__name__}")


def _leaf_group_cap(executor) -> int:
    """The `pinot.timeseries.leaf.max.groups` knob: per-bucket group-row
    ceiling on one leaf fetch. Reads the executor's config when it
    carries one; otherwise a default PinotConfiguration (which still
    honors PINOT_TPU_* env overrides)."""
    cfg = getattr(executor, "config", None)
    if cfg is None:
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = PinotConfiguration()
    return cfg.get_int("pinot.timeseries.leaf.max.groups")


def _execute_leaf(node: LeafTimeSeriesPlanNode, executor) -> TimeSeriesBlock:
    fire("timeseries.leaf.fetch", table=node.table)
    b = node.buckets
    bucket_expr = (f"floor(({node.time_column} - {b.start}) / {b.step})")
    tags = list(node.group_by_tags)
    select = [bucket_expr] + tags + [
        f"{node.value_agg}({node.metric_column})"]
    where = (f"{node.time_column} >= {b.start} AND "
             f"{node.time_column} < {b.end}")
    if node.filter_sql:
        where += f" AND ({node.filter_sql})"
    group = ", ".join([bucket_expr] + tags)
    limit = b.count * _leaf_group_cap(executor)
    # fetch limit+1 so exactly-limit results are distinguishable from
    # truncation
    sql = (f"SELECT {', '.join(select)} FROM {node.table} "
           f"WHERE {where} GROUP BY {group} "
           f"LIMIT {limit + 1}")
    resp = executor.execute(sql)
    if getattr(resp, "exceptions", None):
        raise RuntimeError(f"leaf query failed: {resp.exceptions}")
    rows = resp.result_table.rows if hasattr(resp, "result_table") and \
        resp.result_table is not None else resp.rows
    if len(rows) > limit:
        # silent truncation would make downstream sums wrong — fail loud
        raise RuntimeError(
            f"leaf fetch hit the {limit}-group cap (too many tag "
            f"combinations); narrow the filter or group by fewer tags")
    series: Dict[Tuple, TimeSeries] = {}
    for row in rows:
        bucket = int(row[0])
        if not 0 <= bucket < b.count:
            continue
        tag_vals = row[1:1 + len(tags)]
        val = float(row[1 + len(tags)])
        key = tuple(tag_vals)
        s = series.get(key)
        if s is None:
            s = series[key] = TimeSeries(
                tags=dict(zip(tags, tag_vals)),
                values=np.full(b.count, np.nan))
        s.values[bucket] = val
    return TimeSeriesBlock(b, list(series.values()))


def _aggregate(block: TimeSeriesBlock,
               node: TimeSeriesAggregationNode) -> TimeSeriesBlock:
    if not block.series:
        return TimeSeriesBlock(block.buckets, [])
    # one scatter-accumulate over the whole [series, buckets] stack
    # (timeseries/gapfill.py) instead of a vstack per group
    uniq: Dict[Tuple, int] = {}
    gids = np.empty(len(block.series), np.int64)
    for i, s in enumerate(block.series):
        key = tuple((t, s.tags.get(t)) for t in node.by_tags)
        gids[i] = uniq.setdefault(key, len(uniq))
    stacked = np.vstack([s.values for s in block.series])
    vals = gapfill.aggregate(stacked, gids, len(uniq), node.agg)
    out = [TimeSeries(tags=dict(key), values=vals[g])
           for key, g in uniq.items()]
    return TimeSeriesBlock(block.buckets, out)


def _transform(block: TimeSeriesBlock,
               node: TimeSeriesTransformNode) -> TimeSeriesBlock:
    if not block.series:
        return TimeSeriesBlock(block.buckets, [])
    # every transform is one vectorized pass over the stacked grid
    stacked = np.vstack([s.values for s in block.series])
    if node.fn == "keep_last_value":
        stacked = gapfill.keep_last_value(stacked)
    elif node.fn == "gapfill":
        stacked = gapfill.gapfill(
            stacked, node.arg if node.arg is not None else 0.0)
    elif node.fn == "interpolate":
        stacked = gapfill.interpolate(stacked)
    elif node.fn == "scale":
        stacked = stacked * (node.arg if node.arg is not None else 1.0)
    elif node.fn == "rate":
        # per-unit first derivative over the bucket step
        stacked = gapfill.rate(stacked, block.buckets.step)
    else:
        raise ValueError(f"unknown transform {node.fn!r}")
    out = [TimeSeries(tags=dict(s.tags), values=stacked[i])
           for i, s in enumerate(block.series)]
    return TimeSeriesBlock(block.buckets, out)


# ---------------------------------------------------------------------------
# builtin 'simpleql' pipe language (the m3ql-plugin analog)
# ---------------------------------------------------------------------------

_STAGE_NAME_RX = re.compile(r"(\w+)\s*\(")


def _split_top(text: str, sep: str) -> List[str]:
    """Split on `sep` only at paren depth 0 — a where() predicate like
    `host = 'a(1)' AND floor(x / 2) > 1` must stay one stage, and its
    function-call commas one argument (the old `[^)]*` regex stopped at
    the FIRST close paren and broke both)."""
    parts: List[str] = []
    depth = 0
    cur: List[str] = []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _parse_stage(raw: str) -> Tuple[str, str]:
    """(name, argstr) from `name( ... )` with balanced parens."""
    s = raw.strip()
    m = _STAGE_NAME_RX.match(s)
    if m is None or not s.endswith(")"):
        raise ValueError(f"bad simpleql stage {raw!r}")
    inner = s[m.end():-1]
    depth = 0
    for ch in inner:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                break
    if depth != 0:
        raise ValueError(f"unbalanced parens in simpleql stage {raw!r}")
    return m.group(1), inner


def _parse_simpleql(text: str, _ctx=None) -> BaseTimeSeriesPlanNode:
    stages = [s.strip() for s in _split_top(text, "|")]
    name, argstr = _parse_stage(stages[0])
    if name != "fetch":
        raise ValueError("simpleql must start with fetch(table, metric, "
                         "time_col, start, end, step)")
    args = [a.strip() for a in _split_top(argstr, ",")]
    if len(args) != 6:
        raise ValueError("fetch needs 6 arguments")
    table, metric, time_col = args[0], args[1], args[2]
    start, end, step = int(args[3]), int(args[4]), int(args[5])
    count = max((end - start) // step, 1)
    buckets = TimeBuckets(start, step, count)
    group_tags: Tuple[str, ...] = ()
    filter_sql: Optional[str] = None
    plan_stages = []
    for raw in stages[1:]:
        name, argstr = _parse_stage(raw)
        args = [a.strip() for a in _split_top(argstr, ",") if a.strip()]
        if name == "where":
            # the predicate rides verbatim into the leaf SQL — commas
            # and parens inside it are the SQL's business, not ours
            filter_sql = argstr.strip()
        elif name == "groupby":
            group_tags = tuple(args)
        else:
            plan_stages.append((name, args))
    node: BaseTimeSeriesPlanNode = LeafTimeSeriesPlanNode(
        table=table, metric_column=metric, time_column=time_col,
        buckets=buckets, group_by_tags=group_tags, filter_sql=filter_sql)
    for name, args in plan_stages:
        if name in ("sum", "avg", "min", "max"):
            node = TimeSeriesAggregationNode(node, agg=name,
                                             by_tags=tuple(args))
        elif name in ("keep_last_value", "rate", "interpolate"):
            node = TimeSeriesTransformNode(node, fn=name)
        elif name == "scale":
            node = TimeSeriesTransformNode(
                node, fn="scale", arg=float(args[0]) if args else 1.0)
        elif name == "gapfill":
            node = TimeSeriesTransformNode(
                node, fn="gapfill", arg=float(args[0]) if args else 0.0)
        else:
            raise ValueError(f"unknown simpleql stage {name!r}")
    return node


register_language("simpleql", _parse_simpleql)


def query(text: str, executor, language: str = "simpleql"
          ) -> TimeSeriesBlock:
    """Parse + execute a time-series query (the TSDB entry point, ref
    the time-series broker request handler)."""
    from pinot_tpu.timeseries.spi import get_language
    planner = get_language(language)
    return execute_plan(planner(text, None), executor)
