"""Time-series plan execution + the builtin 'simpleql' pipe language.

Reference parity: pinot-timeseries-planner executing the SPI plan tree
over the leaf engine, and pinot-plugins/pinot-timeseries-lang/
pinot-timeseries-m3ql's pipe syntax. The builtin language:

    fetch(table, metric, time_col, start, end, step)
      [ | where(<sql predicate>) ]
      [ | groupby(tag1, tag2) ]
      [ | sum() | avg() | min() | max() ]        # cross-series, drop tags
      [ | sum(tag) ... ]                          # cross-series, keep tags
      [ | keep_last_value() | scale(x) | rate() ] # per-series transforms

Leaf fetches ride the regular query engine (SQL GROUP BY over the time
bucket + tags — device offload included when the engine supports the
shape), so the TSDB layer adds no second storage path.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.timeseries.spi import (BaseTimeSeriesPlanNode,
                                      LeafTimeSeriesPlanNode, TimeBuckets,
                                      TimeSeries, TimeSeriesAggregationNode,
                                      TimeSeriesBlock,
                                      TimeSeriesTransformNode,
                                      register_language)


def execute_plan(node: BaseTimeSeriesPlanNode, executor) -> TimeSeriesBlock:
    """executor: a query executor with .execute(sql) (QueryExecutor or a
    broker handler) — the leaf bridge (ref LeafTimeSeriesPlanNode)."""
    if isinstance(node, LeafTimeSeriesPlanNode):
        return _execute_leaf(node, executor)
    if isinstance(node, TimeSeriesAggregationNode):
        return _aggregate(execute_plan(node.child, executor), node)
    if isinstance(node, TimeSeriesTransformNode):
        return _transform(execute_plan(node.child, executor), node)
    raise ValueError(f"unknown plan node {type(node).__name__}")


def _execute_leaf(node: LeafTimeSeriesPlanNode, executor) -> TimeSeriesBlock:
    b = node.buckets
    bucket_expr = (f"floor(({node.time_column} - {b.start}) / {b.step})")
    tags = list(node.group_by_tags)
    select = [bucket_expr] + tags + [
        f"{node.value_agg}({node.metric_column})"]
    where = (f"{node.time_column} >= {b.start} AND "
             f"{node.time_column} < {b.end}")
    if node.filter_sql:
        where += f" AND ({node.filter_sql})"
    group = ", ".join([bucket_expr] + tags)
    limit = b.count * 10_000
    # fetch limit+1 so exactly-limit results are distinguishable from
    # truncation
    sql = (f"SELECT {', '.join(select)} FROM {node.table} "
           f"WHERE {where} GROUP BY {group} "
           f"LIMIT {limit + 1}")
    resp = executor.execute(sql)
    if getattr(resp, "exceptions", None):
        raise RuntimeError(f"leaf query failed: {resp.exceptions}")
    rows = resp.result_table.rows if hasattr(resp, "result_table") and \
        resp.result_table is not None else resp.rows
    if len(rows) > limit:
        # silent truncation would make downstream sums wrong — fail loud
        raise RuntimeError(
            f"leaf fetch hit the {limit}-group cap (too many tag "
            f"combinations); narrow the filter or group by fewer tags")
    series: Dict[Tuple, TimeSeries] = {}
    for row in rows:
        bucket = int(row[0])
        if not 0 <= bucket < b.count:
            continue
        tag_vals = row[1:1 + len(tags)]
        val = float(row[1 + len(tags)])
        key = tuple(tag_vals)
        s = series.get(key)
        if s is None:
            s = series[key] = TimeSeries(
                tags=dict(zip(tags, tag_vals)),
                values=np.full(b.count, np.nan))
        s.values[bucket] = val
    return TimeSeriesBlock(b, list(series.values()))


def _aggregate(block: TimeSeriesBlock,
               node: TimeSeriesAggregationNode) -> TimeSeriesBlock:
    groups: Dict[Tuple, List[TimeSeries]] = {}
    for s in block.series:
        key = tuple((t, s.tags.get(t)) for t in node.by_tags)
        groups.setdefault(key, []).append(s)
    out = []
    for key, members in groups.items():
        stack = np.vstack([m.values for m in members])
        with np.errstate(all="ignore"):
            if node.agg == "sum":
                vals = np.nansum(stack, axis=0)
                vals[np.all(np.isnan(stack), axis=0)] = np.nan
            elif node.agg == "avg":
                vals = np.nanmean(stack, axis=0)
            elif node.agg == "min":
                vals = np.nanmin(stack, axis=0)
            elif node.agg == "max":
                vals = np.nanmax(stack, axis=0)
            else:
                raise ValueError(f"unknown series agg {node.agg!r}")
        out.append(TimeSeries(tags=dict(key), values=vals))
    return TimeSeriesBlock(block.buckets, out)


def _transform(block: TimeSeriesBlock,
               node: TimeSeriesTransformNode) -> TimeSeriesBlock:
    out = []
    for s in block.series:
        v = s.values.copy()
        if node.fn == "keep_last_value":
            last = np.nan
            for i in range(len(v)):
                if np.isnan(v[i]):
                    v[i] = last
                else:
                    last = v[i]
        elif node.fn == "scale":
            v = v * (node.arg if node.arg is not None else 1.0)
        elif node.fn == "rate":
            # per-second first derivative over the bucket step
            dv = np.diff(v, prepend=np.nan)
            v = dv / block.buckets.step
        else:
            raise ValueError(f"unknown transform {node.fn!r}")
        out.append(TimeSeries(tags=dict(s.tags), values=v))
    return TimeSeriesBlock(block.buckets, out)


# ---------------------------------------------------------------------------
# builtin 'simpleql' pipe language (the m3ql-plugin analog)
# ---------------------------------------------------------------------------

_STAGE_RX = re.compile(r"(\w+)\s*\(([^)]*)\)\s*$")


def _parse_simpleql(text: str, _ctx=None) -> BaseTimeSeriesPlanNode:
    stages = [s.strip() for s in text.split("|")]
    m = _STAGE_RX.match(stages[0])
    if m is None or m.group(1) != "fetch":
        raise ValueError("simpleql must start with fetch(table, metric, "
                         "time_col, start, end, step)")
    args = [a.strip() for a in m.group(2).split(",")]
    if len(args) != 6:
        raise ValueError("fetch needs 6 arguments")
    table, metric, time_col = args[0], args[1], args[2]
    start, end, step = int(args[3]), int(args[4]), int(args[5])
    count = max((end - start) // step, 1)
    buckets = TimeBuckets(start, step, count)
    group_tags: Tuple[str, ...] = ()
    filter_sql: Optional[str] = None
    plan_stages = []
    for raw in stages[1:]:
        m = _STAGE_RX.match(raw)
        if m is None:
            raise ValueError(f"bad simpleql stage {raw!r}")
        name = m.group(1)
        args = [a.strip() for a in m.group(2).split(",") if a.strip()]
        if name == "where":
            filter_sql = m.group(2).strip()
        elif name == "groupby":
            group_tags = tuple(args)
        else:
            plan_stages.append((name, args))
    node: BaseTimeSeriesPlanNode = LeafTimeSeriesPlanNode(
        table=table, metric_column=metric, time_column=time_col,
        buckets=buckets, group_by_tags=group_tags, filter_sql=filter_sql)
    for name, args in plan_stages:
        if name in ("sum", "avg", "min", "max"):
            node = TimeSeriesAggregationNode(node, agg=name,
                                             by_tags=tuple(args))
        elif name in ("keep_last_value", "rate"):
            node = TimeSeriesTransformNode(node, fn=name)
        elif name == "scale":
            node = TimeSeriesTransformNode(
                node, fn="scale", arg=float(args[0]) if args else 1.0)
        else:
            raise ValueError(f"unknown simpleql stage {name!r}")
    return node


register_language("simpleql", _parse_simpleql)


def query(text: str, executor, language: str = "simpleql"
          ) -> TimeSeriesBlock:
    """Parse + execute a time-series query (the TSDB entry point, ref
    the time-series broker request handler)."""
    from pinot_tpu.timeseries.spi import get_language
    planner = get_language(language)
    return execute_plan(planner(text, None), executor)
