"""Vectorized series math on the stacked [series, buckets] grid.

Reference parity: pinot-timeseries post-leaf operators (keepLastValue /
interpolate / gapfill in the m3ql-style pipe stages). The engine used
to walk each TimeSeries with Python loops (`keep_last_value` was an
element-at-a-time scan) and re-vstack per aggregation group; every
transform here instead runs ONCE over the whole block stacked as a
single float64 [series, buckets] array — the same
one-big-dense-array discipline the device legs use, so a dashboard
with thousands of series costs a handful of numpy passes, not a
Python loop per cell.

NaN is the "no data in this bucket" marker throughout (matching
TimeSeries.values); every helper preserves that contract — all-NaN
stays NaN unless a fill explicitly replaces it.
"""
from __future__ import annotations

import numpy as np


def keep_last_value(arr: np.ndarray) -> np.ndarray:
    """Forward-fill NaN buckets per row from the last seen value;
    leading NaNs (nothing seen yet) stay NaN. The cummax-of-indices
    trick: each cell remembers the column of the latest valid value at
    or before it, then one gather fills the row."""
    a = np.array(arr, dtype=np.float64, copy=True)
    if a.size == 0:
        return a
    valid = ~np.isnan(a)
    col = np.arange(a.shape[1])[None, :]
    last = np.maximum.accumulate(np.where(valid, col, -1), axis=1)
    filled = np.take_along_axis(a, np.clip(last, 0, None), axis=1)
    return np.where(last >= 0, filled, np.nan)


def gapfill(arr: np.ndarray, value: float = 0.0) -> np.ndarray:
    """Replace every NaN bucket with a constant (m3ql gapfill/zero-fill
    — the 'treat missing as 0 before summing' dashboard idiom)."""
    a = np.array(arr, dtype=np.float64, copy=True)
    a[np.isnan(a)] = value
    return a


def interpolate(arr: np.ndarray) -> np.ndarray:
    """Linear interpolation across interior NaN runs per row; leading
    and trailing NaNs (no bracketing samples) stay NaN. prev/next valid
    indices come from a forward cummax and a reversed cummin — no
    Python loop over cells."""
    a = np.array(arr, dtype=np.float64, copy=True)
    if a.size == 0:
        return a
    B = a.shape[1]
    valid = ~np.isnan(a)
    col = np.arange(B)[None, :]
    prev = np.maximum.accumulate(np.where(valid, col, -1), axis=1)
    nxt = np.minimum.accumulate(
        np.where(valid, col, B)[:, ::-1], axis=1)[:, ::-1]
    interior = (~valid) & (prev >= 0) & (nxt < B)
    p = np.clip(prev, 0, B - 1)
    n = np.clip(nxt, 0, B - 1)
    pv = np.take_along_axis(a, p, axis=1)
    nv = np.take_along_axis(a, n, axis=1)
    frac = (col - p) / np.maximum(n - p, 1)
    return np.where(interior, pv + (nv - pv) * frac, a)


def rate(arr: np.ndarray, step: float) -> np.ndarray:
    """Per-unit first derivative over the bucket step (first bucket has
    no predecessor -> NaN), whole stack at once."""
    a = np.asarray(arr, dtype=np.float64)
    return np.diff(a, axis=1, prepend=np.nan) / step


def aggregate(stacked: np.ndarray, group_ids: np.ndarray,
              num_groups: int, agg: str) -> np.ndarray:
    """Cross-series aggregation: scatter-accumulate the [series,
    buckets] stack into [num_groups, buckets] planes in one pass
    (np.add.at / minimum.at / maximum.at), NaN-aware — a (group,
    bucket) cell with no data in ANY member series comes back NaN,
    matching the old per-group nansum/nanmean/nanmin/nanmax semantics
    exactly."""
    a = np.asarray(stacked, dtype=np.float64)
    valid = ~np.isnan(a)
    B = a.shape[1]
    cnt = np.zeros((num_groups, B))
    np.add.at(cnt, group_ids, valid.astype(np.float64))
    if agg in ("sum", "avg"):
        tot = np.zeros((num_groups, B))
        np.add.at(tot, group_ids, np.where(valid, a, 0.0))
        with np.errstate(invalid="ignore"):
            vals = tot / cnt if agg == "avg" else tot
        return np.where(cnt > 0, vals, np.nan)
    if agg == "min":
        acc = np.full((num_groups, B), np.inf)
        np.minimum.at(acc, group_ids, np.where(valid, a, np.inf))
        return np.where(cnt > 0, acc, np.nan)
    if agg == "max":
        acc = np.full((num_groups, B), -np.inf)
        np.maximum.at(acc, group_ids, np.where(valid, a, -np.inf))
        return np.where(cnt > 0, acc, np.nan)
    raise ValueError(f"unknown series agg {agg!r}")
