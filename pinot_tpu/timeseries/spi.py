"""Time-series SPI: buckets, blocks, plan nodes, language registry.

Reference parity: pinot-timeseries-spi tsdb/spi/ — TimeBuckets (aligned
bucket edges), TimeSeries/TimeSeriesBlock (per-tag-combination value
arrays over the buckets), BaseTimeSeriesPlanNode tree, and
TimeSeriesLogicalPlanner (one per query language, resolved by name —
the m3ql plugin seam). Languages register via the plugin registry
(utils/plugins.py, kind 'timeseries_lang').
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class TimeBuckets:
    """Aligned bucket grid [start, start+step, ...) (ref TimeBuckets)."""
    start: int          # inclusive, seconds (or any integral unit)
    step: int           # bucket width
    count: int

    @property
    def end(self) -> int:
        return self.start + self.step * self.count

    def edges(self) -> np.ndarray:
        return self.start + self.step * np.arange(self.count + 1)

    def centers(self) -> np.ndarray:
        return self.start + self.step * np.arange(self.count) + self.step // 2

    def index_of(self, ts: np.ndarray) -> np.ndarray:
        """Bucket index per timestamp; -1 = outside the grid."""
        idx = (np.asarray(ts) - self.start) // self.step
        return np.where((idx >= 0) & (idx < self.count), idx, -1).astype(
            np.int64)


@dataclass
class TimeSeries:
    """One tag combination's values over the buckets (NaN = no data)."""
    tags: Dict[str, Any]
    values: np.ndarray  # float64 [buckets.count]

    def tag_key(self) -> Tuple:
        return tuple(sorted(self.tags.items()))


@dataclass
class TimeSeriesBlock:
    """Ref TimeSeriesBlock: buckets + the series that survived the plan."""
    buckets: TimeBuckets
    series: List[TimeSeries] = field(default_factory=list)

    def by_tags(self) -> Dict[Tuple, TimeSeries]:
        return {s.tag_key(): s for s in self.series}


# ---------------------------------------------------------------------------
# plan nodes (ref BaseTimeSeriesPlanNode subclasses)
# ---------------------------------------------------------------------------

class BaseTimeSeriesPlanNode:
    children: Sequence["BaseTimeSeriesPlanNode"] = ()


@dataclass
class LeafTimeSeriesPlanNode(BaseTimeSeriesPlanNode):
    """Fetch: table scan -> bucketized series per tag combination (ref
    LeafTimeSeriesPlanNode bridging to the leaf query engine)."""
    table: str
    metric_column: str
    time_column: str
    buckets: TimeBuckets
    #: per-bucket accumulation within one series: sum|avg|min|max|count
    value_agg: str = "sum"
    group_by_tags: Tuple[str, ...] = ()
    filter_sql: Optional[str] = None
    children = ()


@dataclass
class TimeSeriesAggregationNode(BaseTimeSeriesPlanNode):
    """Cross-series aggregation, keeping only `by_tags` (ref m3ql's
    sum/avg by): sum|avg|min|max over series sharing the kept tags."""
    child: BaseTimeSeriesPlanNode
    agg: str = "sum"
    by_tags: Tuple[str, ...] = ()

    @property
    def children(self):
        return (self.child,)


@dataclass
class TimeSeriesTransformNode(BaseTimeSeriesPlanNode):
    """Per-series value transform (keepLastValue, scale, rate...)."""
    child: BaseTimeSeriesPlanNode
    fn: str = "keep_last_value"
    arg: Optional[float] = None

    @property
    def children(self):
        return (self.child,)


# ---------------------------------------------------------------------------
# language registry (ref TimeSeriesLogicalPlanner per language)
# ---------------------------------------------------------------------------

def register_language(name: str,
                      planner: Callable[[str, "object"], BaseTimeSeriesPlanNode]
                      ) -> None:
    """planner(query_text, context) -> plan tree."""
    from pinot_tpu.utils import plugins
    plugins.register("timeseries_lang", name, planner)


def get_language(name: str):
    from pinot_tpu.utils import plugins
    return plugins.get("timeseries_lang", name)
