"""CLI tools (ref pinot-tools: PinotAdministrator + quickstarts)."""
