"""pinot-tpu administrator CLI.

Reference parity: pinot-tools PinotAdministrator.java:93 — subcommand
front door (StartServer/StartBroker, AddTable, LaunchDataIngestionJob,
PostQuery, Quickstart...). Usage:

  python -m pinot_tpu.tools.admin Quickstart [--port 8099]
  python -m pinot_tpu.tools.admin LaunchDataIngestionJob \\
      --table table.json --schema schema.json \\
      --input 'data/*.csv' --output segments/
  python -m pinot_tpu.tools.admin StartCluster --table table.json \\
      --schema schema.json --segments 'segments/*' [--port 8099]
  python -m pinot_tpu.tools.admin PostQuery --broker localhost:8099 \\
      --query 'SELECT ...'
  python -m pinot_tpu.tools.admin CreateSegment ... (alias of ingestion job)
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time


def _load_table_schema(args):
    from pinot_tpu.models import Schema, TableConfig
    with open(args.table) as f:
        table_config = TableConfig.from_dict(json.load(f))
    with open(args.schema) as f:
        schema = Schema.from_dict(json.load(f))
    return table_config, schema


def cmd_ingest(args) -> int:
    from pinot_tpu.ingest.batch import IngestionJobSpec, run_ingestion_job
    table_config, schema = _load_table_schema(args)
    spec = IngestionJobSpec(
        input_pattern=args.input, output_dir=args.output,
        table_config=table_config, schema=schema,
        input_format=args.format,
        rows_per_segment=args.rows_per_segment)
    out = run_ingestion_job(spec)
    print(f"created {len(out)} segment(s):")
    for d in out:
        print(" ", d)
    return 0


def cmd_start_cluster(args) -> int:
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.segment.loader import load_segment
    table_config, schema = _load_table_schema(args)
    cluster = MiniCluster(num_servers=args.servers, use_tpu=not args.no_tpu)
    cluster.start(with_http=False)
    cluster.http = _http_on_port(cluster, args.port)
    cluster.add_table(table_config.name, table_config.table_type.value,
                      time_column=table_config.retention.time_column)
    n = 0
    for i, seg_dir in enumerate(sorted(glob.glob(args.segments))):
        if not os.path.isdir(seg_dir):
            continue
        cluster.add_segment(table_config.name, load_segment(seg_dir),
                            server_idx=i % args.servers,
                            table_type=table_config.table_type.value)
        n += 1
    print(f"serving {n} segment(s) of table {table_config.name!r} "
          f"on http://127.0.0.1:{cluster.http.port}/query/sql")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


def _http_on_port(cluster, port: int):
    from pinot_tpu.broker.http_api import BrokerHttpServer
    http = BrokerHttpServer(cluster.broker, port=port)
    http.start()
    return http


def cmd_post_query(args) -> int:
    import urllib.request
    req = urllib.request.Request(
        f"http://{args.broker}/query/sql",
        data=json.dumps({"sql": args.query}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as f:
        body = json.loads(f.read())
    print(json.dumps(body, indent=2, default=str))
    return 0


def cmd_quickstart(args) -> int:
    """Ref Quickstart.java — synthesize a demo table, serve it, run a
    sample query."""
    import numpy as np
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig, TableType)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    import tempfile

    schema = Schema("baseballStats", [
        FieldSpec("playerID", DataType.STRING),
        FieldSpec("teamID", DataType.STRING),
        FieldSpec("yearID", DataType.INT),
        FieldSpec("league", DataType.STRING),
        FieldSpec("runs", DataType.INT, FieldType.METRIC),
        FieldSpec("hits", DataType.INT, FieldType.METRIC),
        FieldSpec("homeRuns", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("baseballStats", TableType.OFFLINE)
    rng = np.random.default_rng(1887)
    n = args.rows
    cols = {
        "playerID": [f"player_{v}" for v in rng.integers(0, n // 20 + 1, n)],
        "teamID": [f"team_{v}" for v in rng.integers(0, 30, n)],
        "yearID": rng.integers(1871, 2024, n).astype(np.int32),
        "league": [("AL", "NL")[v] for v in rng.integers(0, 2, n)],
        "runs": rng.integers(0, 150, n).astype(np.int32),
        "hits": rng.integers(0, 250, n).astype(np.int32),
        "homeRuns": rng.integers(0, 60, n).astype(np.int32),
    }
    tmp = tempfile.mkdtemp(prefix="pinot_tpu_quickstart_")
    creator = SegmentCreator(tc, schema)
    segs = []
    per_seg = max(n // 4, 1)
    for i in range(4):
        sl = slice(i * per_seg, (i + 1) * per_seg if i < 3 else n)
        seg_cols = {k: (v[sl] if hasattr(v, "__getitem__") else v)
                    for k, v in cols.items()}
        d = os.path.join(tmp, f"seg_{i}")
        creator.build(seg_cols, d, f"baseballStats_{i}")
        segs.append(load_segment(d))

    cluster = MiniCluster(num_servers=2, use_tpu=not args.no_tpu)
    cluster.start(with_http=False)
    cluster.http = _http_on_port(cluster, args.port)
    cluster.add_table("baseballStats")
    for i, seg in enumerate(segs):
        cluster.add_segment("baseballStats", seg, server_idx=i % 2)
    print(f"quickstart cluster up: http://127.0.0.1:{cluster.http.port}/query/sql")
    for sql in (
            "SELECT COUNT(*) FROM baseballStats",
            "SELECT SUM(runs) FROM baseballStats",
            "SELECT league, SUM(homeRuns) FROM baseballStats "
            "GROUP BY league ORDER BY league LIMIT 10"):
        resp = cluster.query(sql)
        print(f"  {sql}\n    -> {resp.rows}")
    if args.exit_after_queries:
        cluster.stop()
        return 0
    print("Ctrl-C to stop")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="pinot-tpu-admin")
    sub = p.add_subparsers(dest="command", required=True)

    q = sub.add_parser("Quickstart", help="demo table + cluster + queries")
    q.add_argument("--port", type=int, default=8099)
    q.add_argument("--rows", type=int, default=100_000)
    q.add_argument("--no-tpu", action="store_true")
    q.add_argument("--exit-after-queries", action="store_true")
    q.set_defaults(fn=cmd_quickstart)

    for name in ("LaunchDataIngestionJob", "CreateSegment"):
        j = sub.add_parser(name, help="files -> segments")
        j.add_argument("--table", required=True)
        j.add_argument("--schema", required=True)
        j.add_argument("--input", required=True, help="input file glob")
        j.add_argument("--output", required=True)
        j.add_argument("--format", default=None)
        j.add_argument("--rows-per-segment", type=int, default=None)
        j.set_defaults(fn=cmd_ingest)

    s = sub.add_parser("StartCluster", help="serve segment dirs over HTTP")
    s.add_argument("--table", required=True)
    s.add_argument("--schema", required=True)
    s.add_argument("--segments", required=True, help="segment dir glob")
    s.add_argument("--servers", type=int, default=2)
    s.add_argument("--port", type=int, default=8099)
    s.add_argument("--no-tpu", action="store_true")
    s.set_defaults(fn=cmd_start_cluster)

    pq = sub.add_parser("PostQuery", help="POST sql to a broker")
    pq.add_argument("--broker", default="localhost:8099")
    pq.add_argument("--query", required=True)
    pq.set_defaults(fn=cmd_post_query)

    # separate-process roles (ref StartController/StartServer/StartBroker
    # admin subcommands; the coordination service replaces ZK/Helix)
    sc = sub.add_parser("StartController",
                        help="coordination service + maintenance loops")
    sc.add_argument("--state-dir", required=True)
    # default 0 = resolve through PinotConfiguration (catalog default 9000)
    sc.add_argument("--port", type=int, default=0)
    sc.add_argument("--deep-store", default=None,
                    help="deep-store base URI (e.g. file:///data/store)")
    sc.add_argument("--http-port", type=int, default=None,
                    help="controller REST API port (disabled when unset)")
    sc.add_argument("--config", default=None,
                    help="instance .properties file (PinotConfiguration)")
    sc.set_defaults(fn=cmd_start_controller)

    sst = sub.add_parser("StartStreamServer",
                         help="TCP stream broker (topic partition logs)")
    sst.add_argument("--port", type=int, default=0)
    sst.set_defaults(fn=cmd_start_stream_server)

    ss = sub.add_parser("StartServer", help="query server joined to a "
                                            "controller")
    ss.add_argument("--instance-id", required=True)
    ss.add_argument("--coordinator", required=True, help="host:port")
    ss.add_argument("--query-port", type=int, default=0)
    ss.add_argument("--tpu", action="store_true")
    ss.add_argument("--tenant", default=None,
                    help="tenant pool this server serves (registers the "
                         "tenant:<name> instance tag; tables tagged with "
                         "the same tenant assign only here)")
    ss.add_argument("--plugins-dir", default=None,
                    help="directory of plugin modules to load at startup")
    ss.add_argument("--config", default=None,
                    help="instance .properties file (PinotConfiguration)")
    ss.set_defaults(fn=cmd_start_server)

    scs = sub.add_parser("StartCacheServer",
                         help="shared L2 cache tier (remote cache role)")
    scs.add_argument("--port", type=int, default=0)
    scs.add_argument("--config", default=None,
                     help="instance .properties file (PinotConfiguration)")
    scs.set_defaults(fn=cmd_start_cache_server)

    sm = sub.add_parser("StartMinion", help="background-task worker "
                                            "joined to a controller")
    sm.add_argument("--instance-id", required=True)
    sm.add_argument("--coordinator", required=True, help="host:port")
    sm.add_argument("--task-types", default=None,
                    help="csv of task types to lease (default: all)")
    sm.add_argument("--work-dir", default=None,
                    help="sandbox dir for task builds (default: tempdir)")
    sm.add_argument("--config", default=None,
                    help="instance .properties file (PinotConfiguration)")
    sm.set_defaults(fn=cmd_start_minion)

    lt = sub.add_parser("ListTasks", help="list the controller task queue")
    lt.add_argument("--coordinator", required=True)
    lt.add_argument("--state", default=None,
                    help="filter: PENDING|LEASED|RUNNING|COMPLETED|"
                         "FAILED|CANCELLED")
    lt.set_defaults(fn=cmd_list_tasks)

    ct = sub.add_parser("CancelTask", help="cancel a queued/running task")
    ct.add_argument("--coordinator", required=True)
    ct.add_argument("--task-id", required=True)
    ct.set_defaults(fn=cmd_cancel_task)

    sb = sub.add_parser("StartBroker", help="HTTP broker joined to a "
                                            "controller")
    sb.add_argument("--coordinator", required=True, help="host:port")
    sb.add_argument("--http-port", type=int, default=0)
    sb.add_argument("--config", default=None,
                    help="instance .properties file (PinotConfiguration)")
    sb.set_defaults(fn=cmd_start_broker)

    at = sub.add_parser("AddTable", help="register table config + schema "
                                         "with the controller")
    at.add_argument("--coordinator", required=True)
    at.add_argument("--table", required=True, help="table config json file")
    at.add_argument("--schema", required=True, help="schema json file")
    at.set_defaults(fn=cmd_add_table)

    us = sub.add_parser("UploadSegment", help="assign a built segment dir")
    us.add_argument("--coordinator", required=True)
    us.add_argument("--table", required=True)
    us.add_argument("--segment-dir", required=True)
    us.add_argument("--table-type", default="OFFLINE")
    us.set_defaults(fn=cmd_upload_segment)

    args = p.parse_args(argv)
    return args.fn(args)


def cmd_start_controller(args) -> int:
    from pinot_tpu.cluster.roles import run_controller
    from pinot_tpu.utils.config import PinotConfiguration
    run_controller(args.state_dir, port=args.port,
                   deep_store_uri=args.deep_store,
                   http_port=getattr(args, "http_port", None),
                   config=PinotConfiguration(getattr(args, "config", None)))
    return 0


def cmd_start_stream_server(args) -> int:
    import time as _time

    from pinot_tpu.ingest.tcp_stream import StreamServer
    server = StreamServer(port=args.port)
    server.start()
    print(f"stream server listening on {server.address}", flush=True)
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_start_server(args) -> int:
    from pinot_tpu.cluster.roles import run_server
    from pinot_tpu.utils import plugins
    from pinot_tpu.utils.config import PinotConfiguration
    plugins.load_builtin_plugins()
    if getattr(args, "plugins_dir", None):
        loaded = plugins.load_plugin_dir(args.plugins_dir)
        print(f"loaded plugins: {loaded}", flush=True)
    cfg = PinotConfiguration(getattr(args, "config", None))
    run_server(args.instance_id, args.coordinator,
               query_port=args.query_port, use_tpu=args.tpu, config=cfg,
               tenant=getattr(args, "tenant", None))
    return 0


def cmd_start_cache_server(args) -> int:
    from pinot_tpu.cluster.roles import run_cache_server
    from pinot_tpu.utils.config import PinotConfiguration
    run_cache_server(port=args.port,
                     config=PinotConfiguration(getattr(args, "config", None)))
    return 0


def cmd_start_minion(args) -> int:
    from pinot_tpu.cluster.roles import run_minion
    from pinot_tpu.utils.config import PinotConfiguration
    task_types = None
    if getattr(args, "task_types", None):
        task_types = [t.strip() for t in args.task_types.split(",")
                      if t.strip()]
    run_minion(args.instance_id, args.coordinator, task_types=task_types,
               work_dir=getattr(args, "work_dir", None),
               config=PinotConfiguration(getattr(args, "config", None)))
    return 0


def cmd_list_tasks(args) -> int:
    from pinot_tpu.controller.coordination import CoordinationClient
    client = CoordinationClient(args.coordinator)
    r = client.request("task_list", state=getattr(args, "state", None))
    client.close()
    print(json.dumps(r["tasks"], indent=2, default=str))
    return 0


def cmd_cancel_task(args) -> int:
    from pinot_tpu.controller.coordination import CoordinationClient
    client = CoordinationClient(args.coordinator)
    r = client.request("task_cancel", task_id=args.task_id)
    client.close()
    if not r.get("ok"):
        print(f"no task {args.task_id}")
        return 1
    print(f"task {args.task_id}: {r['state']}")
    return 0


def cmd_start_broker(args) -> int:
    from pinot_tpu.cluster.roles import run_broker
    from pinot_tpu.utils.config import PinotConfiguration
    run_broker(args.coordinator, http_port=args.http_port,
               config=PinotConfiguration(getattr(args, "config", None)))
    return 0


def cmd_add_table(args) -> int:
    import json as _json

    from pinot_tpu.controller.coordination import CoordinationClient
    from pinot_tpu.models import Schema, TableConfig
    with open(args.table) as f:
        cfg = TableConfig.from_dict(_json.load(f))
    with open(args.schema) as f:
        schema = Schema.from_dict(_json.load(f))
    client = CoordinationClient(args.coordinator)
    client.add_table(cfg, schema)
    client.close()
    print(f"added table {cfg.name}")
    return 0


def cmd_upload_segment(args) -> int:
    from pinot_tpu.controller.coordination import CoordinationClient
    client = CoordinationClient(args.coordinator)
    r = client.upload_segment(args.table, args.segment_dir,
                              table_type=args.table_type)
    client.close()
    print(f"assigned to {r['segment']['instances']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
