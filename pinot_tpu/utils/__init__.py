"""Cross-cutting utilities: config, metrics, tracing, resource accounting.

Reference parity: pinot-spi's cross-cutting SPIs (SURVEY.md §2.1 row 1):
env/PinotConfiguration, metrics/PinotMetricsRegistry, trace/Tracing,
accounting/ThreadResourceUsageAccountant.
"""
