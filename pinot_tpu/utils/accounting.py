"""Per-query resource accounting + memory-pressure query killing.

Reference parity: pinot-spi accounting/ThreadResourceUsageAccountant +
the production PerQueryCPUMemResourceUsageAccountant
(pinot-core accounting/PerQueryCPUMemAccountantFactory.java:63) — threads
register the query they work for, per-thread CPU/allocations aggregate per
query, and a WatcherTask (:560) interrupts the most expensive queries
under heap pressure. Python twist: cooperative cancellation — executors
poll `check_cancelled()` in their loops (the reference's hot loops call
Tracing.ThreadAccountantOps.sample() the same way, DocIdSetOperator.java:70).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pinot_tpu.utils import errorcodes


class QueryCancelledError(RuntimeError):
    pass


class BrokerTimeoutError(RuntimeError):
    """A query exceeded its end-to-end deadline (ref QueryException
    EXECUTION_TIMEOUT_ERROR_CODE = 250). Raised broker-side when a server
    misses the budget, and server-side when the per-query deadline kills
    the segment loop — the response carries it as an errorCode-250 entry
    with partialResult=true, never a hang."""

    ERROR_CODE = errorcodes.EXECUTION_TIMEOUT


class ServerOverloadedError(RuntimeError):
    """The server REFUSED a query at admission instead of queueing it
    toward a deadline miss (ref "Overload Control for Scaling WeChat
    Microservices", SOSP 2018 — reject early, reject cheap). Raised by
    the bounded scheduler queues and the admission controller
    (server/admission.py); the transport answers a typed
    errorCode-211 entry whose message carries a ``retryAfterMs=`` drain
    hint, having consumed no execution resources. Distinct from the 250
    deadline miss by construction: a 250 burned budget, a 211 did not.
    """

    ERROR_CODE = errorcodes.SERVER_OVERLOADED

    def __init__(self, reason: str, retry_after_ms: float = 0.0):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


@dataclass
class QueryUsage:
    query_id: str
    start_time: float = field(default_factory=time.time)
    cpu_ns: int = 0
    bytes_allocated: int = 0
    cancelled: bool = False
    threads: int = 0
    #: absolute wall-clock deadline (time.time() domain); None = no budget
    deadline: Optional[float] = None
    # -- workload-accounting charges (PR 14): device + data-path costs
    # charged per query where PR 12 already measures them (dispatch ring,
    # residency odometer, tiered cache). Coalesced batch members split
    # the shared launch's kernel ms by doc share (dispatch.split_charge).
    device_kernel_ms: float = 0.0
    rows_scanned: int = 0
    bytes_scanned: int = 0
    transfer_bytes: int = 0
    cache_hit_bytes: int = 0
    cache_miss_bytes: int = 0
    # -- attribution dimensions (the WorkloadStats rollup key)
    tenant: str = ""
    table: str = ""
    plan_fingerprint: str = ""


class ChargeSlip:
    """Thread-safe cost-charging handle for ONE query: a (accountant,
    query id) pair whose :meth:`add` lands deltas on the query's
    :class:`QueryUsage` under the accountant lock. Captured on the
    request thread and handed across pool boundaries explicitly (the
    dispatch ring's launch/fetch pools, the engine staging pool) — the
    same discipline as tracing.SpanHandle, because thread-locals don't
    flow into pools."""

    __slots__ = ("_accountant", "query_id")

    def __init__(self, accountant: "ResourceAccountant", query_id: str):
        self._accountant = accountant
        self.query_id = query_id

    def add(self, **deltas) -> None:
        self._accountant.charge(self.query_id, **deltas)


_slip_tls = threading.local()


def current_slip() -> Optional[ChargeSlip]:
    """The calling thread's active charge slip (None when the request
    is not being accounted) — capture it where the request thread is
    live, pass it to pool work explicitly."""
    return getattr(_slip_tls, "slip", None)


@contextlib.contextmanager
def charging(slip: Optional[ChargeSlip]):
    """Make ``slip`` the thread's active charge slip for the scope —
    the accounting analog of a RequestTrace activation."""
    prev = getattr(_slip_tls, "slip", None)
    _slip_tls.slip = slip
    try:
        yield slip
    finally:
        _slip_tls.slip = prev


class ResourceAccountant:
    """Tracks per-query usage; kills the most expensive under pressure."""

    def __init__(self, memory_limit_bytes: Optional[int] = None,
                 query_timeout_s: Optional[float] = None):
        self._queries: Dict[str, QueryUsage] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.memory_limit_bytes = memory_limit_bytes
        self.query_timeout_s = query_timeout_s

    # -- per-query deadline registration -------------------------------------
    def begin_query(self, query_id: str,
                    timeout_s: Optional[float] = None) -> QueryUsage:
        """Register a query with an optional remaining-time budget. The
        deadline is enforced by checker() polls (cooperative, same
        discipline as check_cancelled) and by the watcher sweep."""
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                u = QueryUsage(query_id)
                self._queries[query_id] = u
            if timeout_s is not None:
                u.deadline = time.time() + timeout_s
            return u

    def check_query(self, query_id: str) -> None:
        """Cooperative cancel/deadline poll for an EXPLICIT query id — the
        executor's per-segment loop runs on pool threads that never called
        setup_worker, so the thread-local path can't see them."""
        with self._lock:
            u = self._queries.get(query_id)
        if u is None:
            return
        if u.cancelled:
            raise QueryCancelledError(f"query {query_id} cancelled")
        if u.deadline is not None and time.time() > u.deadline:
            u.cancelled = True
            raise BrokerTimeoutError(
                f"query {query_id} exceeded its deadline")

    def checker(self, query_id: str):
        """Zero-arg closure for hot loops: raises when the query is
        cancelled or past its deadline, else returns None."""
        return lambda: self.check_query(query_id)

    # -- workload charging (PR 14) -------------------------------------
    def slip(self, query_id: str) -> ChargeSlip:
        """A thread-safe charging handle for the query (see ChargeSlip)."""
        return ChargeSlip(self, query_id)

    def charge(self, query_id: str, *, device_kernel_ms: float = 0.0,
               rows_scanned: int = 0, bytes_scanned: int = 0,
               transfer_bytes: int = 0, cache_hit_bytes: int = 0,
               cache_miss_bytes: int = 0) -> None:
        """Accumulate workload-cost deltas on the query's usage record.
        Charges landing after finish_query (a fetch-pool straggler) drop
        silently — the usage record already left for the rollup."""
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                return
            u.device_kernel_ms += float(device_kernel_ms)
            u.rows_scanned += int(rows_scanned)
            u.bytes_scanned += int(bytes_scanned)
            u.transfer_bytes += int(transfer_bytes)
            u.cache_hit_bytes += int(cache_hit_bytes)
            u.cache_miss_bytes += int(cache_miss_bytes)

    def annotate(self, query_id: str, *, tenant: Optional[str] = None,
                 table: Optional[str] = None,
                 plan_fingerprint: Optional[str] = None) -> None:
        """Stamp the attribution dimensions (tenant, table, plan
        fingerprint) the WorkloadStats rollup keys on."""
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                return
            if tenant is not None:
                u.tenant = tenant
            if table is not None:
                u.table = table
            if plan_fingerprint is not None:
                u.plan_fingerprint = plan_fingerprint

    # -- per-thread registration (ref setupRunner / clear) -------------------
    def setup_worker(self, query_id: str) -> None:
        self._tls.query_id = query_id
        self._tls.cpu_start = time.thread_time_ns()
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                u = QueryUsage(query_id)
                self._queries[query_id] = u
            u.threads += 1

    def clear_worker(self) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        spent = time.thread_time_ns() - self._tls.cpu_start
        with self._lock:
            u = self._queries.get(qid)
            if u is not None:
                u.cpu_ns += spent
                u.threads -= 1
        self._tls.query_id = None

    def record_allocation(self, nbytes: int) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        with self._lock:
            u = self._queries.get(qid)
            if u is not None:
                u.bytes_allocated += nbytes

    # -- cooperative cancellation (ref sample() in hot loops) ----------------
    def check_cancelled(self) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        with self._lock:
            u = self._queries.get(qid)
        if u is None:
            return
        if u.cancelled:
            raise QueryCancelledError(f"query {qid} cancelled by accountant")
        if u.deadline is not None and time.time() > u.deadline:
            u.cancelled = True
            raise BrokerTimeoutError(f"query {qid} exceeded its deadline")

    #: cancel tombstones older than this are swept (a cancel whose query
    #: never arrives must not accumulate forever)
    TOMBSTONE_TTL_S = 300.0

    def cancel(self, query_id: str) -> bool:
        """Sticky: cancelling an id that has not begun yet leaves a
        cancelled TOMBSTONE, so a request still sitting in the scheduler
        queue (its begin_query hasn't run) dies at its first cooperative
        check instead of executing in full — the hedge-loser case.
        finish_query reaps it after that run; stale tombstones for
        requests that never arrive are swept here by age."""
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                now = time.time()
                for qid in [qid for qid, e in self._queries.items()
                            if e.cancelled and e.threads == 0
                            and now - e.start_time > self.TOMBSTONE_TTL_S]:
                    del self._queries[qid]
                u = QueryUsage(query_id)
                self._queries[query_id] = u
            u.cancelled = True
            return True

    def finish_query(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            return self._queries.pop(query_id, None)

    def usage(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            return self._queries.get(query_id)

    # -- watcher (ref WatcherTask) ------------------------------------------
    def watch_once(self, rss_bytes: Optional[int] = None) -> List[str]:
        """One watcher sweep: kill the most expensive query when over the
        memory limit, and any query over the timeout. Returns killed ids."""
        killed: List[str] = []
        now = time.time()
        with self._lock:
            live = [u for u in self._queries.values() if not u.cancelled]
            for u in live:
                if u.deadline is not None and now > u.deadline:
                    u.cancelled = True
                    killed.append(u.query_id)
            live = [u for u in live if not u.cancelled]
            if self.query_timeout_s is not None:
                for u in live:
                    if now - u.start_time > self.query_timeout_s:
                        u.cancelled = True
                        killed.append(u.query_id)
            if self.memory_limit_bytes is not None:
                rss = rss_bytes if rss_bytes is not None else _rss_bytes()
                if rss is not None and rss > self.memory_limit_bytes:
                    live = [u for u in live if not u.cancelled]
                    if live:
                        worst = max(live, key=lambda u: u.bytes_allocated)
                        worst.cancelled = True
                        killed.append(worst.query_id)
        return killed

    def start_watcher(self, interval_s: float = 1.0) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                self.watch_once()

        threading.Thread(target=loop, daemon=True,
                         name="accountant-watcher").start()
        return stop


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None
