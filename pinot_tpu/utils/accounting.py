"""Per-query resource accounting + memory-pressure query killing.

Reference parity: pinot-spi accounting/ThreadResourceUsageAccountant +
the production PerQueryCPUMemResourceUsageAccountant
(pinot-core accounting/PerQueryCPUMemAccountantFactory.java:63) — threads
register the query they work for, per-thread CPU/allocations aggregate per
query, and a WatcherTask (:560) interrupts the most expensive queries
under heap pressure. Python twist: cooperative cancellation — executors
poll `check_cancelled()` in their loops (the reference's hot loops call
Tracing.ThreadAccountantOps.sample() the same way, DocIdSetOperator.java:70).
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class QueryCancelledError(RuntimeError):
    pass


@dataclass
class QueryUsage:
    query_id: str
    start_time: float = field(default_factory=time.time)
    cpu_ns: int = 0
    bytes_allocated: int = 0
    cancelled: bool = False
    threads: int = 0


class ResourceAccountant:
    """Tracks per-query usage; kills the most expensive under pressure."""

    def __init__(self, memory_limit_bytes: Optional[int] = None,
                 query_timeout_s: Optional[float] = None):
        self._queries: Dict[str, QueryUsage] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.memory_limit_bytes = memory_limit_bytes
        self.query_timeout_s = query_timeout_s

    # -- per-thread registration (ref setupRunner / clear) -------------------
    def setup_worker(self, query_id: str) -> None:
        self._tls.query_id = query_id
        self._tls.cpu_start = time.thread_time_ns()
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                u = QueryUsage(query_id)
                self._queries[query_id] = u
            u.threads += 1

    def clear_worker(self) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        spent = time.thread_time_ns() - self._tls.cpu_start
        with self._lock:
            u = self._queries.get(qid)
            if u is not None:
                u.cpu_ns += spent
                u.threads -= 1
        self._tls.query_id = None

    def record_allocation(self, nbytes: int) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        with self._lock:
            u = self._queries.get(qid)
            if u is not None:
                u.bytes_allocated += nbytes

    # -- cooperative cancellation (ref sample() in hot loops) ----------------
    def check_cancelled(self) -> None:
        qid = getattr(self._tls, "query_id", None)
        if qid is None:
            return
        with self._lock:
            u = self._queries.get(qid)
        if u is not None and u.cancelled:
            raise QueryCancelledError(f"query {qid} cancelled by accountant")

    def cancel(self, query_id: str) -> bool:
        with self._lock:
            u = self._queries.get(query_id)
            if u is None:
                return False
            u.cancelled = True
            return True

    def finish_query(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            return self._queries.pop(query_id, None)

    def usage(self, query_id: str) -> Optional[QueryUsage]:
        with self._lock:
            return self._queries.get(query_id)

    # -- watcher (ref WatcherTask) ------------------------------------------
    def watch_once(self, rss_bytes: Optional[int] = None) -> List[str]:
        """One watcher sweep: kill the most expensive query when over the
        memory limit, and any query over the timeout. Returns killed ids."""
        killed: List[str] = []
        now = time.time()
        with self._lock:
            live = [u for u in self._queries.values() if not u.cancelled]
            if self.query_timeout_s is not None:
                for u in live:
                    if now - u.start_time > self.query_timeout_s:
                        u.cancelled = True
                        killed.append(u.query_id)
            if self.memory_limit_bytes is not None:
                rss = rss_bytes if rss_bytes is not None else _rss_bytes()
                if rss is not None and rss > self.memory_limit_bytes:
                    live = [u for u in live if not u.cancelled]
                    if live:
                        worst = max(live, key=lambda u: u.bytes_allocated)
                        worst.cancelled = True
                        killed.append(worst.query_id)
        return killed

    def start_watcher(self, interval_s: float = 1.0) -> threading.Event:
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                self.watch_once()

        threading.Thread(target=loop, daemon=True,
                         name="accountant-watcher").start()
        return stop


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None
