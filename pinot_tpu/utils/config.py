"""Instance configuration: layered properties + env overrides.

Reference parity: pinot-spi env/PinotConfiguration.java (commons-config
over properties files with relaxed env-var overrides) + the
CommonConstants key catalog (utils/CommonConstants.java — all config
keys in one place). Precedence, highest first:

  1. explicit overrides passed to the constructor
  2. environment variables: `pinot.server.query.port` reads
     `PINOT_TPU_SERVER_QUERY_PORT` (relaxed upper-snake mapping)
  3. a java-style .properties file (key=value, '#' comments)
  4. catalog defaults (KEYS below)
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

ENV_PREFIX = "PINOT_TPU_"

#: the CommonConstants analog — every tunable in one catalog with its
#: default (subsystems read through a PinotConfiguration, not os.environ)
KEYS: Dict[str, Any] = {
    "pinot.server.query.port": 0,
    "pinot.server.query.num.threads": 8,
    "pinot.server.query.scheduler": "fcfs",     # fcfs | priority | binary
    "pinot.server.stream.chunk.segments": 4,
    # concurrent-query dispatch pipeline (ops/dispatch.py):
    # mode 'pipelined' = dispatch ring + shared-plan micro-batching +
    # staging/compute overlap; 'serialized' reproduces the pre-ring
    # inline dispatch (A/B baseline + escape hatch)
    "pinot.server.dispatch.mode": "pipelined",
    "pinot.server.dispatch.ring.size": 64,      # bounded launch queue
    # micro-batch coalescing: fingerprint-equal concurrent queries merge
    # into one launch within this window (only waited when >1 caller is
    # active), capped at batch.max per launch. 'auto' sizes the window
    # from an EWMA of observed caller inter-arrival times, clamped to
    # [0.5x, 4x] of the static default below — bursty fleets wait just
    # long enough for their peers, lone callers converge to the floor
    "pinot.server.dispatch.batch.window.ms": 2.0,
    "pinot.server.dispatch.batch.max": 16,
    # cross-table shape-bucketed batching (the unified kernel factory,
    # ops/kernels.py): queries coalesce on (plan fingerprint, shape
    # bucket) — padded S/D pow2 buckets + staged-array shape signature —
    # so same-plan queries over DIFFERENT tables/partitions share one
    # launch (column blocks stack along a leading batch axis). Off =
    # PR-4 behavior (identical segment batch only). doc.bucket.max caps
    # the doc bucket eligible for cross-table stacking: above it, a
    # stacked [B, S, D] copy would dominate HBM, so such launches keep
    # the same-batch (broadcast-only) key.
    "pinot.server.dispatch.batch.cross.table": True,
    "pinot.server.dispatch.doc.bucket.max": 1 << 20,
    # HBM memory tiers (ops/engine.py + ops/residency.py):
    # .hbm.cache.bytes bounds the ASSEMBLED [S, D] block cache;
    # .hbm.resident.* bounds the per-(segment, column) resident-row tier
    # that survives batch recomposition (misses assemble on-device).
    # Admission is TinyLFU-style: when full, a candidate row must be
    # more frequent than the LRU victim to be retained (warmup-seeded
    # rows bypass the duel); .admission.sample is the frequency aging
    # window (counters halve when it fills).
    "pinot.server.hbm.cache.bytes": 8 << 30,
    "pinot.server.hbm.resident.enabled": True,
    "pinot.server.hbm.resident.bytes": 6 << 30,
    "pinot.server.hbm.admission.enabled": True,
    "pinot.server.hbm.admission.sample": 4096,
    "pinot.server.host.row.cache.bytes": 16 << 30,
    # collective broker merge (ops/collective.py): on a multi-chip mesh
    # the cross-segment partial fold runs as ONE on-device collective;
    # False is the escape hatch back to the host IndexedTable fold
    "pinot.server.mesh.collective.merge": True,
    # star-tree device leg (ops/startree_device.py): fitted aggregations
    # answer from pre-agg records through the kernel factory; .hbm.resident
    # admits the pre-agg pseudo-columns into the resident-row tier
    "pinot.server.startree.enabled": True,
    "pinot.server.startree.hbm.resident": True,
    # CLP log-column LIKE/regex pushdown (ops/clp_device.py): patterns
    # compile to logtype LUTs + variable-slot conditions evaluated as
    # device filter leaves; .hbm.resident admits the logtype-id/var-slot
    # pseudo-columns into the resident-row tier
    "pinot.server.clp.enabled": True,
    "pinot.server.clp.hbm.resident": True,
    # vector-similarity device leg (ops/vector_device.py): ANN top-K as
    # a batched matmul over staged vector blocks; .hbm.resident admits
    # the __vec__ pseudo-columns into the resident-row tier
    "pinot.server.vector.enabled": True,
    "pinot.server.vector.hbm.resident": True,
    # time-series device leg (ops/timeseries_device.py): fuse
    # floor((t-start)/step) into the group-by kernel's key instead of
    # falling back to the host expression path
    "pinot.server.timeseries.bucket.enabled": True,
    # time-series leaf fetch cap: a leaf SQL may return at most
    # count * this many group rows before the engine fails loud
    # (silent truncation would corrupt downstream sums)
    "pinot.timeseries.leaf.max.groups": 10_000,
    "pinot.server.segment.cache.enabled": True,   # tier-2 partial cache
    "pinot.server.segment.cache.bytes": 256 << 20,
    "pinot.server.segment.cache.ttl.seconds": 300.0,
    # tier-2 backend: local (process-private L1) | tiered (L1 + shared
    # remote L2 at .remote.address — a cache-server role instance)
    "pinot.server.segment.cache.backend": "local",
    "pinot.server.segment.cache.remote.address": "127.0.0.1:9600",
    # warmup: replay the recent-plan fingerprint log against freshly
    # loaded immutable segments BEFORE they serve queries
    "pinot.server.segment.warmup.enabled": True,
    "pinot.server.segment.warmup.max.plans": 32,
    "pinot.server.segment.warmup.log.plans.per.table": 64,
    # fingerprint-log journal: persist the warmup plan log so a restarted
    # server warms from history, not an empty log ("" = in-memory only)
    "pinot.server.segment.warmup.journal.dir": "",
    "pinot.server.segment.warmup.journal.max.bytes": 1 << 20,
    # server-side grace added to the broker-shipped remaining budget
    # before the local deadline trips (absorbs clock skew + queue jitter)
    "pinot.server.query.deadline.grace.ms": 50,
    # -- server admission control (server/admission.py) -----------------
    # Overload protection at the transport edge: a query is REJECTED
    # with a typed errorCode-211 (+ retryAfterMs hint) instead of
    # queueing toward a deadline miss when (a) the scheduler's bounded
    # queue is full (.queue.limit, also enforced inside the schedulers
    # as a backstop; 0 = unbounded), (b) its remaining deadline budget
    # is below the EWMA-estimated queue wait + execution time
    # (.exec.ewma.alpha smooths the estimates), (c) memory/HBM pressure
    # (residency-tier + realtime-ingest bytes vs their budgets) is at/
    # over .memory.threshold, or (d) the queue is past .shed.start
    # occupancy and the query's tenant weight ranks below the
    # occupancy-scaled cutoff (lowest-priority tenants shed first,
    # DAGOR-style).
    "pinot.server.admission.enabled": True,
    "pinot.server.admission.queue.limit": 128,
    "pinot.server.admission.shed.start": 0.5,
    "pinot.server.admission.memory.threshold": 0.95,
    "pinot.server.admission.exec.ewma.alpha": 0.2,
    # realtime ingestion backpressure (ingest/realtime_manager.py):
    # .memory.bytes bounds one partition consumer's mutable bytes plus
    # sealed-segments-awaiting-build bytes — approaching the budget
    # shrinks fetch batches adaptively, reaching it PAUSES the consumer
    # (0 = unbounded, the pre-backpressure behavior). .lag.pause.ms
    # bounds how far a paused partition may fall behind: past it, the
    # manager sheds memory by force-sealing the mutable into the build
    # pipeline instead of pausing indefinitely (0 = no ceiling).
    # .fetch.max.rows caps one fetch's messages (the adaptive ceiling).
    "pinot.server.ingest.memory.bytes": 0,
    "pinot.server.ingest.lag.pause.ms": 0.0,
    "pinot.server.ingest.fetch.max.rows": 10_000,
    "pinot.broker.http.port": 8099,
    "pinot.broker.fanout.threads": 16,
    "pinot.broker.adaptive.selector": "hybrid",  # latency|inflight|hybrid
    # end-to-end query budget (ref CommonConstants BROKER_TIMEOUT_MS):
    # OPTION(timeoutMs=...) > table override > this default. The broker
    # ships the REMAINING budget to servers, waits deadline-derived
    # times, and cancels still-pending server work on expiry.
    "pinot.broker.timeout.ms": 60000,
    # hedged scatter (speculative retry, "The Tail at Scale"): after an
    # adaptive delay — p95 over the selector's pooled per-server latency
    # reservoirs (true per-request tails), clamped to [delay.min,
    # delay.max] — re-issue still-pending plan entries on a different
    # healthy replica and keep the first clean response. Off by default:
    # it doubles worst-case fan-out.
    "pinot.broker.hedge.enabled": False,
    "pinot.broker.hedge.delay.min.ms": 25,
    "pinot.broker.hedge.delay.max.ms": 1000,
    # -- broker retry budget (broker/adaptive.py RetryBudget) -----------
    # Finagle-style per-table retry budget so failures and overload
    # rejections cannot amplify into retry storms: every clean primary
    # response DEPOSITS .ratio tokens (capped at .cap), every retry or
    # hedge WITHDRAWS one; a table starts with .min tokens so a cold
    # broker can still salvage the odd failure. Exhausted budget means
    # the failure surfaces as a typed partial instead of re-offering
    # the load that is sinking the fleet.
    "pinot.broker.retry.budget.enabled": True,
    "pinot.broker.retry.budget.ratio": 0.2,
    "pinot.broker.retry.budget.min": 3.0,
    "pinot.broker.retry.budget.cap": 10.0,
    # -- brownout mode (health/brownout.py) -----------------------------
    # Graceful degradation closing the SLO observe->act loop: sustained
    # SLO burn (the PR-14 watchdog) or sustained shed rate (admission
    # rejections + overload partials per query over the short window at/
    # over .shed.rate.threshold) climbs a per-role degradation ladder —
    # disable hedging -> serve result-cache entries up to
    # .stale.ttl.grace.seconds past TTL with staleResult=true -> shrink
    # dispatch batch windows by .batch.window.scale -> shed secondary
    # workloads at admission. Hysteresis: one rung up only after the
    # signal holds .up.seconds, one rung down only after it stays clear
    # .down.seconds (exit threshold is half the entry threshold).
    "pinot.brownout.enabled": True,
    "pinot.brownout.shed.rate.threshold": 0.1,
    "pinot.brownout.up.seconds": 10.0,
    "pinot.brownout.down.seconds": 30.0,
    "pinot.brownout.batch.window.scale": 0.25,
    "pinot.brownout.stale.ttl.grace.seconds": 120.0,
    # multi-stage engine budget: OPTION(timeoutMs=...) > this knob >
    # pinot.broker.timeout.ms — the budget travels in every stage and is
    # enforced on every mailbox wait ("" = inherit the broker default)
    "pinot.broker.mse.timeout.ms": None,
    # MSE stage hedging ("The Tail at Scale", MSE edition): after an
    # adaptive delay — a quantile of the dispatcher's pooled per-worker
    # STAGE-latency reservoirs, clamped to [delay.min, delay.max] — a
    # still-running leaf stage instance is re-issued on another alive
    # worker holding the same local segment view; the first attempt to
    # finish CLEAN claims the (query, stage, worker-slot) output and
    # sends, the loser is cancelled and sends nothing (exactly one EOS
    # per sender slot — no double-merge by construction). Off by
    # default: it doubles worst-case leaf fan-out.
    "pinot.broker.mse.hedge.enabled": False,
    "pinot.broker.mse.hedge.delay.min.ms": 25,
    "pinot.broker.mse.hedge.delay.max.ms": 1000,
    "pinot.broker.mse.hedge.quantile": 0.95,
    # pipelined intermediate stages: senders chunk stage output into
    # <= chunk.rows frames and fold-capable receivers (aggregate /
    # final_agg over a receive) merge frames AS THEY ARRIVE instead of
    # barriering on receive_all — upstream compute overlaps downstream
    # merge, and fan-in no longer serializes on the slowest sender.
    # watermark.rows bounds the decoded-but-unfolded buffer (the fold
    # granularity); enabled=False restores the full-barrier receive.
    "pinot.server.mse.pipeline.enabled": True,
    "pinot.server.mse.pipeline.chunk.rows": 8192,
    "pinot.server.mse.pipeline.watermark.rows": 8192,
    # leaf-stage output cache (mse/stage_cache.py): one worker's whole
    # scan/leaf_agg stage block per (segment version set, stage-plan
    # fingerprint) — epoch-invalidated like the tier-2 partial cache,
    # never caches partials, and skips tables with a mutable tail.
    # backend 'tiered' mounts the shared remote L2 (cache-server role /
    # ring) under the local tier so ONE replica's warm leaf output
    # serves the fleet: keys carry content CRC versions (never the
    # per-process generation stamps), payloads are typed Block serde
    "pinot.server.mse.stage.cache.enabled": True,
    "pinot.server.mse.stage.cache.bytes": 64 << 20,
    "pinot.server.mse.stage.cache.ttl.seconds": 300.0,
    "pinot.server.mse.stage.cache.backend": "local",
    "pinot.server.mse.stage.cache.remote.address": "127.0.0.1:9600",
    # negative cache: memoize pruned-to-zero plans (epoch-keyed) so
    # dashboard misfires skip routing + scatter entirely
    "pinot.broker.negative.cache.enabled": True,
    "pinot.broker.negative.cache.bytes": 1 << 20,
    "pinot.broker.negative.cache.ttl.seconds": 60.0,
    # tier-1 whole-result cache: opt-in — a cached response bypasses
    # scatter/gather entirely, including failure detection
    "pinot.broker.result.cache.enabled": False,
    "pinot.broker.result.cache.bytes": 64 << 20,
    "pinot.broker.result.cache.ttl.seconds": 60.0,
    # cache tables with a consuming side (appends don't move the routing
    # epoch, so hits may be TTL-stale) — off unless you can tolerate that
    "pinot.broker.result.cache.realtime": False,
    # tier-1 backend: local | tiered (shared remote L2, see server keys)
    "pinot.broker.result.cache.backend": "local",
    "pinot.broker.result.cache.remote.address": "127.0.0.1:9600",
    # hybrid tables: cache the offline side's merged partial keyed by the
    # OFFLINE epoch so only the realtime side re-scatters
    "pinot.broker.result.cache.hybrid.offline": True,
    # the cache-server role (cluster/roles.py run_cache_server)
    "pinot.cache.server.port": 9600,
    "pinot.cache.server.bytes": 512 << 20,
    "pinot.cache.server.ttl.seconds": 300.0,
    # remote-tier payload compression: payloads at/above this size are
    # wrapped with a segment/codec.py codec before the wire (and decoded
    # transparently on GET); <= 0 disables
    "pinot.cache.server.compress.threshold.bytes": 16384,
    # shared remote-client knobs (both tiers' L2 mounts)
    "pinot.cache.remote.timeout.seconds": 2.0,
    "pinot.cache.remote.pool.size": 2,
    "pinot.cache.remote.breaker.failures": 3,
    "pinot.cache.remote.breaker.reset.seconds": 5.0,
    # cache ring: `...remote.address` with >= 2 comma-separated addresses
    # consistent-hashes the key space client-side (cache/ring.py);
    # virtual-node count trades placement evenness for ring-build cost
    "pinot.cache.remote.ring.vnodes": 64,
    "pinot.controller.port": 9000,
    "pinot.controller.deep.store.uri": "",
    "pinot.controller.retention.frequency.seconds": 60,
    "pinot.coordination.liveness.ttl.seconds": 15.0,
    # minimal-disruption rebalancer (controller/rebalancer.py): a move
    # never drops a segment below min(replication, min.available.replicas)
    # live loaded copies; max.parallel.moves moves share one batched
    # routing-epoch bump (set 1 for byte-identical seeded chaos replays)
    "pinot.controller.rebalance.min.available.replicas": 1,
    "pinot.controller.rebalance.max.parallel.moves": 4,
    "pinot.controller.rebalance.journal.max.bytes": 1 << 20,
    # automatic failure repair (controller/repair.py): an instance whose
    # heartbeat age exceeds grace on two consecutive ticks (debounced —
    # flapping never churns replicas) gets its segments re-replicated
    "pinot.controller.repair.enabled": True,
    "pinot.controller.repair.grace.seconds": 30.0,
    "pinot.controller.repair.frequency.seconds": 10.0,
    # minion task fabric, controller side (controller/task_manager.py):
    # lease TTL + heartbeat-renewed leases; an expired lease requeues the
    # task with capped exponential backoff until max.attempts
    "pinot.controller.task.lease.seconds": 30.0,
    "pinot.controller.task.max.attempts": 3,
    "pinot.controller.task.retry.backoff.seconds": 1.0,
    "pinot.controller.task.retry.backoff.cap.seconds": 30.0,
    # cadence of the generator scan + lease-expiry sweep
    "pinot.controller.task.frequency.seconds": 30.0,
    "pinot.controller.task.generators.enabled": True,
    "pinot.controller.task.journal.max.bytes": 1 << 20,
    # minion task fabric, worker side (minion/worker.py)
    "pinot.minion.poll.seconds": 1.0,
    "pinot.minion.heartbeat.seconds": 2.0,
    "pinot.minion.task.types": "",   # csv; "" = all registered executors
    "pinot.minion.work.dir": "",     # "" = per-worker tempdir sandbox
    # worker-side executor pool: a minion runs up to this many tasks
    # concurrently (each with its own lease heartbeat); per-type caps
    # layer on top via pinot.minion.executor.concurrency.<TaskType>
    "pinot.minion.executor.concurrency": 2,
    # -- distributed tracing (utils/tracing.py + utils/trace_store.py) --
    # master switch: off = NO trace machinery at all (no RequestTrace,
    # no wire context, no tail capture) — the bench.py --trace-overhead
    # A-side. On = shadow span collection per query (stitched trees kept
    # only for trace=true responses and slow-query tail capture).
    "pinot.trace.enabled": True,
    # bounded per-role in-memory trace retention behind /debug/traces
    "pinot.trace.store.capacity": 256,
    # tail-based slow-query capture: queries at/over the threshold keep
    # their full stitched trace in the broker store and emit a
    # structured slow-query log line EVEN when trace=false (0 = off)
    "pinot.broker.slow.query.threshold.ms": 10000.0,
    # server-local tail capture over the server's own span tree (0=off;
    # sampled traces are stored in the server store regardless)
    "pinot.server.slow.query.threshold.ms": 0.0,
    "pinot.minion.slow.task.threshold.ms": 0.0,
    # per-role debug/metrics HTTP surface (utils/trace_store.py
    # DebugHttpServer): /metrics + /debug/traces + /debug/queries for
    # roles without an HTTP edge. 0 = ephemeral port (printed at
    # startup), >0 = fixed port, <0 = disabled.
    "pinot.server.admin.port": 0,
    "pinot.minion.admin.port": 0,
    "pinot.cache.server.admin.port": 0,
    # -- fleet health plane (pinot_tpu/health/) -------------------------
    # metrics history: a background sampler appends one flat
    # MetricsRegistry.sample() per interval to a bounded per-role ring
    # holding window.seconds worth — /debug/metrics/history serves it,
    # the SLO watchdog evaluates burn rates over it, and the selfmetrics
    # connector exposes it to the time-series engine. enabled=False
    # builds NO history machinery at all (the bench.py --health A-side).
    "pinot.metrics.history.enabled": True,
    "pinot.metrics.history.interval.ms": 1000.0,
    "pinot.metrics.history.window.seconds": 300.0,
    # SLO watchdog (health/slo.py): declarative targets evaluated as
    # multi-window burn rates over the history; a target left at 0 is
    # disabled. query.p99.ms bounds the role's per-sample latency p99;
    # error.rate bounds (exceptions + errorCode-250) per query;
    # freshness.ms bounds the worst per-partition ingestion lag.
    # latency.budget is the fraction of samples ALLOWED over a
    # sample-fraction target (burn = bad fraction / budget); a breach
    # needs BOTH the short and long window burn over burn.threshold.
    "pinot.slo.query.p99.ms": 0.0,
    "pinot.slo.error.rate": 0.0,
    "pinot.slo.freshness.ms": 0.0,
    "pinot.slo.window.short.seconds": 60.0,
    "pinot.slo.window.long.seconds": 300.0,
    "pinot.slo.burn.threshold": 1.0,
    "pinot.slo.latency.budget": 0.01,
    # per-query workload accounting (utils/accounting.ChargeSlip +
    # health/workload.py): device kernel ms, rows/bytes scanned,
    # transfer bytes, cache hit/miss bytes charged per query and rolled
    # into per-(tenant, table, plan) WorkloadStats at /debug/workload.
    # False = no slips, no rollup (the bench.py --health A-side).
    "pinot.workload.accounting.enabled": True,
    # cluster rollup (health/rollup.py): the controller's periodic
    # fleet sweep over every registered instance's admin_url into
    # GET /cluster/health + /cluster/metrics; scrape failures mark the
    # instance degraded, never throw.
    "pinot.cluster.health.enabled": True,
    "pinot.cluster.health.interval.seconds": 5.0,
    "pinot.cluster.health.scrape.timeout.seconds": 2.0,
}


def _env_name(key: str) -> str:
    # 'pinot.server.query.port' -> PINOT_TPU_SERVER_QUERY_PORT (the
    # shared 'pinot.' prefix folds into the env prefix)
    if key.startswith("pinot."):
        key = key[len("pinot."):]
    return ENV_PREFIX + key.replace(".", "_").upper()


class PinotConfiguration:
    def __init__(self, properties_file: Optional[str] = None,
                 overrides: Optional[Dict[str, Any]] = None):
        self._file: Dict[str, str] = {}
        if properties_file:
            self._file = load_properties(properties_file)
        self._overrides = dict(overrides or {})

    # ------------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        env = os.environ.get(_env_name(key))
        if env is not None:
            return env
        if key in self._file:
            return self._file[key]
        if key in KEYS:
            return KEYS[key]
        return default

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self.get(key, default))

    def get_float(self, key: str, default: float = 0.0) -> float:
        return float(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("1", "true", "yes", "on")

    def get_str(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def is_set(self, key: str) -> bool:
        """True when the key was EXPLICITLY configured (constructor
        override or properties file) rather than falling through to the
        env/catalog defaults — harnesses use this to layer their own
        defaults without clobbering operator choices."""
        return key in self._overrides or key in self._file

    def with_overrides(self, extra: Dict[str, Any]) -> "PinotConfiguration":
        """A derived config: same properties-file contents, overrides
        layered on top of (and winning over) the existing ones. Use this
        instead of rebuilding from `_overrides` alone — that would drop
        every file-based setting."""
        derived = PinotConfiguration(overrides={**self._overrides, **extra})
        derived._file = dict(self._file)
        return derived

    def subset(self, prefix: str) -> Dict[str, Any]:
        """All effective keys under a dotted prefix (catalog + file +
        overrides; env consulted per key)."""
        if not prefix.endswith("."):
            prefix += "."
        names = {k for k in KEYS if k.startswith(prefix)}
        names |= {k for k in self._file if k.startswith(prefix)}
        names |= {k for k in self._overrides if k.startswith(prefix)}
        return {k[len(prefix):]: self.get(k) for k in sorted(names)}


def load_properties(path: str) -> Dict[str, str]:
    """Minimal java .properties reader (key=value / key: value)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith(("#", "!")):
                continue
            # split at the FIRST occurrence of either separator (java
            # .properties semantics — 'k: a=b' must not split at '=')
            cuts = [i for i in (line.find("="), line.find(":")) if i >= 0]
            if not cuts:
                continue
            i = min(cuts)
            out[line[:i].strip()] = line[i + 1:].strip()
    return out
