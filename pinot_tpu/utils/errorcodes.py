"""Canonical query errorCode registry.

Reference parity: pinot-common QueryException / QueryErrorCode — every
error a broker response can carry has ONE assigned integer, defined in
one place. Before this module the literals (150, 190, 200, 250, 427,
429) were scattered across broker/server/mse/client modules; a typo'd
code would ship silently and the client's typed-error mapping would
miss it.

This is the error-code analog of the ``SITES`` failpoint table and the
``KEYS`` knob catalog: the ``errorcodes`` static-analysis checker
(analysis/checkers/errorcodes.py) enforces that

* every literal ``errorCode`` emission/comparison in production code
  references a name defined here (no bare ints);
* every name defined here is referenced somewhere in production code
  (no phantom codes);
* every name appears in the README error-code table.

The README "Error codes" table renders from :data:`CODES`; do not fork
a second list.
"""
from __future__ import annotations

from typing import Dict

#: SQL failed to parse under both engines' grammars
#: (ref QueryException.SQL_PARSING_ERROR_CODE)
SQL_PARSING = 150

#: the queried table exists in no routing table
#: (ref QueryException.TABLE_DOES_NOT_EXIST_ERROR_CODE)
TABLE_DOES_NOT_EXIST = 190

#: server-side execution raised (the catch-all execution failure,
#: ref QueryException.QUERY_EXECUTION_ERROR_CODE)
QUERY_EXECUTION = 200

#: the server REFUSED the query at admission — queue full, deadline
#: budget unservable, memory pressure, or load-shed priority class
#: (ref QueryException.SERVER_OUT_OF_CAPACITY_ERROR_CODE). Distinct
#: from 250 by design: the query consumed no execution resources and
#: the message carries a ``retryAfterMs=`` hint; the client maps it to
#: PinotOverloadError, the broker retries it on at most one other
#: replica and never escalates it to a raw 427.
SERVER_OVERLOADED = 211

#: the query exceeded its end-to-end deadline budget
#: (ref QueryException.EXECUTION_TIMEOUT_ERROR_CODE)
EXECUTION_TIMEOUT = 250

#: a server could not be reached / answered with a hard failure and no
#: surviving replica could cover its segments
#: (ref QueryException.SERVER_NOT_RESPONDING_ERROR_CODE)
SERVER_ERROR = 427

#: the query was rejected by a table/tenant QPS quota
#: (ref QueryException.TOO_MANY_REQUESTS_ERROR_CODE)
QUOTA_EXCEEDED = 429

# -- the SERVER_OVERLOADED retryAfterMs in-band contract ---------------------
# The exception wire format is (code, message) tuples, so the drain
# hint travels inside the 211 message. Format and parse live HERE, next
# to the code they belong to — the server response builder, the broker
# retry path, and the client error mapping all share this pair instead
# of three hand-rolled regexes drifting apart.

_RETRY_AFTER_RE = None


def format_retry_after(ms: float) -> str:
    """The hint fragment appended to a 211 message. Floored at 1ms:
    'retry now' is never an honest hint from a shedding server."""
    return f"(retryAfterMs={int(round(max(1.0, ms)))})"


def parse_retry_after(message: str):
    """The hint parsed back out of a 211 message; None when absent."""
    global _RETRY_AFTER_RE
    if _RETRY_AFTER_RE is None:
        import re
        _RETRY_AFTER_RE = re.compile(r"retryAfterMs=(\d+(?:\.\d+)?)")
    m = _RETRY_AFTER_RE.search(str(message))
    return float(m.group(1)) if m else None


#: THE canonical registry: code name -> one-line contract. The
#: ``errorcodes`` checker keeps it in lockstep with the constants above
#: and with the README error-code table.
CODES: Dict[str, str] = {
    "SQL_PARSING":
        "SQL rejected by both the single-stage and MSE grammars",
    "TABLE_DOES_NOT_EXIST":
        "no routing table knows the queried table",
    "QUERY_EXECUTION":
        "server-side execution raised (catch-all failure)",
    "SERVER_OVERLOADED":
        "rejected at server admission (queue/deadline/memory/priority "
        "shed) — carries a retryAfterMs hint, consumed no execution",
    "EXECUTION_TIMEOUT":
        "end-to-end deadline budget exhausted; response is a typed "
        "partial",
    "SERVER_ERROR":
        "server unreachable or hard-failed with no surviving replica",
    "QUOTA_EXCEEDED":
        "table or tenant QPS quota rejected the query",
}
