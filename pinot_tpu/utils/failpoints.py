"""Deterministic failpoint (chaos) registry.

Reference parity: the reference ecosystem provokes slow/dead-replica
scenarios with external chaos tooling; here fault injection is a
first-class, deterministic library feature so every deadline / hedge /
retry path has a reproducible test. Named sites are compiled into the
production code as ``fire("site.name", ...)`` calls; when the site is
unarmed the call is a dict lookup + None check (sub-microsecond), so the
hooks are safe to leave in hot-ish control paths (they are NOT placed in
per-row loops).

The canonical site registry is the ``SITES`` table below — one entry
per compiled-in site with its one-line contract. The static-analysis
``failpoints`` checker (pinot_tpu/analysis) keeps it honest three ways:
every ``fire("…")`` literal in production code must be a SITES entry,
every SITES entry must be fired somewhere, and every SITES entry must
be armed by at least one test. The README "Reliability" failpoint table
derives from SITES; do not fork a second list.

Policies are armed per site with deterministic, seeded behavior:

  fp.arm("server.execute.before", delay=0.5)                 # fixed delay
  fp.arm("netframe.send", error=ConnectionError("chaos"))    # raise
  fp.arm("connection.request", torn=True)                    # truncate payload
  fp.arm("cache.remote.get", drop=True)                      # ConnectionError
  fp.arm(site, delay=0.1, exponential=True, seed=7)          # seeded exp delay
  fp.arm(site, error=..., times=1)                           # one-shot
  fp.arm(site, delay=1.0, probability=0.3, seed=42)          # seeded coin
  fp.arm(site, delay=1.0, where={"instance": "server_0"})    # ctx match

Every ``hit()`` decision (fired or skipped) is appended to the policy's
``decisions`` list, so a schedule replayed with the same seed can be
asserted identical — chaos that reproduces exactly (ISSUE 3).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


#: THE canonical failpoint-site registry: site name -> one-line
#: contract. Enforced by the `failpoints` static-analysis checker
#: (fired-somewhere, documented-here, armed-by-a-test — all three); the
#: README failpoint table renders from this dict.
SITES: Dict[str, str] = {
    "broker.scatter.before":
        "before the broker fans a plan entry out",
    "broker.group.scatter":
        "before a scatter to a replica-group member (ctx: server, "
        "table, group index — arm with where={'group': 0} to kill one "
        "fault domain)",
    "cache.ring.node":
        "every cache-ring key->node resolution (ctx: node, key — arm "
        "with where={'node': addr} to fail one node's key range)",
    "server.admission.reject":
        "server admission decision point (ctx: table, tenant, workload) "
        "— arm with error=ServerOverloadedError(...) to force seeded "
        "rejections; decisions journal for byte-identical replay",
    "broker.retry.budget":
        "broker-side, at every retry/hedge budget withdrawal (ctx: "
        "table) — arm with error=FailpointError() to force seeded "
        "budget exhaustion",
    "server.execute.before":
        "server-side, before a query executes",
    "server.execute.segment":
        "per segment in the execution loop",
    "server.dispatch.before":
        "kernel dispatch (ring + inline paths)",
    "server.dispatch.batch":
        "per MEMBER inside the coalesced-batch path (ctx: table, mode, "
        "batch_size) — an erroring member fails only its own future; "
        "peers stay batched and complete",
    "netframe.send":
        "every framed send (coordination, cache, stream)",
    "connection.request":
        "broker->server request, response payload hook",
    "cache.remote.get":
        "remote cache-tier GET",
    "ingest.realtime.consume":
        "realtime consume loop (a SimulatedCrash here VANISHES the "
        "consumer mid-batch — the SIGKILL stand-in; recovery = new "
        "manager from the committed offset + snapshots)",
    "ingest.tcp.frame":
        "TCP stream consumer edge",
    "ingest.seal.build":
        "immutable-segment build start (async build-pool leg and the "
        "FSM path); errors retry with backoff, the sealed mutable "
        "keeps serving meanwhile",
    "ingest.seal.swap":
        "before the warmed immutable swaps in over the sealed mutable "
        "(tdm.add_segment)",
    "ingest.checkpoint":
        "replay-checkpoint persistence, payload hook (torn= truncates "
        "the offset payload: the manager persists NOTHING and retries "
        "— restart re-consumes, never corrupts)",
    "ingest.upsert.apply":
        "per-row upsert metadata application, BEFORE any state lands "
        "(an armed error skips the row whole, never half-applied)",
    "controller.rebalance.move":
        "per move-engine step (ctx: segment, table, instance, stage="
        "load|commit|drain) — arm with where={'stage': 'commit'} + "
        "SimulatedCrash to kill the controller between LOADING and "
        "ROUTED; seeded delays journal for byte-identical replay",
    "controller.rebalance.journal":
        "move-journal line write, payload hook (torn= truncates the "
        "JSON line: replay SKIPS it and resume re-executes that "
        "idempotent transition — a torn write means resume, never a "
        "corrupt plan)",
    "controller.repair.replicate":
        "repair checker, before re-replicating one segment onto a "
        "healthy target (ctx: segment, table, target) — an armed error "
        "skips that segment this tick; the next tick retries",
    "controller.task.assign":
        "task-fabric lease grant",
    "controller.task.lease.renew":
        "task-fabric heartbeat renewal",
    "controller.segment.replace":
        "the atomic minion segment swap",
    "minion.task.execute":
        "worker-side, as task execution starts",
    "minion.startree.build":
        "per segment inside StarTreeBuildTask, before the rebuild (a "
        "SimulatedCrash leaves the source segment serving via the scan "
        "path; the re-leased task rebuilds byte-identical tree output)",
    "minion.clp.compact":
        "per segment inside ClpCompactionTask, before the re-encode (a "
        "SimulatedCrash leaves the source segment serving via the host "
        "decode path; the re-leased task re-encodes byte-identical CLP "
        "output)",
    "mse.dispatch.stage":
        "broker-side, before one stage dispatches",
    "mse.mailbox.send":
        "every mailbox frame send (torn=, delay= keep stream framing "
        "intact)",
    "mse.mailbox.recv":
        "every mailbox frame receive",
    "mse.stage.execute":
        "worker-side, as a stage instance starts",
    "mse.stage.hedge":
        "broker-side, as a leaf-stage hedge attempt is issued (the "
        "PR-10 claim-book race — seeded journals replay byte-identical)",
    "mse.worker.crash":
        "MSE worker kill point: SimulatedCrash vanishes the worker "
        "(mailbox gone, no error frames — receivers must detect)",
    "server.mesh.collective":
        "server-side, before the collective-merge path stages a query "
        "(ctx: table, mode) — an armed error falls back to the host "
        "IndexedTable fold with mesh_merge_fallback{reason=chaos}; "
        "seeded decisions journal byte-identical",
    "server.vector.search":
        "server-side, as a vector_similarity top-K enters the device "
        "leg (ctx: table) — an armed error surfaces as a query "
        "exception (the broker's retry/hedge machinery owns recovery); "
        "seeded decisions journal for byte-identical replay",
    "timeseries.leaf.fetch":
        "time-series engine, before a leaf plan node issues its "
        "GROUP-BY SQL (ctx: table) — an armed error fails that panel's "
        "fetch whole, never a half-filled bucket grid; seeded "
        "decisions journal for byte-identical replay",
}


class FailpointError(RuntimeError):
    """Default error raised by an armed ``error=True`` policy."""


class TornPayloadError(ValueError):
    """Raised by consumers that detect a payload truncated by chaos."""


class SimulatedCrash(Exception):
    """Armed as a site's ``error=`` to emulate a hard process kill: the
    component that catches it must VANISH silently — no failure report,
    no cleanup handshake — leaving recovery to lease-expiry / liveness
    sweeps, exactly as if the process had been SIGKILLed."""


class Failpoint:
    """One armed site: action + trigger discipline + decision log."""

    def __init__(self, site: str, delay: float = 0.0,
                 exponential: bool = False,
                 error: Optional[BaseException] = None,
                 drop: bool = False, torn: bool = False,
                 times: Optional[int] = None, probability: float = 1.0,
                 seed: int = 0,
                 where: Optional[Dict[str, Any]] = None):
        self.site = site
        self.delay = float(delay)
        self.exponential = exponential
        self.error = error
        self.drop = drop
        self.torn = torn
        self.times = times
        self.probability = float(probability)
        self.where = dict(where or {})
        # private seeded PRNG: decisions depend ONLY on (seed, hit order),
        # never on the global random state, so a schedule replays exactly
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        #: per-hit decision log: (fired, delay_applied) tuples
        self.decisions: List[Tuple[bool, float]] = []
        self.hits = 0
        self.fired = 0

    def _matches(self, ctx: Dict[str, Any]) -> bool:
        return all(ctx.get(k) == v for k, v in self.where.items())

    def apply(self, ctx: Dict[str, Any],
              payload: Optional[bytes]) -> Optional[bytes]:
        """Run the policy for one hit; returns the (possibly mutated)
        payload, sleeps, or raises — per the armed action."""
        with self._lock:
            if not self._matches(ctx):
                return payload
            self.hits += 1
            if self.times is not None and self.fired >= self.times:
                self.decisions.append((False, 0.0))
                return payload
            # the PRNG advances once per MATCHED hit whether or not the
            # coin lands, so decision N is a pure function of (seed, N)
            roll = self._rng.random()
            if roll >= self.probability:
                self.decisions.append((False, 0.0))
                return payload
            self.fired += 1
            wait = self.delay
            if wait and self.exponential:
                wait = self._rng.expovariate(1.0 / wait)
            self.decisions.append((True, wait))
        if wait:
            time.sleep(wait)
        if self.error is not None:
            raise self.error
        if self.drop:
            raise ConnectionError(f"failpoint {self.site}: connection drop")
        if self.torn and payload is not None:
            return payload[: max(1, len(payload) // 2)]
        return payload


class FailpointRegistry:
    """Process-global site registry. Unarmed sites cost one dict get."""

    def __init__(self):
        self._sites: Dict[str, List[Failpoint]] = {}
        self._lock = threading.Lock()

    # -- arming --------------------------------------------------------
    def arm(self, site: str, **kwargs) -> Failpoint:
        fp = Failpoint(site, **kwargs)
        with self._lock:
            self._sites.setdefault(site, []).append(fp)
        return fp

    def disarm(self, site: str) -> None:
        with self._lock:
            self._sites.pop(site, None)

    def clear(self) -> None:
        with self._lock:
            self._sites.clear()

    def armed(self, site: str, **kwargs) -> "_Armed":
        """Context manager: ``with failpoints.armed(site, delay=1): ...``"""
        return _Armed(self, site, kwargs)

    # -- the hot call --------------------------------------------------
    def hit(self, site: str, payload: Optional[bytes] = None,
            **ctx) -> Optional[bytes]:
        # lint: unlocked(deliberately lock-free hot path: unarmed cost must stay one dict lookup; arm/disarm replace the LIST atomically and the copy below tolerates concurrent disarm)
        fps = self._sites.get(site)
        if not fps:
            return payload
        for fp in list(fps):
            payload = fp.apply(ctx, payload)
        return payload

    def count(self, site: str) -> int:
        """Total fired actions across the site's armed policies."""
        with self._lock:
            return sum(fp.fired for fp in self._sites.get(site, []))


class _Armed:
    def __init__(self, registry: FailpointRegistry, site: str, kwargs: dict):
        self._registry = registry
        self._site = site
        self._kwargs = kwargs
        self.failpoint: Optional[Failpoint] = None

    def __enter__(self) -> Failpoint:
        self.failpoint = self._registry.arm(self._site, **self._kwargs)
        return self.failpoint

    def __exit__(self, *exc) -> None:
        with self._registry._lock:
            fps = self._registry._sites.get(self._site)
            if fps and self.failpoint in fps:
                fps.remove(self.failpoint)
                if not fps:
                    del self._registry._sites[self._site]


class FaultSchedule:
    """A named batch of (site, policy-kwargs) armed/disarmed together —
    the ``MiniCluster(chaos=...)`` payload.

    >>> sched = FaultSchedule([("server.execute.before",
    ...                         {"delay": 0.5, "where": {"instance": "s0"}})])
    >>> sched.arm(); ...; sched.disarm()
    """

    def __init__(self, entries: List[Tuple[str, Dict[str, Any]]]):
        self.entries = list(entries)
        self.failpoints: List[Failpoint] = []

    def arm(self, registry: Optional[FailpointRegistry] = None) -> None:
        registry = registry or failpoints
        self.failpoints = [registry.arm(site, **kwargs)
                           for site, kwargs in self.entries]

    def disarm(self, registry: Optional[FailpointRegistry] = None) -> None:
        registry = registry or failpoints
        with registry._lock:
            for fp in self.failpoints:
                fps = registry._sites.get(fp.site)
                if fps and fp in fps:
                    fps.remove(fp)
                    if not fps:
                        del registry._sites[fp.site]
        self.failpoints = []

    def decisions(self) -> List[List[Tuple[bool, float]]]:
        """Per-entry decision logs — assert two same-seed runs equal."""
        return [list(fp.decisions) for fp in self.failpoints]


#: the process-global registry production sites fire against
failpoints = FailpointRegistry()

#: module-level alias used at instrumented sites:
#:   from pinot_tpu.utils.failpoints import fire
#:   payload = fire("connection.request", payload=payload, server=name)
fire = failpoints.hit
