"""Metrics registry: meters, gauges, timers + Prometheus text exposition.

Reference parity: pinot-spi metrics/PinotMetricsRegistry.java + the typed
role registries over AbstractMetrics (pinot-common metrics/ —
ServerMetrics/BrokerMetrics/ControllerMetrics/MinionMetrics with per-role
meter/gauge/timer enums, exported via JMX). Here one thread-safe registry
with the same meter/gauge/timer trio, exported as Prometheus text
(the modern equivalent of the JMX reporter).
"""
from __future__ import annotations

import math
import random
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Tuple

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, labels: Optional[Dict[str, str]]) -> _Key:
    return (name, tuple(sorted((labels or {}).items())))


class Timer:
    """count/sum/max plus p50/p95/p99 from a fixed-size reservoir
    (Vitter's algorithm R — every observation has equal probability of
    being sampled, so tails survive long runs; a keep-last-N window
    would forget cold-start latencies the moment traffic warms up)."""

    __slots__ = ("count", "total_ms", "max_ms", "_reservoir", "_rng")

    RESERVOIR_SIZE = 256

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self._reservoir: List[float] = []
        # private PRNG: seeded for reproducible tests, and never touches
        # the global random state
        self._rng = random.Random(0x5EED)

    def update(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        self.max_ms = max(self.max_ms, ms)
        if len(self._reservoir) < self.RESERVOIR_SIZE:
            self._reservoir.append(ms)
        else:
            j = self._rng.randrange(self.count)
            if j < self.RESERVOIR_SIZE:
                self._reservoir[j] = ms

    def snapshot(self) -> "Timer":
        """A detached consistent copy (counters + reservoir). Callers
        must take it under whatever lock serializes update() — the
        registry does (MetricsRegistry.timer); standalone Timers (the
        adaptive selector's reservoirs) snapshot under their owner's
        lock."""
        t = Timer.__new__(Timer)
        t.count = self.count
        t.total_ms = self.total_ms
        t.max_ms = self.max_ms
        t._reservoir = list(self._reservoir)
        t._rng = random.Random(0x5EED)
        return t

    def quantile(self, q: float) -> float:
        """Empirical quantile estimate from the reservoir (0 when no
        observations yet)."""
        if not self._reservoir:
            return 0.0
        s = sorted(self._reservoir)
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return s[idx]

    @property
    def samples(self) -> Tuple[float, ...]:
        """Snapshot of the reservoir's per-observation samples (ms) —
        consumers pooling tails across several timers (e.g. the broker's
        adaptive hedge delay over per-server reservoirs) read the raw
        samples instead of mixing already-collapsed quantiles."""
        return tuple(self._reservoir)


class MetricsRegistry:
    """Ref PinotMetricsRegistry — meters (counters), gauges, timers."""

    def __init__(self, role: str = "server"):
        self.role = role
        self._meters: Dict[_Key, float] = defaultdict(float)
        self._gauges: Dict[_Key, float] = {}
        self._timers: Dict[_Key, Timer] = defaultdict(Timer)
        #: per-timer last trace id (exemplar): links a /metrics tail to
        #: the stored trace at /debug/traces/<id>
        self._exemplars: Dict[_Key, str] = {}
        self._lock = threading.Lock()

    # -- write side ---------------------------------------------------------
    def add_meter(self, name: str, value: float = 1,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._meters[_key(name, labels)] += value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def remove_gauge(self, name: str,
                     labels: Optional[Dict[str, str]] = None) -> bool:
        """Drop one labeled gauge series entirely. A gauge whose subject
        is GONE (a removed ingestion partition, an unloaded segment) must
        leave the exposition — zeroing it keeps the stale labeled series
        on /metrics forever, and dashboards aggregate it as live data.
        Returns whether the series existed."""
        with self._lock:
            return self._gauges.pop(_key(name, labels), None) is not None

    def add_timing(self, name: str, ms: float,
                   labels: Optional[Dict[str, str]] = None,
                   exemplar: Optional[str] = None) -> None:
        """exemplar: the trace id of the request this observation came
        from — the timer remembers the LAST one, so a tail spike on
        /metrics names a concrete stored trace to pull."""
        with self._lock:
            k = _key(name, labels)
            self._timers[k].update(ms)
            if exemplar:
                self._exemplars[k] = exemplar

    class _TimeCtx:
        def __init__(self, reg, name, labels):
            self.reg, self.name, self.labels = reg, name, labels

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.reg.add_timing(self.name,
                                (time.perf_counter() - self.t0) * 1000.0,
                                self.labels)

    def time(self, name: str, labels: Optional[Dict[str, str]] = None):
        return MetricsRegistry._TimeCtx(self, name, labels)

    # -- read side ----------------------------------------------------------
    def meter(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return self._meters.get(_key(name, labels), 0.0)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def timer(self, name: str, labels: Optional[Dict[str, str]] = None) -> Timer:
        """A consistent SNAPSHOT of the timer (empty on miss). Taken
        under the registry lock: the previous implementation handed out
        the live Timer, whose reservoir list a concurrent update()
        mutates while quantile()/samples iterate it — and a detached
        EMPTY Timer on miss, silently dropping updates made through it.
        A snapshot is race-free either way; writes go through
        add_timing()."""
        with self._lock:
            t = self._timers.get(_key(name, labels))
            return t.snapshot() if t is not None else Timer()

    def set_exemplar(self, name: str,
                     labels: Optional[Dict[str, str]] = None,
                     trace_id: str = "") -> None:
        """Stamp a timer's exemplar out of band (wrappers that own the
        trace id but not the timing call)."""
        if not trace_id:
            return
        with self._lock:
            self._exemplars[_key(name, labels)] = trace_id

    def exemplar(self, name: str,
                 labels: Optional[Dict[str, str]] = None) -> Optional[str]:
        """Last trace id recorded against the timer (None when never)."""
        with self._lock:
            return self._exemplars.get(_key(name, labels))

    def sample(self) -> dict:
        """One timestamped FLAT snapshot of the whole registry — the
        unit the metrics history ring stores and the cluster rollup
        scrapes. Keys are ``name`` or ``name{k="v",...}`` (the exposition
        label syntax, so history consumers and /metrics agree on series
        identity); timers collapse to count/sum/max plus the reservoir
        quantiles. Taken under the registry lock: one sample is
        internally consistent."""
        with self._lock:
            counters = {f"{n}{_fmt(ls)}": v
                        for (n, ls), v in self._meters.items()}
            gauges = {f"{n}{_fmt(ls)}": v
                      for (n, ls), v in self._gauges.items()}
            timers = {}
            for (n, ls), t in self._timers.items():
                timers[f"{n}{_fmt(ls)}"] = {
                    "count": t.count,
                    "sum_ms": round(t.total_ms, 3),
                    "max_ms": round(t.max_ms, 3),
                    "p50": round(t.quantile(0.5), 3),
                    "p95": round(t.quantile(0.95), 3),
                    "p99": round(t.quantile(0.99), 3),
                }
        return {"ts": time.time(), "role": self.role,
                "counters": counters, "gauges": gauges, "timers": timers}

    def prometheus_text(self) -> str:
        """Prometheus exposition format (the JMX-reporter analog).

        `# TYPE` is emitted once per metric NAME — two label sets of the
        same metric share one family header (duplicate TYPE lines are
        invalid exposition and make scrapers reject the whole page).
        `# HELP` rides beside it from the metric-name catalog
        (utils/metrics_catalog.py) for every cataloged family."""
        from pinot_tpu.utils.metrics_catalog import METRICS
        out: List[str] = []
        prefix = f"pinot_tpu_{self.role}_"
        typed: set = set()

        def type_line(base: str, kind: str, name: str = "") -> None:
            if base not in typed:
                typed.add(base)
                desc = METRICS.get(name)
                if desc:
                    out.append(f"# HELP {base} {_escape_help(desc)}")
                out.append(f"# TYPE {base} {kind}")

        with self._lock:
            for (name, labels), v in sorted(self._meters.items()):
                type_line(f"{prefix}{name}", "counter", name)
                out.append(f"{prefix}{name}{_fmt(labels)} {v:g}")
            for (name, labels), v in sorted(self._gauges.items()):
                type_line(f"{prefix}{name}", "gauge", name)
                out.append(f"{prefix}{name}{_fmt(labels)} {v:g}")
            for (name, labels), t in sorted(self._timers.items()):
                base = f"{prefix}{name}"
                type_line(base, "summary", name)
                for q in (0.5, 0.95, 0.99):
                    qlabels = labels + (("quantile", f"{q:g}"),)
                    out.append(f"{base}{_fmt(qlabels)} {t.quantile(q):g}")
                out.append(f"{base}_count{_fmt(labels)} {t.count}")
                out.append(f"{base}_sum_ms{_fmt(labels)} {t.total_ms:g}")
                out.append(f"{base}_max_ms{_fmt(labels)} {t.max_ms:g}")
                ex = self._exemplars.get((name, labels))
                if ex:
                    # exemplar as a comment line: Prometheus text parsers
                    # skip non-HELP/TYPE comments, humans and tooling get
                    # the /metrics-tail -> /debug/traces/<id> link
                    out.append(f"# EXEMPLAR {base}{_fmt(labels)} "
                               f'trace_id="{_escape(ex)}"')
        return "\n".join(out) + "\n"


def _escape(v: str) -> str:
    """Label-value escaping per the exposition spec: backslash, quote,
    newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP-text escaping per the exposition spec: backslash, newline
    (quotes stay literal in HELP lines)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


# role-level singletons (ref ServerMetrics.get() style accessors)
_registries: Dict[str, MetricsRegistry] = {}
_reg_lock = threading.Lock()


def get_registry(role: str = "server") -> MetricsRegistry:
    with _reg_lock:
        reg = _registries.get(role)
        if reg is None:
            reg = MetricsRegistry(role)
            _registries[role] = reg
        return reg
