"""Metric-name catalog: one description per metric family.

The exposition analog of the ``KEYS`` knob catalog in utils/config.py:
every literal metric name emitted through a registry
(``add_meter``/``set_gauge``/``add_timing``/``time``/``observe``) has an
entry here, ``MetricsRegistry.prometheus_text`` emits the description as
the family's ``# HELP`` line, and the README "Metrics reference"
appendix is generated from the same text — so /metrics, the docs, and
the code can't drift apart. The ``metrics_docs`` static-analysis checker
(analysis/checkers/metrics_docs.py) enforces all three legs in tier-1.

Prefix-composed families (cache/core.py's ``<prefix>_hits/misses/...``,
cache/remote.py's ``remote_cache_<name>``) are namespaced by
construction and documented as families in the README prose; their
short suffixes are not catalog entries.
"""
from __future__ import annotations

from typing import Dict

#: metric name -> one-line HELP description (kind lives at the emission
#: site; the exposition checker keeps each name single-kind)
METRICS: Dict[str, str] = {
    # -- broker query path ------------------------------------------------
    "broker_query_ms": "end-to-end broker latency per query (ms)",
    "broker_queries": "queries handled by this broker",
    "broker_query_errors": "broker responses carrying any exception",
    "broker_error_code_250":
        "broker responses carrying an errorCode-250 (deadline) entry",
    "deadline_expired":
        "queries whose gather abandoned servers at the deadline",
    "hedge_issued": "hedged scatter attempts issued",
    "hedge_won": "hedge attempts that beat the primary",
    "hedge_wasted": "hedge attempts the primary beat",
    "hedge_split": "hedges split across replicas (partial layouts)",
    "slow_queries": "queries at/over the slow-query threshold",
    # -- server query path ------------------------------------------------
    "queries": "queries executed by this server",
    "queries_killed": "queries stopped by deadline/cancel",
    # -- overload protection (PR 15) --------------------------------------
    "server_admission_rejected":
        "queries rejected at server admission (label reason=queue|"
        "deadline|memory|tenant|workload|chaos)",
    "scheduler_queue_rejected":
        "submissions refused by a scheduler's bounded queue backstop",
    "broker_overload_rejections":
        "server overload (211) rejections received by this broker",
    "broker_overload_partials":
        "responses where an overload rejection surfaced as a typed "
        "partial (no replica absorbed the retry)",
    "broker_retries_issued": "scatter retry units launched after failures",
    "broker_retry_budget_exhausted":
        "retries/hedges suppressed by an exhausted per-table budget",
    "broker_retry_budget_tokens":
        "per-table retry-budget tokens remaining (label table=)",
    "brownout_level":
        "current brownout ladder level (0 = healthy, 4 = full brownout)",
    "brownout_transitions":
        "brownout ladder moves (label direction=up|down)",
    "stale_results_served":
        "result-cache entries served past TTL under brownout "
        "(staleResult=true)",
    "query_exceptions": "queries that raised server-side",
    "query_execution": "server-side execution latency per query (ms)",
    "scheduler_inflight": "queries currently inside the scheduler",
    # -- dispatch ring / kernel factory ----------------------------------
    "dispatch_queue_depth": "launches waiting in the dispatch ring",
    "dispatch_batch_size": "coalesced members per launch",
    "dispatch_batch_cross_table":
        "batch members coalesced across tables (stacked/dedup variants)",
    "dispatch_batch_dedup":
        "batch members sharing a stack entry via same-cols grouping",
    "staging_overlap_ms":
        "staging wall time overlapped with another query's kernel (ms)",
    "kernel_retrace": "kernel retraces (steady-state retraces are bugs)",
    "kernel_retrace_by_plan":
        "kernel retraces attributed per plan fingerprint",
    "startree_served":
        "queries answered by the device star-tree pre-agg leg",
    "startree_fallback":
        "tree-carrying batches routed to the scan path (label reason="
        "disabled|aggregation|groupBy|noTree|fit|filter|precision|"
        "groups|staging)",
    "clp_served":
        "queries whose CLP-column LIKE/regex filter served device-side",
    "clp_fallback":
        "CLP-column LIKE/regex filters routed to the host decode path "
        "(label reason=disabled|predicate|charWildcard|regex|wildcard|"
        "partial|slots|alignments|staging)",
    "vector_served":
        "vector_similarity top-K queries answered by the device "
        "batched-matmul leg",
    "vector_fallback":
        "vector_similarity queries routed to the host index scan "
        "(label reason=disabled|noIndex|metric|hybrid|staging|"
        "precision)",
    "timeseries_leaf_device":
        "leaf group-bys whose time bucket fused into the device "
        "group-by kernel (ops/timeseries_device.py) instead of the "
        "host expression path",
    "mesh_merge_served":
        "mesh queries whose cross-segment partial merge ran as ONE "
        "on-device collective (no host IndexedTable fold)",
    "mesh_merge_fallback":
        "mesh queries routed to the host partial fold (label reason="
        "disabled|chaos|precision|groups|staging)",
    # -- memory tiers (HBM residency) ------------------------------------
    "hbm_cache_bytes":
        "assembled [S, D] block-cache bytes on device (multi-chip "
        "engines also emit a per-chip split under a device= label)",
    "hbm_resident_bytes":
        "resident-row tier bytes per chip (label device=platform:id — "
        "the skew the per-chip admission pressure gates on)",
    "hbm_block_hit": "assembled-block cache hits",
    "hbm_block_miss": "assembled-block cache misses",
    "hbm_resident_hit": "resident-row tier hits",
    "hbm_resident_miss": "resident-row tier misses",
    "hbm_admission_rejected": "rows the TinyLFU admission duel rejected",
    "hbm_evicted": "rows evicted from the resident tier",
    "hbm_transfer_bytes": "host->device bytes shipped by residency",
    "host_row_cache_bytes": "host padded-row cache bytes",
    "host_row_hit": "host row-cache hits",
    "host_row_miss": "host row-cache misses",
    "host_row_evicted": "host row-cache evictions",
    # -- ingestion --------------------------------------------------------
    "ingest_rows_indexed": "rows indexed into mutable segments",
    "ingest_rows_skipped": "rows dropped by transforms/poison guards",
    "ingest_segments_sealed": "mutable segments sealed",
    "ingest_seal_build_failures": "immutable builds that failed (retried)",
    "ingest_checkpoint_torn": "torn checkpoint writes detected",
    "ingest_backpressure_pauses": "consumer pauses at the memory budget",
    "ingest_lag_shed_seals": "force-seals shed by the lag ceiling",
    "ingestion_delay_ms": "per-partition end-to-end ingestion lag (ms)",
    # -- caches / remote fabric ------------------------------------------
    "remote_cache_request": "remote cache-tier round-trip latency (ms)",
    "remote_cache_errors": "remote cache-tier request failures",
    "remote_cache_breaker_state":
        "remote-tier circuit breaker (0 closed, 1 open, 2 half-open)",
    "remote_cache_compressed_bytes":
        "bytes saved by remote-tier payload compression",
    "segment_warmup_segments": "segments warmed before first serve",
    "segment_warmup_entries": "cache entries populated by warmup",
    # -- multi-stage engine ----------------------------------------------
    "mse_queries": "multi-stage queries dispatched",
    "mse_cancelled": "multi-stage queries cancelled",
    "mse_deadline_expired": "multi-stage queries past their budget",
    "mse_mailbox_sent_frames": "mailbox frames sent",
    "mse_mailbox_sent_bytes": "mailbox bytes sent",
    "mse_mailbox_recv_frames": "mailbox frames received",
    "mse_mailbox_recv_bytes": "mailbox bytes received",
    "mse_mailbox_retries": "mailbox sends retried on a fresh socket",
    "mse_mailbox_poisoned": "mailbox queues poisoned by abort",
    "mse_stage_hedge_issued": "MSE stage hedges issued",
    "mse_stage_hedge_won": "MSE stage hedges that won",
    "mse_stage_hedge_wasted": "MSE stage hedges the primary beat",
    "mse_stage_cache_remote_hits":
        "leaf-stage cache hits served from the shared remote tier",
    # -- minion task fabric ----------------------------------------------
    "task_queue_depth": "active (non-terminal) tasks in the queue",
    "minion_running_tasks": "tasks currently executing on this worker",
    "minion_tasks_completed": "tasks completed by this worker",
    "minion_tasks_failed": "tasks failed by this worker",
    "minion_tasks_retried": "expired leases requeued for retry",
    "minion_task_duration_ms": "per-type task execution latency (ms)",
    "minion_manifest_resumes": "crash-mid-commit manifest resumes",
    # -- fleet health plane (PR 14) --------------------------------------
    "metrics_history_samples": "registry samples appended to the history",
    "slo_burn_rate":
        "short-window SLO error-budget burn rate (label slo=<target>)",
    "slo_latency_bad":
        "queries over the pinot.slo.query.p99.ms target "
        "(the latency-burn numerator)",
    "slo_breaches": "SLO breach onsets (multi-window burn over threshold)",
    "workload_tenant_cost_ms":
        "accumulated per-tenant cost (device kernel ms + cpu ms)",
    "cluster_scrape_failures": "instance scrapes that failed",
    "cluster_instances_live": "instances the last sweep verdicted live",
    "cluster_instances_degraded":
        "instances the last sweep verdicted degraded",
    # -- self-healing maintenance (PR 18) ---------------------------------
    "segments_missing_replicas":
        "segments below their configured replication (label table=; "
        "repair draining this to zero is the convergence signal)",
    "segments_offline": "segments in OFFLINE status (label table=)",
    "rebalance_moves_completed":
        "segment moves the rebalance engine completed (DONE)",
    "repair_replications":
        "segments re-replicated by the automatic failure repair loop",
}
