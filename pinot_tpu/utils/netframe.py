"""Shared framed-JSON TCP protocol: u32 little-endian length | JSON.

Used by the coordination service (controller/coordination.py) and the TCP
stream connector (ingest/tcp_stream.py) — one implementation of framing,
frame-size limits, and the reconnecting request channel, so wire fixes
land everywhere at once.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Optional

from pinot_tpu.utils.failpoints import fire

LEN = struct.Struct("<I")
MAX_FRAME = 64 << 20


def send_frame(sock: socket.socket, obj: Any) -> None:
    send_raw_frame(sock, json.dumps(obj).encode())


def recv_frame(sock: socket.socket) -> Optional[dict]:
    body = recv_raw_frame(sock)
    return None if body is None else json.loads(body)


def send_raw_frame(sock: socket.socket, payload: bytes) -> None:
    """Length-prefixed RAW bytes (no JSON) — used for binary payloads
    (cache entries) interleaved with JSON control frames on one channel.
    JSON frames are the same framing with a json.dumps/loads layer, so
    both kinds stay in sync by construction."""
    # chaos site: delay / drop / tear ANY framed send (coordination,
    # cache fabric, stream connector). A torn payload ships truncated
    # bytes under a matching header — the frame arrives whole but its
    # content no longer decodes, the half-written-entry failure the
    # decode layers must degrade on (cache: miss; JSON: error surface)
    payload = fire("netframe.send", payload=payload)
    sock.sendall(LEN.pack(len(payload)) + payload)


def recv_raw_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = recv_exact(sock, 4)
    if hdr is None:
        return None
    n = LEN.unpack(hdr)[0]
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return recv_exact(sock, n)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class FramedChannel:
    """Thread-safe blocking request/response channel with one reconnect.

    retry=False callers (non-idempotent ops like stream publish) surface
    the connection error instead of re-sending a request the server may
    have already applied."""

    def __init__(self, address: str, timeout: Optional[float] = 30.0):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def request(self, req: dict, retry: bool = True) -> dict:
        with self._lock:
            attempts = (0, 1) if retry else (1,)
            for attempt in attempts:
                try:
                    if self._sock is None:
                        self._sock = socket.create_connection(
                            (self.host, self.port), timeout=self.timeout)
                    send_frame(self._sock, req)
                    resp = recv_frame(self._sock)
                    if resp is None:
                        raise ConnectionError("channel closed")
                    break
                except (ConnectionError, OSError):
                    self._close_locked()
                    if attempt:
                        raise
        if "error" in resp:
            raise RuntimeError(f"remote error: {resp['error']}")
        return resp

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
