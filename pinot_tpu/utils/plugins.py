"""Plugin registry + loader: the PluginManager/ServiceLoader analog.

Reference parity: pinot-spi plugin/PluginManager.java:52 (plugins loaded
from a directory, each in its own classloader) +
pinot-segment-spi index/IndexPlugin.java (ServiceLoader registration of
index types). Python version: one central registry keyed by
(kind, name); plugins are python modules that call `register(...)` at
import time, loaded either from a plugins directory
(`load_plugin_dir`, the PluginManager directory scan) or by dotted module
path (`load_plugin_module`, the entry-point analog).

Kinds in use:
  'stream'        — StreamConsumerFactory (ingest/stream.py delegates here)
  'fs'            — PinotFS factories by URI scheme (segment/fs.py)
  'input_format'  — record readers (ingest/batch.py)
  'codec'         — chunk compression codecs (segment/codec.py names)
  'index'         — index build/read hooks (segment/index_types.py keys)

Built-ins register through the same seam (the CLP forward index and the
TCP stream connector prove it), so third-party plugins are
indistinguishable from shipped ones.
"""
from __future__ import annotations

import importlib
import importlib.util
import logging
import os
import sys
import threading
from typing import Any, Callable, Dict, List, Tuple

log = logging.getLogger(__name__)

_REGISTRY: Dict[Tuple[str, str], Any] = {}
_LOCK = threading.Lock()


def register(kind: str, name: str, impl: Any) -> None:
    """Register an implementation under (kind, name). Last write wins
    (a user plugin may deliberately override a built-in)."""
    with _LOCK:
        _REGISTRY[(kind, name.lower())] = impl


def get(kind: str, name: str) -> Any:
    with _LOCK:
        impl = _REGISTRY.get((kind, name.lower()))
    if impl is None:
        raise KeyError(
            f"no {kind!r} plugin named {name!r} "
            f"(available: {available(kind)})")
    return impl


def available(kind: str) -> List[str]:
    with _LOCK:
        return sorted(n for k, n in _REGISTRY if k == kind)


def is_registered(kind: str, name: str) -> bool:
    with _LOCK:
        return (kind, name.lower()) in _REGISTRY


def get_or_load(kind: str, name: str) -> Any:
    """get() with a one-shot builtin-plugin load fallback — entry points
    that never called load_builtin_plugins still resolve shipped
    plugins."""
    if not is_registered(kind, name):
        load_builtin_plugins()
    return get(kind, name)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_plugin_module(dotted: str) -> None:
    """Import a plugin by module path; its import-time register() calls
    add it to the registry (the ServiceLoader entry-point analog)."""
    importlib.import_module(dotted)


def load_plugin_dir(plugins_dir: str) -> List[str]:
    """Import every *.py file (or package dir) under plugins_dir — the
    PluginManager directory scan (ref PluginManager.java:54). Returns the
    module names loaded; failures are logged, not fatal (one bad plugin
    must not take the server down)."""
    loaded = []
    if not os.path.isdir(plugins_dir):
        return loaded
    for entry in sorted(os.listdir(plugins_dir)):
        path = os.path.join(plugins_dir, entry)
        name = None
        if entry.endswith(".py"):
            name = entry[:-3]
        elif os.path.isdir(path) and \
                os.path.exists(os.path.join(path, "__init__.py")):
            name = entry
            path = os.path.join(path, "__init__.py")
        if name is None:
            continue
        mod_name = f"pinot_tpu_plugin_{name}"
        try:
            spec = importlib.util.spec_from_file_location(mod_name, path)
            assert spec is not None and spec.loader is not None
            mod = importlib.util.module_from_spec(spec)
            sys.modules[mod_name] = mod
            spec.loader.exec_module(mod)
            loaded.append(mod_name)
        except Exception:  # noqa: BLE001
            # a half-initialized module must not stay importable
            sys.modules.pop(mod_name, None)
            log.exception("failed to load plugin %s", path)
    return loaded


def load_builtin_plugins() -> None:
    """Import the shipped plugin modules so their registrations exist
    (idempotent; called by the package entry points)."""
    for mod in ("pinot_tpu.ingest.tcp_stream",
                "pinot_tpu.segment.clp"):
        try:
            importlib.import_module(mod)
        except Exception:  # noqa: BLE001
            log.exception("builtin plugin %s failed to load", mod)
