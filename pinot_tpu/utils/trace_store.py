"""Per-role trace retention + in-flight query registry + /debug surfaces.

The observability backplane for the distributed tracing layer
(utils/tracing.py):

* ``TraceStore`` — a bounded in-memory ring of finished trace trees per
  role. ``trace=true`` traces and tail-captured slow queries land here;
  ``/debug/traces`` lists them, ``/debug/traces/<id>`` returns one.
* ``InflightRegistry`` — queries currently executing on this role, with
  elapsed time and the phase they're in (parse/route/scatter/gather/
  reduce broker-side; execute server-side). ``/debug/queries`` reads it:
  "what is the broker doing RIGHT NOW" without attaching a debugger.
* ``slow_query_log`` — one structured (JSON) log line per query over the
  slow threshold, trace id included, so production tails are grep-able
  after the fact even when the store has rolled over.
* ``DebugHttpServer`` — a tiny stdlib HTTP surface any role can mount
  (server, minion, cache server: roles with no existing HTTP edge)
  serving /health, /metrics (Prometheus exposition over the role's
  registries) and the /debug endpoints above. The broker and controller
  mount the same payloads into their existing HTTP APIs via
  ``debug_payload``.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence

slow_log = logging.getLogger("pinot_tpu.slowquery")

DEFAULT_CAPACITY = 256


class TraceStore:
    """Bounded FIFO of finished traces for one role (newest kept)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def record(self, trace_id: str, tree: dict, *, sql: str = "",
               duration_ms: float = 0.0, slow: bool = False,
               extra: Optional[dict] = None) -> None:
        entry = {"traceId": trace_id, "sql": sql,
                 "durationMs": round(float(duration_ms), 3),
                 "slow": bool(slow), "storedAt": time.time(),
                 "trace": tree}
        if extra:
            entry.update(extra)
        with self._lock:
            # re-recording (broker stores the sampled trace, then the
            # slow-capture pass fires too) replaces, never duplicates
            self._traces[trace_id] = entry
            self._traces.move_to_end(trace_id)
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            hit = self._traces.get(trace_id)
            if hit is not None:
                return hit
            # instance-suffixed keys (several instances of one role in
            # a single process — the embedded-cluster topology — store
            # under "<traceId>@<instance>" so they don't overwrite each
            # other): fall back to a scan on the recorded traceId
            for e in reversed(self._traces.values()):
                if e.get("traceId") == trace_id:
                    return e
            return None

    def recent(self, limit: int = 50) -> List[dict]:
        """Newest first, trace trees elided (fetch one by id for the
        full tree) — the /debug/traces listing."""
        with self._lock:
            items = list(self._traces.values())[-max(1, int(limit)):]
        out = []
        for e in reversed(items):
            summary = {k: v for k, v in e.items() if k != "trace"}
            out.append(summary)
        return out

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class InflightRegistry:
    """Queries currently executing on this role, with current phase."""

    def __init__(self):
        self._entries: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def begin(self, key: str, *, sql: str = "", trace_id: str = "",
              detail: str = "", tenant: Optional[str] = None,
              deadline: Optional[float] = None) -> None:
        """tenant/deadline: attribution + the absolute wall-clock
        deadline (time.time() domain) — /debug/queries surfaces both so
        an incident responder sees WHOSE query is in flight and how much
        budget it has left, not just how long it has run."""
        with self._lock:
            self._entries[key] = {
                "queryId": key, "sql": sql, "traceId": trace_id,
                "startedAt": time.time(), "phase": "started",
                "detail": detail, "tenant": tenant, "deadline": deadline}

    def phase(self, key: str, phase: str, detail: str = "") -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e["phase"] = phase
                if detail:
                    e["detail"] = detail

    def annotate(self, key: str, *, tenant: Optional[str] = None,
                 deadline: Optional[float] = None) -> None:
        """Late attribution: the broker learns tenant + deadline only
        after parse/route, well inside the entry's lifetime."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if tenant is not None:
                    e["tenant"] = tenant
                if deadline is not None:
                    e["deadline"] = deadline

    def end(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def snapshot(self) -> List[dict]:
        now = time.time()
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
        for e in entries:
            e["elapsedMs"] = round((now - e.pop("startedAt")) * 1000.0, 3)
            deadline = e.pop("deadline", None)
            e["remainingDeadlineMs"] = (
                round((deadline - now) * 1000.0, 3)
                if deadline is not None else None)
        entries.sort(key=lambda e: -e["elapsedMs"])
        return entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- per-role singletons (the get_registry pattern) -------------------------
_stores: Dict[str, TraceStore] = {}
_inflight: Dict[str, InflightRegistry] = {}
_lock = threading.Lock()


def get_store(role: str = "server",
              capacity: Optional[int] = None) -> TraceStore:
    with _lock:
        s = _stores.get(role)
        if s is None:
            s = _stores[role] = TraceStore(capacity or DEFAULT_CAPACITY)
        elif capacity is not None:
            s.capacity = max(1, int(capacity))
        return s


def get_inflight(role: str = "server") -> InflightRegistry:
    with _lock:
        r = _inflight.get(role)
        if r is None:
            r = _inflight[role] = InflightRegistry()
        return r


def log_slow_query(role: str, trace_id: str, sql: str, duration_ms: float,
                   threshold_ms: float, **extra) -> None:
    """One structured line per slow query: grep-able JSON with the trace
    id linking to the stored tree (`/debug/traces/<id>`)."""
    payload = {"role": role, "traceId": trace_id, "sql": sql,
               "durationMs": round(float(duration_ms), 3),
               "thresholdMs": round(float(threshold_ms), 3), **extra}
    slow_log.warning("SLOW_QUERY %s", json.dumps(payload, default=str))


# -- shared HTTP payloads ----------------------------------------------------

def debug_payload(role: str, path: str) -> Optional[Any]:
    """The /debug router shared by every HTTP surface. Returns the JSON
    payload for the path, or None when the path isn't a debug route.
    Health-plane routes (PR 14) import lazily — the trace store must not
    drag the health package in at module import."""
    if path == "/debug/traces":
        return {"role": role, "traces": get_store(role).recent()}
    if path.startswith("/debug/traces/"):
        tid = path[len("/debug/traces/"):]
        entry = get_store(role).get(tid)
        return entry if entry is not None \
            else {"error": f"no trace {tid}", "role": role}
    if path == "/debug/queries":
        return {"role": role, "queries": get_inflight(role).snapshot()}
    if path == "/debug/metrics/sample":
        from pinot_tpu.utils.metrics import get_registry
        return get_registry(role).sample()
    if path == "/debug/metrics/history":
        from pinot_tpu.health.history import get_history
        return {"role": role, "samples": get_history(role).samples()}
    if path == "/debug/health":
        from pinot_tpu.health.rollup import role_health_summary
        return role_health_summary(role)
    if path == "/debug/workload":
        from pinot_tpu.health.workload import get_workload
        return get_workload(role).payload()
    return None


class DebugHttpServer:
    """Tiny ops surface for roles without an HTTP edge (server, minion,
    cache server): /health, /metrics (exposition over the role's
    registries), /debug/traces[/id], /debug/queries."""

    def __init__(self, roles: Sequence[str], host: str = "127.0.0.1",
                 port: int = 0):
        roles = list(roles)
        primary = roles[0] if roles else "server"

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                path = self.path.partition("?")[0].rstrip("/") or "/"
                if path == "/health":
                    body, ctype = b"OK", "text/plain"
                elif path == "/metrics":
                    from pinot_tpu.utils.metrics import get_registry
                    body = b"".join(
                        get_registry(r).prometheus_text().encode()
                        for r in roles)
                    ctype = "text/plain"
                else:
                    payload = debug_payload(primary, path)
                    if payload is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps(payload, default=str).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"debug-http-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        self._thread = None
