"""Per-query operator tracing.

Reference parity: pinot-spi trace/Tracing.java:45 — a registry holding one
Tracer; every operator wraps nextBlock() in an InvocationScope
(core/operator/BaseOperator.java:47) recording operator class + rows/docs;
enabled per query via the trace=true query option and returned in the
broker response. Here a contextvar-scoped trace tree with the same shape.
"""
from __future__ import annotations

import contextvars
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_current: contextvars.ContextVar[Optional["TraceNode"]] = \
    contextvars.ContextVar("pinot_tpu_trace", default=None)


@dataclass
class TraceNode:
    operator: str
    start_ms: float = 0.0
    duration_ms: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)
    children: List["TraceNode"] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"operator": self.operator,
                "durationMs": round(self.duration_ms, 3),
                **self.attrs,
                **({"children": [c.to_dict() for c in self.children]}
                   if self.children else {})}


class Scope:
    """Ref InvocationScope (try-with-resources around nextBlock)."""

    def __init__(self, operator: str, **attrs):
        self.node = TraceNode(operator, attrs=dict(attrs))
        self._token = None
        self._active = False

    def __enter__(self) -> "Scope":
        parent = _current.get()
        if parent is not None:
            parent.children.append(self.node)
            self._token = _current.set(self.node)
            self._active = True
            self.node.start_ms = time.perf_counter() * 1000.0
        return self

    def set(self, **attrs) -> None:
        if self._active:
            self.node.attrs.update(attrs)

    def __exit__(self, *exc):
        if self._active:
            self.node.duration_ms = \
                time.perf_counter() * 1000.0 - self.node.start_ms
            _current.reset(self._token)


class RequestTrace:
    """Root scope for one query; activates tracing for the request."""

    def __init__(self, request_id: int = 0):
        self.root = TraceNode("BrokerRequest", attrs={"requestId": request_id})
        self._token = None

    def __enter__(self) -> "RequestTrace":
        self.root.start_ms = time.perf_counter() * 1000.0
        self._token = _current.set(self.root)
        return self

    def __exit__(self, *exc):
        self.root.duration_ms = \
            time.perf_counter() * 1000.0 - self.root.start_ms
        _current.reset(self._token)

    def to_dict(self) -> dict:
        return self.root.to_dict()


def active() -> bool:
    return _current.get() is not None


def get_attr(name: str, default: Any = None) -> Any:
    """Read an attr off the CURRENT trace node (default when tracing is
    off or the attr is unset) — lets cross-cutting annotators implement
    set-if-absent / dominance rules."""
    node = _current.get()
    return default if node is None else node.attrs.get(name, default)


def annotate(**attrs) -> None:
    """Attach attrs to the CURRENT trace node (no-op when tracing is off).
    Used for cross-cutting marks like cacheHit that belong to whichever
    operator is running, not to a new child scope."""
    node = _current.get()
    if node is not None:
        node.attrs.update(attrs)
