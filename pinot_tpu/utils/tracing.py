"""Per-query distributed tracing: span trees + cross-process propagation.

Reference parity: pinot-spi trace/Tracing.java:45 — a registry holding one
Tracer; every operator wraps nextBlock() in an InvocationScope
(core/operator/BaseOperator.java:47) recording operator class + rows/docs;
enabled per query via the trace=true query option and returned in the
broker response. The reference stops at process edges; here the tree
crosses them:

* ``TraceContext`` (traceId, parent spanId, sampled) travels on every
  wire hop — broker→server requests, MSE ``submit_stage``, cache-fabric
  ops, minion task params — and each remote side opens its OWN span tree
  (``RequestTrace`` with the inherited trace id), shipping it back in
  response metadata so the broker stitches ONE cross-process tree
  (``SpanHandle.graft``).
* ``SpanHandle`` is the explicit thread-safe span API for code that runs
  OFF the request thread (the dispatch ring's launch/fetch pools, the
  broker's scatter fan-out): capture a handle where the contextvar is
  live (``capture()``), attach children/attrs from any thread later.
  Contextvar-scoped ``Scope``/``annotate`` stay for same-thread code.

All tree mutation goes through one module lock: span operations are rare
(tens per query) relative to the work they time, so a coarse lock is
cheaper than per-node locks and makes cross-thread appends race-free.
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_current: contextvars.ContextVar[Optional["TraceNode"]] = \
    contextvars.ContextVar("pinot_tpu_trace", default=None)
_request: contextvars.ContextVar[Optional["RequestTrace"]] = \
    contextvars.ContextVar("pinot_tpu_trace_req", default=None)

#: one lock for ALL tree mutation (child appends, attr updates): handles
#: attach spans from pool threads while the request thread keeps building
_tree_lock = threading.Lock()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


@dataclass
class TraceContext:
    """What crosses a wire hop: enough for the remote side to join the
    trace (trace id), parent its tree (span id), and know whether the
    client asked for the trace back (sampled) — tail capture collects
    either way; sampled only controls the client-visible traceInfo."""

    trace_id: str
    span_id: str = ""
    sampled: bool = False

    def to_wire(self) -> dict:
        return {"traceId": self.trace_id, "spanId": self.span_id,
                "sampled": self.sampled}

    @classmethod
    def from_wire(cls, d: Optional[dict]) -> Optional["TraceContext"]:
        if not d or not d.get("traceId"):
            return None
        return cls(trace_id=str(d["traceId"]),
                   span_id=str(d.get("spanId", "")),
                   sampled=bool(d.get("sampled")))


@dataclass
class TraceNode:
    operator: str
    start_ms: float = 0.0
    duration_ms: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)
    children: List["TraceNode"] = field(default_factory=list)

    def to_dict(self) -> dict:
        with _tree_lock:
            return self._to_dict_locked()

    def _to_dict_locked(self) -> dict:
        return {"operator": self.operator,
                "durationMs": round(self.duration_ms, 3),
                **self.attrs,
                **({"children": [c._to_dict_locked()
                                 for c in self.children]}
                   if self.children else {})}

    @classmethod
    def from_dict(cls, d: dict) -> "TraceNode":
        """Inverse of to_dict — rebuilds a remote side's shipped tree so
        the broker can graft it into its own."""
        attrs = {k: v for k, v in d.items()
                 if k not in ("operator", "durationMs", "children")}
        node = cls(operator=str(d.get("operator", "?")),
                   duration_ms=float(d.get("durationMs", 0.0) or 0.0),
                   attrs=attrs)
        node.children = [cls.from_dict(c) for c in d.get("children", ())]
        return node


class SpanHandle:
    """Explicit thread-safe handle on one span: the capture-and-attach
    API for code paths where contextvars don't flow (the dispatch ring's
    pools, broker fan-out threads, MSE stage threads)."""

    __slots__ = ("node",)

    def __init__(self, node: TraceNode):
        self.node = node

    def child(self, operator: str, **attrs) -> "SpanHandle":
        """Open a child span (timing starts now); end it with .end()."""
        n = TraceNode(operator, attrs=dict(attrs))
        n.start_ms = time.perf_counter() * 1000.0
        with _tree_lock:
            self.node.children.append(n)
        return SpanHandle(n)

    def end(self, **attrs) -> None:
        with _tree_lock:
            if attrs:
                self.node.attrs.update(attrs)
            if self.node.duration_ms == 0.0 and self.node.start_ms:
                self.node.duration_ms = \
                    time.perf_counter() * 1000.0 - self.node.start_ms

    def set(self, **attrs) -> None:
        with _tree_lock:
            self.node.attrs.update(attrs)

    def get(self, name: str, default: Any = None) -> Any:
        with _tree_lock:
            return self.node.attrs.get(name, default)

    @contextlib.contextmanager
    def scope(self, operator: str, **attrs):
        """Context-manager child span on THIS handle (no contextvar):
        thread-safe timing for worker-thread code."""
        h = self.child(operator, **attrs)
        try:
            yield h
        finally:
            h.end()

    def graft(self, tree: Optional[dict]) -> None:
        """Attach a remote side's shipped span tree (to_dict form) as a
        child — the stitch point for cross-process traces."""
        if not tree:
            return
        try:
            node = TraceNode.from_dict(tree)
        except Exception:  # noqa: BLE001 — a torn tree must not fail a query
            return
        with _tree_lock:
            self.node.children.append(node)

    @contextlib.contextmanager
    def activate(self):
        """Make this span the contextvar-current node for the calling
        thread, so same-thread Scope/annotate instrumentation (cache
        tiers, segment executors) lands under it."""
        token = _current.set(self.node)
        try:
            yield self
        finally:
            _current.reset(token)


class Scope:
    """Ref InvocationScope (try-with-resources around nextBlock)."""

    def __init__(self, operator: str, **attrs):
        self.node = TraceNode(operator, attrs=dict(attrs))
        self._token = None
        self._active = False

    def __enter__(self) -> "Scope":
        parent = _current.get()
        if parent is not None:
            with _tree_lock:
                parent.children.append(self.node)
            self._token = _current.set(self.node)
            self._active = True
            self.node.start_ms = time.perf_counter() * 1000.0
        return self

    def set(self, **attrs) -> None:
        if self._active:
            with _tree_lock:
                self.node.attrs.update(attrs)

    def __exit__(self, *exc):
        if self._active:
            self.node.duration_ms = \
                time.perf_counter() * 1000.0 - self.node.start_ms
            _current.reset(self._token)


class RequestTrace:
    """Root span for one request (broker query, server request, MSE
    stage, minion task); activates contextvar tracing for the opening
    thread and carries the trace identity."""

    def __init__(self, request_id: Any = 0, operator: str = "BrokerRequest",
                 trace_id: Optional[str] = None, sampled: bool = True,
                 **attrs):
        self.trace_id = trace_id or new_trace_id()
        #: did the CLIENT ask for the trace back (trace=true)? Tail
        #: capture stores the tree either way; this gates traceInfo.
        self.sampled = sampled
        self.root = TraceNode(operator,
                              attrs={"requestId": request_id,
                                     "traceId": self.trace_id, **attrs})
        self._token = None
        self._req_token = None

    def __enter__(self) -> "RequestTrace":
        self.root.start_ms = time.perf_counter() * 1000.0
        self._token = _current.set(self.root)
        self._req_token = _request.set(self)
        return self

    def __exit__(self, *exc):
        self.root.duration_ms = \
            time.perf_counter() * 1000.0 - self.root.start_ms
        _current.reset(self._token)
        _request.reset(self._req_token)

    def handle(self) -> SpanHandle:
        return SpanHandle(self.root)

    def wire_context(self) -> dict:
        """The TraceContext dict shipped on outgoing hops."""
        return TraceContext(self.trace_id, new_span_id(),
                            self.sampled).to_wire()

    def to_dict(self) -> dict:
        return self.root.to_dict()


def active() -> bool:
    return _current.get() is not None


def capture() -> Optional[SpanHandle]:
    """Thread-safe handle on the CURRENT span (None when tracing is off)
    — capture on the request thread, attach from any thread later."""
    node = _current.get()
    return None if node is None else SpanHandle(node)


def current_trace_id() -> Optional[str]:
    """Trace id of the enclosing RequestTrace (None when untraced) —
    side channels (cache-op headers, task params) stamp it on requests
    so remote logs correlate back to the query."""
    req = _request.get()
    return None if req is None else req.trace_id


def current_request() -> Optional["RequestTrace"]:
    """The enclosing RequestTrace, if the calling thread runs under one
    — lets deep layers (the MSE dispatcher parsing its own options) flip
    `sampled` on the request they ride."""
    return _request.get()


def get_attr(name: str, default: Any = None) -> Any:
    """Read an attr off the CURRENT trace node (default when tracing is
    off or the attr is unset) — lets cross-cutting annotators implement
    set-if-absent / dominance rules."""
    node = _current.get()
    if node is None:
        return default
    with _tree_lock:
        return node.attrs.get(name, default)


def annotate(**attrs) -> None:
    """Attach attrs to the CURRENT trace node (no-op when tracing is off).
    Used for cross-cutting marks like cacheHit that belong to whichever
    operator is running, not to a new child scope."""
    node = _current.get()
    if node is not None:
        with _tree_lock:
            node.attrs.update(attrs)
