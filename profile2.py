import os, sys, time
import numpy as np
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import bench

bench.build_data()
segments = bench.load()
import jax
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.ops import kernels
from pinot_tpu.query.context import QueryContext

engine = TpuOperatorExecutor()
ctx = QueryContext.from_sql(bench.QUERY)
plan, slots = engine._plan(segments, ctx)
cols, params, num_docs, S_real, D = engine._stage(segments, ctx, plan)
kernel = kernels.compiled_kernel(plan)
o = kernel(cols, params, num_docs, D=D); np.asarray(o)  # warm

# 1) fresh dispatch -> np.asarray (what engine.execute does)
for i in range(3):
    t0 = time.perf_counter()
    o = kernel(cols, params, num_docs, D=D)
    t1 = time.perf_counter()
    a = np.asarray(o)
    t2 = time.perf_counter()
    print(f"dispatch {1000*(t1-t0):8.3f} ms   asarray {1000*(t2-t1):8.3f} ms")

# 2) fresh dispatch -> block_until_ready -> asarray
for i in range(3):
    t0 = time.perf_counter()
    o = kernel(cols, params, num_docs, D=D)
    o.block_until_ready()
    t1 = time.perf_counter()
    a = np.asarray(o)
    t2 = time.perf_counter()
    print(f"dispatch+block {1000*(t1-t0):8.3f} ms   asarray {1000*(t2-t1):8.3f} ms")

# 3) deep pipeline: 20 dispatches, then asarray each
t0 = time.perf_counter()
outs = [kernel(cols, params, num_docs, D=D) for _ in range(20)]
t1 = time.perf_counter()
arrs = [np.asarray(o) for o in outs]
t2 = time.perf_counter()
print(f"20 dispatches {1000*(t1-t0):8.3f} ms   20 asarrays {1000*(t2-t1):8.3f} ms"
      f"  -> amortized {1000*(t2-t0)/20:8.3f} ms/query")

# 4) jax.device_get vs np.asarray
o = kernel(cols, params, num_docs, D=D)
t0 = time.perf_counter(); a = jax.device_get(o); t1 = time.perf_counter()
print(f"device_get fresh {1000*(t1-t0):8.3f} ms")
