import os, sys, time
import numpy as np
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import jax, jax.numpy as jnp

# pure link round trip: trivial kernel on tiny data
@jax.jit
def triv(x):
    return x + 1.0

x = jnp.zeros((8,), jnp.float32)
np.asarray(triv(x))  # warm
for i in range(5):
    t0 = time.perf_counter()
    a = np.asarray(triv(x))
    t1 = time.perf_counter()
    print(f"trivial sync {1000*(t1-t0):8.3f} ms")

# medium kernel: reduce 128M f32 (0.5 GB)
big = jax.device_put(np.zeros((16, 8_388_608), np.float32))
@jax.jit
def red(v):
    return jnp.sum(v, axis=1)
np.asarray(red(big))
for i in range(5):
    t0 = time.perf_counter()
    a = np.asarray(red(big))
    t1 = time.perf_counter()
    print(f"0.5GB reduce sync {1000*(t1-t0):8.3f} ms")

# 2.5 GB reduce (5 col equivalents)
bigs = [jax.device_put(np.zeros((16, 8_388_608), np.float32)) for _ in range(5)]
@jax.jit
def red5(vs):
    return sum(jnp.sum(v, axis=1) for v in vs)
np.asarray(red5(bigs))
for i in range(5):
    t0 = time.perf_counter()
    a = np.asarray(red5(bigs))
    t1 = time.perf_counter()
    print(f"2.5GB reduce sync {1000*(t1-t0):8.3f} ms")
