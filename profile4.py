import os, sys, time
from concurrent.futures import ThreadPoolExecutor
import numpy as np
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import jax, jax.numpy as jnp

bigs = [jax.device_put(np.zeros((16, 8_388_608), np.float32)) for _ in range(5)]

@jax.jit
def red5(vs):
    return sum(jnp.sum(v, axis=1) for v in vs)

np.asarray(red5(bigs))

def one(_):
    return np.asarray(red5(bigs))

for nthreads in (1, 2, 4, 8, 16):
    n = nthreads * 4
    with ThreadPoolExecutor(nthreads) as pool:
        list(pool.map(one, range(nthreads)))  # warm
        t0 = time.perf_counter()
        list(pool.map(one, range(n)))
        dt = time.perf_counter() - t0
    print(f"threads={nthreads:3d}  {n:3d} queries in {dt*1000:8.1f} ms  "
          f"-> {dt/n*1000:7.2f} ms/query")

# async fetch: dispatch all, copy_to_host_async all, then gather
n = 16
t0 = time.perf_counter()
outs = [red5(bigs) for _ in range(n)]
for o in outs:
    try:
        o.copy_to_host_async()
    except Exception as e:
        print("copy_to_host_async failed:", e)
        break
arrs = [np.asarray(o) for o in outs]
dt = time.perf_counter() - t0
print(f"async-fetch {n} queries in {dt*1000:8.1f} ms -> {dt/n*1000:7.2f} ms/query")
