"""One-off profiler: break the bench query's wall time into phases.

Phases: parse, plan, stage (steady-state), kernel dispatch->ready, fetch,
assemble, reduce. Also measures amortized pure-kernel time by issuing K
dispatches back-to-back and blocking once (hides link latency).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench  # reuse data builder


def main():
    os.makedirs(bench.DATA_DIR, exist_ok=True)
    bench.build_data()
    segments = bench.load()
    total_rows = sum(s.num_docs for s in segments)
    print(f"total rows: {total_rows:,}", file=sys.stderr)

    import jax
    print("devices:", jax.devices(), file=sys.stderr)

    from pinot_tpu.ops.engine import TpuOperatorExecutor
    from pinot_tpu.ops import kernels
    from pinot_tpu.query.context import QueryContext
    from pinot_tpu.query.reduce import reduce_results

    engine = TpuOperatorExecutor()

    def t(label, fn, n=20):
        # warmup
        fn()
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        dt = (time.perf_counter() - t0) / n * 1000
        print(f"{label:35s} {dt:10.3f} ms")
        return out

    ctx = t("parse", lambda: QueryContext.from_sql(bench.QUERY))
    plan_info = t("plan", lambda: engine._plan(segments, ctx))
    plan, slots_of_fn = plan_info
    staged = t("stage(steady)", lambda: engine._stage(segments, ctx, plan))
    cols, params, num_docs, S_real, D = staged

    kernel = kernels.compiled_kernel(plan)
    # one full dispatch+block
    out = kernel(cols, params, num_docs, D=D)
    out.block_until_ready()

    def dispatch_block():
        o = kernel(cols, params, num_docs, D=D)
        o.block_until_ready()
        return o

    out = t("kernel dispatch+block (1x)", dispatch_block, n=20)

    # amortized: K dispatches, block once
    K = 20
    o = None
    t0 = time.perf_counter()
    for _ in range(K):
        o = kernel(cols, params, num_docs, D=D)
    o.block_until_ready()
    dt = (time.perf_counter() - t0) / K * 1000
    print(f"{'kernel amortized (20 deep)':35s} {dt:10.3f} ms")

    packed = t("fetch np.asarray", lambda: np.asarray(out))
    results = t("assemble", lambda: engine._assemble(
        segments, ctx, plan, packed, S_real, slots_of_fn))
    t("reduce", lambda: reduce_results(ctx, results))

    # full engine.execute for comparison
    def full():
        r, rem = engine.execute(segments, ctx)
        return r
    t("engine.execute full", full, n=10)

    from pinot_tpu.query.executor import QueryExecutor
    ex = QueryExecutor(segments, use_tpu=True, engine=engine)
    t("QueryExecutor.execute full", lambda: ex.execute(bench.QUERY), n=10)

    bw = 5 * total_rows * 4 / 1e9
    print(f"\nbytes touched/query: {bw:.2f} GB")


if __name__ == "__main__":
    main()
