"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host-platform virtual devices (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

NOTE: this environment pre-imports jax at interpreter startup (an
.axon_site sitecustomize), so env vars like JAX_PLATFORMS / XLA_FLAGS set
here are too late — the runtime jax.config.update path is required, and it
works because the backend isn't initialized until first use.
"""
import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the config knob doesn't exist, but the backend is not
    # initialized yet so the XLA flag still takes effect
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 CI")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection suites "
        "(utils/failpoints.py) — seeded and reproducible, so they run in "
        "tier-1; the marker exists to select/deselect them explicitly "
        "(e.g. -m chaos / -m 'not chaos')")


_exit_status = [None]


def pytest_sessionfinish(session, exitstatus):
    _exit_status[0] = int(exitstatus)


def pytest_unconfigure(config):
    """Skip interpreter finalization after the verdict is in.

    A full-suite run occasionally dies with ``terminate called without
    an active exception`` (SIGABRT, exit 134) DURING CPython teardown,
    AFTER pytest has printed its summary — an XLA/TSL C++ worker thread
    being finalized mid-flight, not a test failure. Exiting hard with
    pytest's own status (recorded in sessionfinish; unconfigure runs
    after the terminal summary prints) preserves the real verdict and
    sidesteps the native teardown entirely (the standard JAX-suite
    workaround). Set PINOT_TPU_SOFT_EXIT=1 to restore normal
    finalization (e.g. for coverage/profiling runs that need atexit
    hooks)."""
    if os.environ.get("PINOT_TPU_SOFT_EXIT") == "1" \
            or _exit_status[0] is None:
        return
    import sys
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_exit_status[0])
