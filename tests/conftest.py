"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host-platform virtual devices (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before the first `import jax` anywhere in the test session.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
