"""Single-node query-correctness harness.

Reference parity: pinot-core BaseQueriesTest
(src/test/java/org/apache/pinot/queries/BaseQueriesTest.java:74) — build
real segments in-process from synthetic rows, run full server-side planning
+ execution + broker reduce in one process with no networking. The TPU
twist: every query runs through BOTH the numpy reference executor and the
device engine, and results must agree (the CPU-parity harness SURVEY.md §7.3
calls for).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.query.reduce import BrokerResponse
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegment, load_segment


def build_segments(tmp_path, schema: Schema, table_config: TableConfig,
                   columns_per_segment: Sequence[Dict[str, list]],
                   ) -> List[ImmutableSegment]:
    creator = SegmentCreator(table_config, schema)
    segs = []
    for i, cols in enumerate(columns_per_segment):
        d = str(tmp_path / f"seg_{i}")
        creator.build(cols, d, f"testTable_{i}")
        segs.append(load_segment(d))
    return segs


class QueriesTestHarness:
    """getBrokerResponse twice (CPU ref + TPU) and assert equality."""

    def __init__(self, segments: List[ImmutableSegment]):
        self.cpu = QueryExecutor(segments, use_tpu=False)
        self.tpu = QueryExecutor(segments, use_tpu=True)

    def broker_response(self, sql: str, check_parity: bool = True) -> BrokerResponse:
        cpu_resp = self.cpu.execute(sql)
        if check_parity:
            tpu_resp = self.tpu.execute(sql)
            assert_responses_equal(cpu_resp, tpu_resp, sql)
        return cpu_resp

    def tpu_response(self, sql: str) -> BrokerResponse:
        return self.tpu.execute(sql)


def assert_responses_equal(a: BrokerResponse, b: BrokerResponse, sql: str,
                           ordered: Optional[bool] = None) -> None:
    ra, rb = a.result_table, b.result_table
    assert (ra is None) == (rb is None), f"one response empty for {sql!r}"
    if ra is None:
        return
    assert ra.columns == rb.columns, f"column mismatch for {sql!r}"
    rows_a, rows_b = ra.rows, rb.rows
    if ordered is None:
        ordered = "order by" in sql.lower()
    if not ordered:
        rows_a = sorted(rows_a, key=_row_key)
        rows_b = sorted(rows_b, key=_row_key)
    assert len(rows_a) == len(rows_b), \
        f"row count mismatch for {sql!r}: {len(rows_a)} != {len(rows_b)}"
    for i, (x, y) in enumerate(zip(rows_a, rows_b)):
        assert len(x) == len(y), f"row width mismatch at {i} for {sql!r}"
        for va, vb in zip(x, y):
            assert values_equal(va, vb), \
                f"value mismatch for {sql!r} row {i}: {x} != {y}"


def values_equal(a, b, rel: float = 1e-9) -> bool:
    if isinstance(a, float) or isinstance(b, float):
        try:
            fa, fb = float(a), float(b)
        except (TypeError, ValueError):
            return a == b
        if math.isnan(fa) and math.isnan(fb):
            return True
        if math.isinf(fa) or math.isinf(fb):
            return fa == fb
        return math.isclose(fa, fb, rel_tol=rel, abs_tol=1e-9)
    return a == b


def _row_key(row):
    return tuple(str(v) for v in row)


# ---------------------------------------------------------------------------
# canonical synthetic table (the baseballStats-like fixture)
# ---------------------------------------------------------------------------

def synthetic_schema() -> Schema:
    return Schema("testTable", [
        FieldSpec("intCol", DataType.INT, FieldType.DIMENSION),
        FieldSpec("longCol", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("floatCol", DataType.FLOAT, FieldType.METRIC),
        FieldSpec("doubleCol", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("stringCol", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("groupCol", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("rawIntCol", DataType.INT, FieldType.METRIC),
    ])


def synthetic_table_config() -> TableConfig:
    tc = TableConfig("testTable", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["rawIntCol"]
    tc.indexing.inverted_index_columns = ["stringCol"]
    tc.indexing.range_index_columns = ["intCol"]
    return tc


def synthetic_columns(num_docs: int, seed: int) -> Dict[str, list]:
    rng = np.random.default_rng(seed)
    ints = rng.integers(0, 1000, num_docs).astype(np.int32)
    return {
        "intCol": ints,
        "longCol": rng.integers(0, 10**12, num_docs).astype(np.int64),
        "floatCol": rng.random(num_docs).astype(np.float32) * 100,
        "doubleCol": rng.random(num_docs) * 1000,
        "stringCol": [f"s{v % 37}" for v in ints.tolist()],
        "groupCol": [f"g{v % 11}" for v in ints.tolist()],
        "rawIntCol": rng.integers(-500, 500, num_docs).astype(np.int32),
    }
