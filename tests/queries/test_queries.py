"""Query correctness: CPU reference vs TPU device path vs hand-computed
expectations (the BaseQueriesTest-style suite, SURVEY.md §4.2)."""
import math

import numpy as np
import pytest

from tests.queries.harness import (
    QueriesTestHarness, build_segments, synthetic_columns, synthetic_schema,
    synthetic_table_config)

NUM_DOCS = 2000
NUM_SEGMENTS = 3


@pytest.fixture(scope="module")
def data():
    return [synthetic_columns(NUM_DOCS, seed=42 + i) for i in range(NUM_SEGMENTS)]


@pytest.fixture(scope="module")
def harness(tmp_path_factory, data):
    tmp = tmp_path_factory.mktemp("queries")
    segs = build_segments(tmp, synthetic_schema(), synthetic_table_config(), data)
    return QueriesTestHarness(segs)


@pytest.fixture(scope="module")
def all_rows(data):
    """Concatenated raw columns across segments for oracle computation."""
    out = {}
    for k in data[0]:
        parts = [np.asarray(d[k]) for d in data]
        out[k] = np.concatenate(parts)
    return out


class TestAggregation:
    def test_count_star(self, harness, all_rows):
        r = harness.broker_response("SELECT COUNT(*) FROM testTable")
        assert r.rows[0][0] == NUM_DOCS * NUM_SEGMENTS

    def test_sum(self, harness, all_rows):
        r = harness.broker_response("SELECT SUM(intCol) FROM testTable")
        assert r.rows[0][0] == pytest.approx(float(all_rows["intCol"].sum()))

    def test_min_max_avg(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT MIN(doubleCol), MAX(doubleCol), AVG(doubleCol) FROM testTable")
        assert r.rows[0][0] == pytest.approx(all_rows["doubleCol"].min())
        assert r.rows[0][1] == pytest.approx(all_rows["doubleCol"].max())
        assert r.rows[0][2] == pytest.approx(all_rows["doubleCol"].mean())

    def test_filtered_sum(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT SUM(intCol) FROM testTable WHERE intCol BETWEEN 100 AND 500")
        v = all_rows["intCol"]
        expected = float(v[(v >= 100) & (v <= 500)].sum())
        assert r.rows[0][0] == pytest.approx(expected)

    def test_filter_eq_string(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE stringCol = 's5'")
        s = np.asarray(all_rows["stringCol"])
        assert r.rows[0][0] == int((s == "s5").sum())

    def test_filter_in(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE stringCol IN ('s1', 's2', 's3')")
        s = np.asarray(all_rows["stringCol"])
        assert r.rows[0][0] == int(np.isin(s, ["s1", "s2", "s3"]).sum())

    def test_filter_not_in(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE stringCol NOT IN ('s1', 's2')")
        s = np.asarray(all_rows["stringCol"])
        assert r.rows[0][0] == int((~np.isin(s, ["s1", "s2"])).sum())

    def test_filter_ne(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE groupCol != 'g3'")
        s = np.asarray(all_rows["groupCol"])
        assert r.rows[0][0] == int((s != "g3").sum())

    def test_filter_and_or_not(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE (intCol < 200 OR intCol > 800) "
            "AND NOT groupCol = 'g1'")
        v, g = all_rows["intCol"], np.asarray(all_rows["groupCol"])
        expected = int((((v < 200) | (v > 800)) & (g != "g1")).sum())
        assert r.rows[0][0] == expected

    def test_filter_on_raw_column(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*), SUM(rawIntCol) FROM testTable WHERE rawIntCol >= 0")
        v = all_rows["rawIntCol"]
        assert r.rows[0][0] == int((v >= 0).sum())
        assert r.rows[0][1] == pytest.approx(float(v[v >= 0].sum()))

    def test_sum_product_expression(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT SUM(intCol * rawIntCol) FROM testTable WHERE intCol < 500")
        a, b = all_rows["intCol"].astype(np.float64), all_rows["rawIntCol"]
        expected = float((a * b)[all_rows["intCol"] < 500].sum())
        assert r.rows[0][0] == pytest.approx(expected)

    def test_like(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE stringCol LIKE 's1%'")
        s = np.asarray(all_rows["stringCol"])
        expected = int(sum(1 for x in s.tolist() if str(x).startswith("s1")))
        assert r.rows[0][0] == expected

    def test_empty_result(self, harness):
        r = harness.broker_response(
            "SELECT SUM(intCol), COUNT(*) FROM testTable WHERE intCol > 100000")
        assert r.rows[0][1] == 0

    def test_minmaxrange(self, harness, all_rows):
        r = harness.broker_response("SELECT MINMAXRANGE(intCol) FROM testTable")
        v = all_rows["intCol"]
        assert r.rows[0][0] == pytest.approx(float(v.max() - v.min()))

    def test_post_aggregation_arithmetic(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT SUM(intCol) / COUNT(*) FROM testTable")
        v = all_rows["intCol"]
        assert r.rows[0][0] == pytest.approx(v.sum() / len(v))


class TestGroupBy:
    def test_group_by_sum(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT groupCol, SUM(intCol) FROM testTable GROUP BY groupCol "
            "ORDER BY groupCol LIMIT 100")
        g = np.asarray(all_rows["groupCol"])
        v = all_rows["intCol"]
        expected = {key: float(v[g == key].sum()) for key in np.unique(g)}
        assert len(r.rows) == len(expected)
        for key, total in r.rows:
            assert total == pytest.approx(expected[key])

    def test_group_by_multi_col(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT groupCol, stringCol, COUNT(*) FROM testTable "
            "GROUP BY groupCol, stringCol ORDER BY COUNT(*) DESC, groupCol, stringCol "
            "LIMIT 20")
        g = np.asarray(all_rows["groupCol"])
        s = np.asarray(all_rows["stringCol"])
        from collections import Counter
        counts = Counter(zip(g.tolist(), s.tolist()))
        top = r.rows[0]
        assert top[2] == max(counts.values())

    def test_group_by_having(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT groupCol, COUNT(*) FROM testTable GROUP BY groupCol "
            "HAVING COUNT(*) > 100 ORDER BY groupCol LIMIT 100")
        g = np.asarray(all_rows["groupCol"])
        from collections import Counter
        counts = Counter(g.tolist())
        expected = {k: c for k, c in counts.items() if c > 100}
        assert {row[0]: row[1] for row in r.rows} == expected

    def test_group_by_with_filter(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT groupCol, AVG(doubleCol) FROM testTable WHERE intCol >= 250 "
            "GROUP BY groupCol ORDER BY groupCol LIMIT 100")
        g = np.asarray(all_rows["groupCol"])
        v, d = all_rows["intCol"], all_rows["doubleCol"]
        for key, avg in r.rows:
            m = (g == key) & (v >= 250)
            assert avg == pytest.approx(d[m].mean())

    def test_group_by_order_by_agg_desc_limit(self, harness):
        r = harness.broker_response(
            "SELECT groupCol, SUM(intCol) FROM testTable GROUP BY groupCol "
            "ORDER BY SUM(intCol) DESC LIMIT 3")
        sums = [row[1] for row in r.rows]
        assert sums == sorted(sums, reverse=True)
        assert len(r.rows) == 3


class TestHostOnlyAggregations:
    def test_distinctcount(self, harness, all_rows):
        r = harness.broker_response("SELECT DISTINCTCOUNT(stringCol) FROM testTable")
        assert r.rows[0][0] == len(np.unique(np.asarray(all_rows["stringCol"])))

    def test_count_distinct_rewrite(self, harness, all_rows):
        r = harness.broker_response("SELECT COUNT(DISTINCT stringCol) FROM testTable")
        assert r.rows[0][0] == len(np.unique(np.asarray(all_rows["stringCol"])))

    def test_distinctcounthll_close(self, harness, all_rows):
        r = harness.broker_response("SELECT DISTINCTCOUNTHLL(longCol) FROM testTable")
        exact = len(np.unique(all_rows["longCol"]))
        assert abs(r.rows[0][0] - exact) / exact < 0.1

    def test_percentile(self, harness, all_rows):
        r = harness.broker_response("SELECT PERCENTILE(doubleCol, 90) FROM testTable")
        v = np.sort(all_rows["doubleCol"])
        expected = v[min(int(len(v) * 0.9), len(v) - 1)]
        assert r.rows[0][0] == pytest.approx(float(expected))

    def test_percentile_legacy_name(self, harness, all_rows):
        r = harness.broker_response("SELECT PERCENTILE50(doubleCol) FROM testTable")
        v = np.sort(all_rows["doubleCol"])
        assert r.rows[0][0] == pytest.approx(float(v[len(v) // 2]))

    def test_percentile_tdigest_close(self, harness, all_rows):
        # host and device digests differ within sketch error (the device
        # path feeds histogram partials), so compare each to exact truth
        # rather than to each other
        sql = "SELECT PERCENTILETDIGEST(doubleCol, 95) FROM testTable"
        r = harness.broker_response(sql, check_parity=False)
        rt = harness.tpu_response(sql)
        exact = np.quantile(all_rows["doubleCol"], 0.95)
        assert abs(r.rows[0][0] - exact) / exact < 0.02
        assert abs(rt.rows[0][0] - exact) / abs(exact) < 0.02

    def test_mode(self, harness, all_rows):
        r = harness.broker_response("SELECT MODE(intCol) FROM testTable")
        v, c = np.unique(all_rows["intCol"], return_counts=True)
        best = v[c == c.max()].min()
        assert r.rows[0][0] == pytest.approx(float(best))


class TestSelection:
    def test_select_star_limit(self, harness):
        r = harness.broker_response("SELECT * FROM testTable LIMIT 5",
                                    check_parity=False)
        assert len(r.rows) == 5
        assert len(r.result_table.columns) == 7

    def test_select_columns_where(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT intCol, stringCol FROM testTable WHERE intCol = 77 LIMIT 10000",
            check_parity=False)
        v = all_rows["intCol"]
        assert len(r.rows) == int((v == 77).sum())
        assert all(row[0] == 77 for row in r.rows)

    def test_select_order_by(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT intCol FROM testTable ORDER BY intCol DESC LIMIT 10",
            check_parity=False)
        v = np.sort(all_rows["intCol"])[::-1][:10]
        assert [row[0] for row in r.rows] == v.tolist()

    def test_select_order_by_multi(self, harness):
        r = harness.broker_response(
            "SELECT groupCol, intCol FROM testTable "
            "ORDER BY groupCol ASC, intCol DESC LIMIT 20", check_parity=False)
        rows = r.rows
        for i in range(1, len(rows)):
            assert rows[i - 1][0] <= rows[i][0]
            if rows[i - 1][0] == rows[i][0]:
                assert rows[i - 1][1] >= rows[i][1]

    def test_select_transform(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT intCol + 1 FROM testTable ORDER BY intCol LIMIT 3",
            check_parity=False)
        v = np.sort(all_rows["intCol"])[:3] + 1
        assert [row[0] for row in r.rows] == v.tolist()

    def test_distinct(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT DISTINCT groupCol FROM testTable ORDER BY groupCol LIMIT 100",
            check_parity=False)
        expected = sorted(set(np.asarray(all_rows["groupCol"]).tolist()))
        assert [row[0] for row in r.rows] == expected

    def test_offset(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT intCol FROM testTable ORDER BY intCol LIMIT 5 OFFSET 10",
            check_parity=False)
        v = np.sort(all_rows["intCol"])[10:15]
        assert [row[0] for row in r.rows] == v.tolist()


class TestResponseMetadata:
    def test_stats(self, harness):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE intCol > 500")
        assert r.stats.total_docs == NUM_DOCS * NUM_SEGMENTS
        assert r.stats.num_segments_processed == NUM_SEGMENTS
        assert 0 < r.stats.num_docs_scanned < NUM_DOCS * NUM_SEGMENTS

    def test_pruning(self, harness):
        # intCol max < 1000, so this prunes every segment
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE intCol > 5000",
            check_parity=False)
        assert r.rows[0][0] == 0

    def test_to_dict_roundtrip(self, harness):
        r = harness.broker_response("SELECT COUNT(*) FROM testTable")
        d = r.to_dict()
        assert d["resultTable"]["rows"][0][0] == NUM_DOCS * NUM_SEGMENTS
        assert d["totalDocs"] == NUM_DOCS * NUM_SEGMENTS


class TestReviewRegressions:
    """Regressions from code-review findings."""

    def test_expression_filter_first_and_operand(self, harness, all_rows):
        # value-space masks must be writable for in-place AND combining
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE intCol + 0 > 500 AND intCol < 900",
            check_parity=False)
        v = all_rows["intCol"]
        assert r.rows[0][0] == int(((v > 500) & (v < 900)).sum())

    def test_column_to_column_predicate(self, harness, all_rows):
        # non-literal rhs must fall back to value-space evaluation
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE intCol = rawIntCol",
            check_parity=False)
        assert r.rows[0][0] == int(
            (all_rows["intCol"] == all_rows["rawIntCol"]).sum())

    def test_filtered_aggregation(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT SUM(intCol) FILTER (WHERE intCol > 500), COUNT(*) "
            "FROM testTable", check_parity=False)
        v = all_rows["intCol"]
        assert r.rows[0][0] == pytest.approx(float(v[v > 500].sum()))
        assert r.rows[0][1] == len(v)

    def test_filtered_aggregation_group_by(self, harness, all_rows):
        r = harness.broker_response(
            "SELECT groupCol, COUNT(*) FILTER (WHERE intCol < 100) FROM testTable "
            "GROUP BY groupCol ORDER BY groupCol LIMIT 100", check_parity=False)
        g = np.asarray(all_rows["groupCol"])
        v = all_rows["intCol"]
        for key, cnt in r.rows:
            assert cnt == int(((g == key) & (v < 100)).sum())

    def test_all_segments_pruned_stats(self, harness):
        r = harness.broker_response(
            "SELECT COUNT(*) FROM testTable WHERE intCol > 5000",
            check_parity=False)
        assert r.stats.num_segments_pruned == NUM_SEGMENTS
        assert r.stats.total_docs == NUM_DOCS * NUM_SEGMENTS
        assert r.rows[0][0] == 0
