"""Funnel, arrayagg, tuple-sketch, and gapfill aggregation families.

Ref: pinot-core query/aggregation/function/FunnelCountAggregationFunction,
ArrayAggFunction, DistinctCountTupleSketchAggregationFunction;
query/reduce/ GapfillProcessor — VERDICT r4 missing #9 / task 10.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment


@pytest.fixture(scope="module")
def events_seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("funnel")
    schema = Schema("ev", [
        FieldSpec("user_id", DataType.INT, FieldType.DIMENSION),
        FieldSpec("action", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("ts", DataType.INT, FieldType.DIMENSION),
    ])
    tc = TableConfig(name="ev")
    # users 0-9 view; 0-5 cart; 0-2 buy; user 11 carts WITHOUT viewing
    rows = []
    for u in range(10):
        rows.append((u, "view", u))
    for u in range(6):
        rows.append((u, "cart", 100 + u))
    for u in range(3):
        rows.append((u, "buy", 200 + u))
    rows.append((11, "cart", 300))
    cols = {"user_id": np.array([r[0] for r in rows]),
            "action": np.array([r[1] for r in rows], object),
            "ts": np.array([r[2] for r in rows])}
    out = str(tmp / "s0")
    SegmentCreator(tc, schema).build(cols, out, "s0")
    return load_segment(out)


class TestFunnel:
    def test_funnelcount(self, events_seg):
        ex = QueryExecutor([events_seg], use_tpu=False)
        r = ex.execute(
            "SELECT FUNNELCOUNT(user_id, action = 'view', "
            "action = 'cart', action = 'buy') FROM ev")
        assert r.rows[0][0] == [10, 6, 3]

    def test_funnel_requires_earlier_steps(self, events_seg):
        # user 11 carted without viewing: step-2 count excludes them
        ex = QueryExecutor([events_seg], use_tpu=False)
        r = ex.execute(
            "SELECT FUNNELCOUNT(user_id, action = 'view', "
            "action = 'cart') FROM ev")
        assert r.rows[0][0] == [10, 6]

    def test_funnelcompletecount(self, events_seg):
        ex = QueryExecutor([events_seg], use_tpu=False)
        r = ex.execute(
            "SELECT FUNNELCOMPLETECOUNT(user_id, action = 'view', "
            "action = 'cart', action = 'buy') FROM ev")
        assert r.rows[0][0] == 3

    def test_funnel_multi_segment_merge(self, events_seg, tmp_path):
        # second segment: user 6 completes cart+buy (viewed in seg 1)
        schema = Schema("ev", [
            FieldSpec("user_id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("action", DataType.STRING, FieldType.DIMENSION),
            FieldSpec("ts", DataType.INT, FieldType.DIMENSION)])
        tc = TableConfig(name="ev")
        cols = {"user_id": np.array([6, 6]),
                "action": np.array(["cart", "buy"], object),
                "ts": np.array([400, 401])}
        out = str(tmp_path / "s1")
        SegmentCreator(tc, schema).build(cols, out, "s1")
        seg2 = load_segment(out)
        ex = QueryExecutor([events_seg, seg2], use_tpu=False)
        r = ex.execute(
            "SELECT FUNNELCOUNT(user_id, action = 'view', "
            "action = 'cart', action = 'buy') FROM ev")
        assert r.rows[0][0] == [10, 7, 4]


class TestArrayAgg:
    def test_arrayagg_grouped(self, events_seg):
        ex = QueryExecutor([events_seg], use_tpu=False)
        r = ex.execute(
            "SELECT action, ARRAYAGG(user_id) FROM ev "
            "GROUP BY action ORDER BY action")
        got = {row[0]: sorted(row[1]) for row in r.rows}
        assert got["buy"] == [0, 1, 2]
        assert got["cart"] == [0, 1, 2, 3, 4, 5, 11]

    def test_tuple_sketch_alias(self, events_seg):
        ex = QueryExecutor([events_seg], use_tpu=False)
        r = ex.execute(
            "SELECT DISTINCTCOUNTTUPLESKETCH(user_id) FROM ev")
        assert r.rows[0][0] == 11


class TestGapfill:
    def test_gapfill_previous_and_zero(self, tmp_path):
        schema = Schema("m", [
            FieldSpec("bucket", DataType.INT, FieldType.DIMENSION),
            FieldSpec("host", DataType.STRING, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig(name="m")
        # host a has buckets 0, 20; host b has 10 only
        cols = {"bucket": np.array([0, 20, 10]),
                "host": np.array(["a", "a", "b"], object),
                "v": np.array([5, 7, 9])}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        sql = ("SET gapfillTimeCol = bucket; SET gapfillStart = 0; "
               "SET gapfillEnd = 30; SET gapfillStep = 10; "
               "SET gapfillMode = PREVIOUS; "
               "SELECT bucket, host, SUM(v) FROM m "
               "GROUP BY bucket, host LIMIT 100")
        r = ex.execute(sql)
        rows = {(row[1], row[0]): row[2] for row in r.rows}
        assert rows[("a", 0)] == 5.0
        assert rows[("a", 10)] == 5.0   # filled with previous
        assert rows[("a", 20)] == 7.0
        assert rows[("b", 10)] == 9.0
        assert rows[("b", 0)] is None   # no previous yet
        assert rows[("b", 20)] == 9.0
        assert len(r.rows) == 6


class TestGapfillEdges:
    def test_off_grid_rows_kept(self, tmp_path):
        schema = Schema("m2", [
            FieldSpec("bucket", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig(name="m2")
        cols = {"bucket": np.array([5, 35]), "v": np.array([1, 2])}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        sql = ("SET gapfillTimeCol = bucket; SET gapfillStart = 0; "
               "SET gapfillEnd = 30; SET gapfillStep = 10; "
               "SET gapfillMode = ZERO; "
               "SELECT bucket, SUM(v) FROM m2 GROUP BY bucket "
               "ORDER BY bucket LIMIT 100")
        r = ex.execute(sql)
        got = {row[0]: row[1] for row in r.rows}
        # real off-grid rows survive; grid gaps filled with 0
        assert got[5] == 1.0 and got[35] == 2.0
        assert got[0] == 0 and got[10] == 0 and got[20] == 0
        # ordered by bucket including filled rows
        assert [row[0] for row in r.rows] == sorted(got)


class TestGapfillGuards:
    def test_unselected_group_col_bails(self, tmp_path):
        schema = Schema("m3", [
            FieldSpec("bucket", DataType.INT, FieldType.DIMENSION),
            FieldSpec("host", DataType.STRING, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig(name="m3")
        cols = {"bucket": np.array([0, 0, 10]),
                "host": np.array(["a", "b", "b"], object),
                "v": np.array([5, 7, 9])}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        base = ("SET gapfillTimeCol = bucket; SET gapfillStart = 0; "
                "SET gapfillEnd = 30; SET gapfillStep = 10; ")
        # host is grouped but NOT selected: gapfill must bail, keeping
        # ALL three rows (no silent collapse)
        r = ex.execute(base + "SELECT bucket, SUM(v) FROM m3 "
                              "GROUP BY bucket, host LIMIT 100")
        assert sorted(row[1] for row in r.rows) == [5.0, 7.0, 9.0]
        # ORDER BY an unselected column under gapfill: no crash
        r2 = ex.execute(base + "SELECT bucket, SUM(v) FROM m3 "
                               "GROUP BY bucket, host "
                               "ORDER BY host LIMIT 100")
        assert len(r2.rows) == 3

    def test_grid_bomb_skipped(self, tmp_path):
        schema = Schema("m4", [
            FieldSpec("bucket", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig(name="m4")
        cols = {"bucket": np.array([0]), "v": np.array([1])}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SET gapfillTimeCol = bucket; SET gapfillStart = 0; "
                       "SET gapfillEnd = 1000000000; SET gapfillStep = 1; "
                       "SELECT bucket, SUM(v) FROM m4 GROUP BY bucket "
                       "LIMIT 10")
        assert len(r.rows) == 1  # fill skipped, data intact
