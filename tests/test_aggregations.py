"""New aggregation-function coverage: moments, covariance, with-time,
histogram, bool folds, distinct folds, theta/KLL sketches, MV family —
each parity-checked host-vs-device (where a device spec exists) and
against numpy oracles; wire serde round-trips for the new sketch types.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.query.aggregation.sketches import KLLSketch, ThetaSketch
from pinot_tpu.server import datatable
from pinot_tpu.query.results import AggregationResult, ExecutionStats
from tests.queries.harness import build_segments

N = 3000


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("aggseg")
    schema = Schema("testTable", [
        FieldSpec("x", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("y", DataType.DOUBLE, FieldType.METRIC),
        FieldSpec("ts", DataType.INT, FieldType.DIMENSION),
        FieldSpec("grp", DataType.INT, FieldType.DIMENSION),
        FieldSpec("flag", DataType.INT, FieldType.DIMENSION),
        FieldSpec("tags", DataType.INT, FieldType.DIMENSION,
                  single_value=False),
    ])
    tc = TableConfig("testTable", TableType.OFFLINE)
    rng0 = np.random.default_rng(100)
    cols = []
    for i in range(2):
        rng = np.random.default_rng(100 + i)
        cols.append({
            "x": rng.normal(50, 10, N),
            "y": rng.normal(5, 2, N),
            "ts": rng.permutation(N).astype(np.int32) + i * N,
            "grp": rng.integers(0, 7, N).astype(np.int32),
            "flag": rng.integers(0, 2, N).astype(np.int32),
            "tags": [rng.integers(0, 50, rng.integers(1, 5)).tolist()
                     for _ in range(N)],
        })
    segs = build_segments(tmp, schema, tc, cols)
    all_cols = {k: (np.concatenate([np.asarray(c[k]) for c in cols])
                    if k != "tags" else
                    [t for c in cols for t in c["tags"]])
                for k in cols[0]}
    return segs, all_cols


def _one_row(segs, sql):
    cpu = QueryExecutor(segs, use_tpu=False)
    tpu = QueryExecutor(segs, use_tpu=True)
    a, b = cpu.execute(sql), tpu.execute(sql)
    assert not a.exceptions and not b.exceptions, (a.exceptions, b.exceptions)
    for x, y in zip(a.rows[0], b.rows[0]):
        if isinstance(x, float) and isinstance(y, float):
            assert abs(x - y) <= 1e-4 * max(1.0, abs(x)), (sql, a.rows, b.rows)
        else:
            assert x == y, (sql, a.rows, b.rows)
    return a.rows[0]


class TestMoments:
    def test_variance_stddev(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT VAR_POP(x), VAR_SAMP(x), STDDEV_POP(x), "
                     "STDDEV_SAMP(x) FROM testTable")
        x = cols["x"]
        assert abs(r[0] - np.var(x)) < 1e-6 * np.var(x)
        assert abs(r[1] - np.var(x, ddof=1)) < 1e-6 * np.var(x)
        assert abs(r[2] - np.std(x)) < 1e-6 * np.std(x)
        assert abs(r[3] - np.std(x, ddof=1)) < 1e-6 * np.std(x)

    def test_skew_kurtosis(self, segs):
        segs, cols = segs
        r = _one_row(segs, "SELECT SKEWNESS(x), KURTOSIS(x) FROM testTable")
        x = cols["x"]
        m = x.mean()
        m2 = ((x - m) ** 2).mean()
        skew = ((x - m) ** 3).mean() / m2 ** 1.5
        kurt = ((x - m) ** 4).mean() / m2 ** 2 - 3
        assert abs(r[0] - skew) < 1e-3
        assert abs(r[1] - kurt) < 1e-3

    def test_variance_group_by(self, segs):
        segs, cols = segs
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True)
        sql = ("SELECT grp, VAR_POP(x), STDDEV_SAMP(x) FROM testTable "
               "GROUP BY grp ORDER BY grp LIMIT 10")
        a, b = cpu.execute(sql), tpu.execute(sql)
        assert len(a.rows) == len(b.rows) == 7
        for ra, rb in zip(a.rows, b.rows):
            assert ra[0] == rb[0]
            assert abs(ra[1] - rb[1]) < 1e-4 * max(1.0, abs(ra[1]))
        x, g = cols["x"], cols["grp"]
        for row in a.rows:
            want = np.var(x[g == row[0]])
            assert abs(row[1] - want) < 1e-6 * max(1.0, want)

    def test_variance_filtered(self, segs):
        segs, cols = segs
        r = _one_row(segs, "SELECT VAR_POP(x) FILTER (WHERE flag = 1), "
                           "COUNT(*) FROM testTable")
        x, f = cols["x"], cols["flag"]
        want = np.var(x[f == 1])
        assert abs(r[0] - want) < 1e-6 * want


class TestCovariance:
    def test_covar(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT COVAR_POP(x, y), COVAR_SAMP(x, y) FROM testTable")
        x, y = cols["x"], cols["y"]
        pop = np.cov(x, y, ddof=0)[0, 1]
        samp = np.cov(x, y, ddof=1)[0, 1]
        assert abs(r[0] - pop) < 1e-6 * max(1.0, abs(pop))
        assert abs(r[1] - samp) < 1e-6 * max(1.0, abs(samp))

    def test_covar_group_by(self, segs):
        segs, cols = segs
        cpu = QueryExecutor(segs, use_tpu=False)
        resp = cpu.execute("SELECT grp, COVAR_POP(x, y) FROM testTable "
                           "GROUP BY grp ORDER BY grp LIMIT 10")
        x, y, g = cols["x"], cols["y"], cols["grp"]
        for row in resp.rows:
            sel = g == row[0]
            want = np.cov(x[sel], y[sel], ddof=0)[0, 1]
            assert abs(row[1] - want) < 1e-6 * max(1.0, abs(want))


class TestWithTime:
    def test_first_last(self, segs):
        segs, cols = segs
        r = _one_row(segs, "SELECT FIRSTWITHTIME(x, ts, 'DOUBLE'), "
                           "LASTWITHTIME(x, ts, 'DOUBLE') FROM testTable")
        x, ts = cols["x"], cols["ts"]
        assert abs(r[0] - x[np.argmin(ts)]) < 1e-9
        assert abs(r[1] - x[np.argmax(ts)]) < 1e-9

    def test_last_group_by(self, segs):
        segs, cols = segs
        cpu = QueryExecutor(segs, use_tpu=False)
        resp = cpu.execute("SELECT grp, LASTWITHTIME(x, ts, 'DOUBLE') "
                           "FROM testTable GROUP BY grp ORDER BY grp LIMIT 10")
        x, ts, g = cols["x"], cols["ts"], cols["grp"]
        for row in resp.rows:
            sel = np.nonzero(g == row[0])[0]
            want = x[sel[np.argmax(ts[sel])]]
            assert abs(row[1] - want) < 1e-9


class TestHistogramBoolDistinct:
    def test_histogram(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT HISTOGRAM(x, 0, 100, 10) FROM testTable")
        want, _ = np.histogram(cols["x"], bins=np.linspace(0, 100, 11))
        assert [int(v) for v in r[0]] == want.tolist()

    def test_bool_folds(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT BOOL_AND(flag), BOOL_OR(flag) FROM testTable")
        assert r[0] == bool(np.all(cols["flag"])) \
            and r[1] == bool(np.any(cols["flag"]))
        r2 = _one_row(segs, "SELECT BOOL_AND(flag), BOOL_OR(flag) "
                            "FROM testTable WHERE flag = 1")
        assert r2[0] is True and r2[1] is True

    def test_distinct_folds(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT DISTINCTSUM(grp), DISTINCTAVG(grp) FROM testTable")
        u = np.unique(cols["grp"])
        assert abs(r[0] - u.sum()) < 1e-9
        assert abs(r[1] - u.mean()) < 1e-9


class TestSketches:
    def test_theta(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT DISTINCTCOUNTTHETASKETCH(ts) FROM testTable")
        true = len(np.unique(cols["ts"]))
        assert abs(r[0] - true) <= 0.05 * true

    def test_kll(self, segs):
        segs, cols = segs
        r = _one_row(segs, "SELECT PERCENTILEKLL(x, 90) FROM testTable")
        want = np.quantile(cols["x"], 0.9)
        assert abs(r[0] - want) < 0.05 * abs(want)
        r2 = _one_row(segs, "SELECT PERCENTILEKLL50(x) FROM testTable")
        assert abs(r2[0] - np.quantile(cols["x"], 0.5)) < 0.05 * 50

    def test_sketch_serde_roundtrip(self):
        rng = np.random.default_rng(0)
        t = ThetaSketch(1024)
        t.add_array(rng.integers(0, 10**6, 50000))
        k = KLLSketch(200)
        k.add_array(rng.random(50000))
        r = AggregationResult([t, k], ExecutionStats())
        buf = datatable.serialize_results([r])
        [out], exc, _ = datatable.deserialize_results(buf)
        assert not exc
        t2, k2 = out.intermediates
        assert t2.estimate() == t.estimate()
        assert abs(k2.quantile(0.5) - k.quantile(0.5)) < 1e-9
        # merged across the wire stays usable
        assert t2.merge(t).estimate() == t.estimate()


class TestMVFamily:
    def test_mv_aggs(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT SUMMV(tags), MINMV(tags), MAXMV(tags), "
                     "AVGMV(tags), MINMAXRANGEMV(tags), "
                     "DISTINCTCOUNTMV(tags), COUNTMV(tags) FROM testTable")
        flat = np.concatenate([np.asarray(t) for t in cols["tags"]])
        assert abs(r[0] - flat.sum()) < 1e-6 * abs(flat.sum())
        assert r[1] == flat.min() and r[2] == flat.max()
        assert abs(r[3] - flat.mean()) < 1e-9
        assert r[4] == flat.max() - flat.min()
        assert r[5] == len(np.unique(flat))
        assert r[6] == len(flat)

    def test_mv_group_by(self, segs):
        segs, cols = segs
        cpu = QueryExecutor(segs, use_tpu=False)
        resp = cpu.execute("SELECT grp, SUMMV(tags) FROM testTable "
                           "GROUP BY grp ORDER BY grp LIMIT 10")
        g = np.asarray(cols["grp"])
        for row in resp.rows:
            want = sum(sum(t) for t, gi in zip(cols["tags"], g)
                       if gi == row[0])
            assert abs(row[1] - want) < 1e-6 * max(1.0, abs(want))

    def test_mv_with_filter(self, segs):
        segs, cols = segs
        r = _one_row(segs,
                     "SELECT SUMMV(tags) FROM testTable WHERE flag = 1")
        g = np.asarray(cols["flag"])
        want = sum(sum(t) for t, f in zip(cols["tags"], g) if f == 1)
        assert abs(r[0] - want) < 1e-6 * max(1.0, abs(want))
