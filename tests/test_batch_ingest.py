"""Batch ingestion job + CLI admin (ref LaunchDataIngestionJob flow)."""
import json
import os

import numpy as np
import pytest

from pinot_tpu.ingest.batch import (
    IngestionJobSpec, read_records, run_ingestion_job)
from pinot_tpu.models import (DataType, FieldSpec, FieldType, IngestionConfig,
                              Schema, TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.loader import load_segment


def make_schema():
    return Schema("bt", [
        FieldSpec("name", DataType.STRING),
        FieldSpec("score", DataType.INT, FieldType.METRIC),
        FieldSpec("bonus", DataType.DOUBLE, FieldType.METRIC),
    ])


class TestReaders:
    def test_csv(self, tmp_path):
        p = tmp_path / "a.csv"
        p.write_text("name,score,bonus\nalice,10,1.5\nbob,20,\n")
        rows = list(read_records(str(p)))
        assert rows == [{"name": "alice", "score": "10", "bonus": "1.5"},
                        {"name": "bob", "score": "20", "bonus": None}]

    def test_jsonl(self, tmp_path):
        p = tmp_path / "a.jsonl"
        p.write_text('{"name": "x", "score": 1}\n{"name": "y", "score": 2}\n')
        assert len(list(read_records(str(p)))) == 2

    def test_json_array(self, tmp_path):
        p = tmp_path / "a.json"
        p.write_text('[{"name": "x"}, {"name": "y"}]')
        assert len(list(read_records(str(p)))) == 2


class TestIngestionJob:
    def test_csv_to_segments_to_query(self, tmp_path):
        for i in range(2):
            (tmp_path / f"in_{i}.csv").write_text(
                "name,score,bonus\n" +
                "\n".join(f"n{j},{j},{j}.5" for j in range(100)) + "\n")
        tc = TableConfig("bt", TableType.OFFLINE)
        spec = IngestionJobSpec(
            input_pattern=str(tmp_path / "in_*.csv"),
            output_dir=str(tmp_path / "out"),
            table_config=tc, schema=make_schema())
        dirs = run_ingestion_job(spec)
        assert len(dirs) == 2  # one per file
        segs = [load_segment(d) for d in dirs]
        ex = QueryExecutor(segs, use_tpu=False)
        r = ex.execute("SELECT COUNT(*), SUM(score) FROM bt")
        assert r.rows[0][0] == 200
        assert r.rows[0][1] == pytest.approx(2 * sum(range(100)))

    def test_rows_per_segment_split(self, tmp_path):
        (tmp_path / "in.csv").write_text(
            "name,score,bonus\n" +
            "\n".join(f"n{j},{j},0.0" for j in range(250)) + "\n")
        tc = TableConfig("bt", TableType.OFFLINE)
        spec = IngestionJobSpec(
            input_pattern=str(tmp_path / "in.csv"),
            output_dir=str(tmp_path / "out"),
            table_config=tc, schema=make_schema(), rows_per_segment=100)
        dirs = run_ingestion_job(spec)
        assert len(dirs) == 3  # 100 + 100 + 50
        assert sum(load_segment(d).num_docs for d in dirs) == 250

    def test_transforms_and_filter_applied(self, tmp_path):
        (tmp_path / "in.jsonl").write_text(
            "\n".join(json.dumps({"name": f"n{j}", "score": j})
                      for j in range(50)))
        tc = TableConfig("bt", TableType.OFFLINE)
        tc.ingestion = IngestionConfig(
            transform_configs=[
                {"columnName": "bonus", "transformFunction": "score * 2"}],
            filter_function="score >= 25")
        spec = IngestionJobSpec(
            input_pattern=str(tmp_path / "in.jsonl"),
            output_dir=str(tmp_path / "out"),
            table_config=tc, schema=make_schema())
        dirs = run_ingestion_job(spec)
        seg = load_segment(dirs[0])
        assert seg.num_docs == 25  # score >= 25 dropped
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SELECT SUM(bonus) FROM bt")
        assert r.rows[0][0] == pytest.approx(2.0 * sum(range(25)))


class TestAdminCli:
    def test_ingest_and_post_query_flow(self, tmp_path):
        from pinot_tpu.tools import admin
        (tmp_path / "data.csv").write_text(
            "name,score,bonus\n" +
            "\n".join(f"n{j},{j},1.0" for j in range(30)) + "\n")
        (tmp_path / "table.json").write_text(json.dumps(
            TableConfig("bt", TableType.OFFLINE).to_dict()))
        (tmp_path / "schema.json").write_text(json.dumps(
            make_schema().to_dict()))
        rc = admin.main([
            "LaunchDataIngestionJob",
            "--table", str(tmp_path / "table.json"),
            "--schema", str(tmp_path / "schema.json"),
            "--input", str(tmp_path / "data.csv"),
            "--output", str(tmp_path / "segments")])
        assert rc == 0
        assert os.path.isdir(tmp_path / "segments" / "bt_0")

    def test_quickstart_exits_cleanly(self):
        from pinot_tpu.tools import admin
        rc = admin.main(["Quickstart", "--rows", "5000", "--no-tpu",
                         "--exit-after-queries", "--port", "0"])
        assert rc == 0


class TestNullSemantics:
    """SQL null handling in the transform pipeline (review round-5):
    simple predicates over NULL keep the row, OR with a TRUE branch still
    drops, expressions over NULL yield NULL, coalesce short-circuits."""

    def _pipeline(self, filter_fn=None, transforms=None):
        from pinot_tpu.ingest.transforms import TransformPipeline
        from pinot_tpu.models import (DataType, FieldSpec, FieldType,
                                      Schema, TableConfig)
        from pinot_tpu.models.table_config import IngestionConfig
        schema = Schema("t", [
            FieldSpec("a", DataType.INT, FieldType.DIMENSION),
            FieldSpec("b", DataType.INT, FieldType.DIMENSION),
            FieldSpec("c", DataType.INT, FieldType.DIMENSION)])
        tc = TableConfig(name="t")
        tc.ingestion = IngestionConfig(
            filter_function=filter_fn,
            transform_configs=transforms or [])
        return TransformPipeline(tc, schema)

    def test_simple_filter_over_null_keeps_row(self):
        p = self._pipeline(filter_fn="a > 100")
        assert p.transform({"a": None, "b": 1}) is not None
        assert p.transform({"a": 200, "b": 1}) is None  # dropped

    def test_or_filter_with_true_branch_drops(self):
        p = self._pipeline(filter_fn="a = 1 OR b = 2")
        assert p.transform({"a": 1, "b": None}) is None   # TRUE OR NULL
        assert p.transform({"a": 3, "b": None}) is not None

    def test_expression_over_null_yields_default(self):
        p = self._pipeline(transforms=[
            {"columnName": "c", "transformFunction": "a * 2"}])
        out = p.transform({"a": None, "b": 0})
        assert out["c"] is None  # null -> creator default fills

    def test_coalesce_short_circuits_and_propagates(self):
        p = self._pipeline(transforms=[
            {"columnName": "c", "transformFunction": "coalesce(a, b + 1)"}])
        assert p.transform({"a": 7, "b": None})["c"] == 7
        assert p.transform({"a": None, "b": 4})["c"] == 5
        assert p.transform({"a": None, "b": None})["c"] is None
