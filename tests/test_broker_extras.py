"""Adaptive server selection, query quotas, python client, controller REST.

Ref: pinot-broker routing/adaptiveserverselector/, queryquota/
HelixExternalViewBasedQueryQuotaManager.java, pinot-clients/
pinot-java-client + jdbc-client, pinot-controller api/resources/ —
VERDICT r4 missing #7/#8 territory + §2.1 client/controller surfaces.
"""
import json
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.broker.adaptive import AdaptiveServerSelector
from pinot_tpu.broker.quota import QueryQuotaManager


class TestAdaptiveSelector:
    def test_prefers_fast_server(self):
        sel = AdaptiveServerSelector()
        for _ in range(5):
            sel.record_start("slow")
            sel.record_end("slow", 1.0)
            sel.record_start("fast")
            sel.record_end("fast", 0.01)
        picks = {sel.pick(["slow", "fast"], set(), rr=i)
                 for i in range(4)}
        assert picks == {"fast"}

    def test_inflight_pressure(self):
        sel = AdaptiveServerSelector(mode="inflight")
        sel.record_start("busy")
        sel.record_start("busy")
        assert sel.pick(["busy", "idle"], set()) == "idle"

    def test_unhealthy_skipped_and_cold_round_robin(self):
        sel = AdaptiveServerSelector()
        assert sel.pick(["a", "b"], {"a"}) == "b"
        cold = {sel.pick(["a", "b"], set(), rr=i) for i in range(2)}
        assert cold == {"a", "b"}  # tie-broken round robin


class TestQuota:
    def test_bucket_limits_and_refills(self):
        q = QueryQuotaManager()
        q.set_quota("t", 2.0)
        assert q.try_acquire("t")
        assert q.try_acquire("t")
        assert not q.try_acquire("t")  # bucket drained
        time.sleep(0.6)
        assert q.try_acquire("t")      # ~1 token refilled
        q.set_quota("t", None)
        for _ in range(10):
            assert q.try_acquire("t")  # unlimited again

    def test_quota_rejects_in_broker(self):
        from pinot_tpu.broker.request_handler import BrokerRequestHandler
        from pinot_tpu.broker.routing import BrokerRoutingManager
        quotas = QueryQuotaManager()
        quotas.set_quota("t", 1.0)
        h = BrokerRequestHandler(BrokerRoutingManager(), {},
                                 quota_manager=quotas)
        r1 = h.handle("SELECT COUNT(*) FROM t")   # table missing: 190
        r2 = h.handle("SELECT COUNT(*) FROM t")   # quota gone: 429
        codes = [x["errorCode"] for x in r1.exceptions + r2.exceptions]
        assert 429 in codes


@pytest.fixture(scope="module")
def mini_http(tmp_path_factory):
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    tmp = tmp_path_factory.mktemp("client")
    schema = Schema("ev", [
        FieldSpec("id", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC)])
    tc = TableConfig(name="ev")
    c = MiniCluster(num_servers=1, use_tpu=False)
    c.start(with_http=True)
    c.add_table("ev")
    out = str(tmp / "s0")
    SegmentCreator(tc, schema).build(
        {"id": np.arange(100), "v": np.arange(100) * 2}, out, "s0")
    c.add_segment("ev", load_segment(out), server_idx=0)
    yield c
    c.stop()


class TestPythonClient:
    def test_execute_and_cursor(self, mini_http):
        from pinot_tpu.client import PinotClientError, connect
        conn = connect(f"127.0.0.1:{mini_http.http.port}")
        rs = conn.execute("SELECT COUNT(*), SUM(v) FROM ev")
        assert rs.rows[0] == [100, 9900.0]
        assert rs.columns == ["count(*)", "sum(v)"]
        cur = conn.cursor()
        cur.execute("SELECT id FROM ev WHERE id < %(lim)s ORDER BY id "
                    "LIMIT 10", {"lim": 3})
        assert cur.fetchall() == [[0], [1], [2]]
        assert cur.description[0][0] == "id"
        with pytest.raises(PinotClientError):
            conn.execute("SELECT * FROM missing_table")

    def test_string_param_quoting(self, mini_http):
        from pinot_tpu.client.connection import _quote
        assert _quote("o'brien") == "'o''brien'"
        assert _quote(None) == "null"
        assert _quote(True) == "true"


class TestControllerRest:
    def test_rest_surface(self, tmp_path):
        from pinot_tpu.controller.cluster_state import ClusterState
        from pinot_tpu.controller.coordination import CoordinationServer
        from pinot_tpu.controller.http_api import ControllerHttpServer
        from pinot_tpu.models import (DataType, FieldSpec, FieldType,
                                      Schema, TableConfig)
        from pinot_tpu.segment.creator import SegmentCreator
        state = ClusterState()
        coord = CoordinationServer(state)
        rest = ControllerHttpServer(state, coordination=coord)
        rest.start()
        base = f"http://127.0.0.1:{rest.port}"
        try:
            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return json.loads(r.read())

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            assert get("/health") == {"status": "OK"}
            assert get("/tables") == {"tables": []}
            schema = Schema("t", [
                FieldSpec("a", DataType.INT, FieldType.DIMENSION)])
            cfg = TableConfig(name="t")
            post("/tables", {"tableConfig": cfg.to_dict(),
                             "schema": schema.to_dict()})
            assert get("/tables") == {"tables": ["t"]}
            assert get("/tables/t")["schema"]["schemaName"] == "t"
            # register a server instance + upload a segment via REST
            from pinot_tpu.controller.cluster_state import InstanceState
            state.register_instance(InstanceState("s0"))
            seg_dir = str(tmp_path / "seg")
            SegmentCreator(cfg, schema).build(
                {"a": np.arange(10)}, seg_dir, "t_0")
            r = post("/tables/t/segments", {"segDir": seg_dir})
            assert r["segment"]["instances"] == ["s0"]
            segs = get("/tables/t/segments")
            assert "t_0" in segs["t_OFFLINE"]
            # delete
            req = urllib.request.Request(base + "/tables/t",
                                         method="DELETE")
            with urllib.request.urlopen(req, timeout=10) as resp:
                json.loads(resp.read())
            assert get("/tables") == {"tables": []}
        finally:
            rest.stop()
            coord.stop()


class TestPinotConfiguration:
    def test_layering(self, tmp_path, monkeypatch):
        from pinot_tpu.utils.config import KEYS, PinotConfiguration
        props = tmp_path / "server.properties"
        props.write_text("# instance config\n"
                         "pinot.server.query.scheduler=priority\n"
                         "pinot.server.query.num.threads: 4\n")
        cfg = PinotConfiguration(str(props))
        # file beats catalog default
        assert cfg.get_str("pinot.server.query.scheduler") == "priority"
        assert cfg.get_int("pinot.server.query.num.threads") == 4
        # catalog default when unset anywhere
        assert cfg.get_int("pinot.broker.http.port") == 8099
        # env beats file (relaxed name mapping)
        monkeypatch.setenv("PINOT_TPU_SERVER_QUERY_SCHEDULER", "binary")
        assert cfg.get_str("pinot.server.query.scheduler") == "binary"
        # explicit overrides beat env
        cfg2 = PinotConfiguration(
            str(props),
            overrides={"pinot.server.query.scheduler": "fcfs"})
        assert cfg2.get_str("pinot.server.query.scheduler") == "fcfs"
        # subset view
        sub = cfg.subset("pinot.server.query.")
        assert int(sub["num.threads"]) == 4
        assert set(KEYS) >= {"pinot.server.query.port"}

    def test_bools_and_missing(self):
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = PinotConfiguration(
            overrides={"x.flag": "Yes", "y.flag": "0"})
        assert cfg.get_bool("x.flag") is True
        assert cfg.get_bool("y.flag") is False
        assert cfg.get("not.a.key", "dflt") == "dflt"

    def test_server_scheduler_from_config(self):
        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.query_server import (QueryServer,
                                                   ServerQueryExecutor)
        from pinot_tpu.server.scheduler import make_scheduler
        srv = QueryServer(
            ServerQueryExecutor(InstanceDataManager("x"), use_tpu=False),
            scheduler="priority", num_threads=2)
        try:
            assert type(srv.scheduler) is type(
                make_scheduler("priority", 2))
        finally:
            srv.scheduler.stop()


class TestAdaptiveHedgeTail:
    """latency_quantile feeds the hedge delay from TRUE per-request tails
    (pooled per-server Timer reservoirs), not p95-of-EWMA smoothed means
    (ISSUE 4 satellite / ROADMAP reliability follow-up)."""

    def test_quantile_sees_tail_requests_ewma_hides(self):
        sel = AdaptiveServerSelector(alpha=0.3)
        # 99 fast requests + 1 huge spike on one server: an EWMA ending
        # on fast traffic forgets the spike entirely
        for i in range(99):
            sel.record_start("s1")
            sel.record_end("s1", 0.010)
        sel.record_start("s1")
        sel.record_end("s1", 2.0)
        for _ in range(20):
            sel.record_start("s1")
            sel.record_end("s1", 0.010)
        # the smoothed mean is far below the spike...
        assert sel._ewma["s1"] < 0.1
        # ...but the per-request p99+ still carries it
        assert sel.latency_quantile(0.999) == pytest.approx(2.0)
        # and the p50 stays at the fast floor (hedges don't fire early)
        assert sel.latency_quantile(0.5) == pytest.approx(0.010)

    def test_quantile_pools_across_servers(self):
        sel = AdaptiveServerSelector()
        for _ in range(10):
            sel.record_start("fast")
            sel.record_end("fast", 0.01)
            sel.record_start("slow")
            sel.record_end("slow", 0.2)
        q95 = sel.latency_quantile(0.95)
        assert q95 == pytest.approx(0.2)
        assert sel.latency_quantile(0.0) <= 0.01 + 1e-9

    def test_zero_until_observed(self):
        assert AdaptiveServerSelector().latency_quantile(0.95) == 0.0
