"""Distributed cache fabric (pinot_tpu/cache/remote|tiered|warmup):
cache-server role, tiered L1/L2 backends, circuit breaker, segment
warmup replay, hybrid offline-partial caching, epoch memoization.

The hard parts covered explicitly: a cache-server outage must degrade to
local-only with ZERO failed queries (breaker open -> half-open -> closed
on recovery), concurrent SET/GET on one key must never return a torn
payload, and replicas must serve hits for work only a sibling performed.
"""
import threading
import time

import numpy as np
import pytest

from pinot_tpu.cache import (CacheServer, FingerprintLog, LruTtlCache,
                             RemoteCacheBackend, SegmentResultCache,
                             TieredCache, segment_version)
from pinot_tpu.cache.core import (wire_dumps_response, wire_dumps_results,
                                  wire_loads_response, wire_loads_results)
from pinot_tpu.cache.remote import (CIRCUIT_CLOSED, CIRCUIT_HALF_OPEN,
                                    CIRCUIT_OPEN, CircuitBreaker)
from pinot_tpu.cache.segment_cache import segment_remote_key
from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.models import Schema, TableConfig
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration


def _schema():
    return Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})


def _table_config():
    return TableConfig.from_dict({"tableName": "t", "tableType": "OFFLINE"})


def _build(tmp_path, name, d, m):
    out = str(tmp_path / name)
    SegmentCreator(_table_config(), _schema()).build(
        {"d": np.asarray(d, np.int64), "m": np.asarray(m, np.int64)},
        out, name)
    return load_segment(out)


@pytest.fixture()
def cache_server():
    s = CacheServer(max_bytes=8 << 20, ttl_seconds=60.0)
    s.start()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
class TestCacheServerProtocol:
    def test_get_set_delete_stats_roundtrip(self, cache_server):
        be = RemoteCacheBackend(cache_server.address)
        try:
            assert be.ping()
            assert be.get("k") is None          # miss on empty
            assert be.put("k", b"payload")
            assert be.get("k") == b"payload"
            st = be.stats()
            assert st["entries"] == 1 and st["hits"] == 1
            assert be.delete("k")
            assert be.get("k") is None
            assert be.put("a", b"1") and be.put("b", b"2")
            assert be.clear()
            assert be.stats()["entries"] == 0
        finally:
            be.close()

    def test_delete_is_keyed_not_a_scan(self, cache_server):
        be = RemoteCacheBackend(cache_server.address)
        try:
            be.put("a", b"xx")
            be.put("b", b"yy")
            assert be.delete("a")
            assert be.get("a") is None and be.get("b") == b"yy"
            # O(1) keyed remove on the underlying cache
            assert not cache_server.cache.remove("a")   # already gone
            assert cache_server.cache.remove("b")
            assert cache_server.cache.size_bytes == 0
        finally:
            be.close()

    def test_per_entry_ttl(self, cache_server):
        be = RemoteCacheBackend(cache_server.address)
        try:
            be.put("short", b"x", ttl_seconds=0.05)
            be.put("long", b"y")                # server default: 60s
            assert be.get("short") == b"x"
            time.sleep(0.12)
            assert be.get("short") is None      # expired server-side
            assert be.get("long") == b"y"
        finally:
            be.close()

    def test_bad_op_and_bad_key_are_refused_not_fatal(self, cache_server):
        import socket

        from pinot_tpu.utils.netframe import recv_frame, send_frame
        sock = socket.create_connection(
            (cache_server.host, cache_server.port), timeout=2)
        try:
            send_frame(sock, {"op": "bogus"})
            assert recv_frame(sock)["ok"] is False
            send_frame(sock, {"op": "get", "key": 123})  # non-string key
            assert recv_frame(sock) == {"ok": True, "hit": False}
            # the connection survived both
            send_frame(sock, {"op": "ping"})
            assert recv_frame(sock)["ok"] is True
        finally:
            sock.close()


class TestCircuitBreaker:
    def test_transitions_closed_open_halfopen_closed(self):
        t = [0.0]
        br = CircuitBreaker(failure_threshold=3, reset_seconds=5.0,
                            clock=lambda: t[0])
        assert br.state == CIRCUIT_CLOSED and br.allow()
        br.record_failure()
        br.record_failure()
        assert br.state == CIRCUIT_CLOSED      # below threshold
        br.record_failure()
        assert br.state == CIRCUIT_OPEN
        assert not br.allow()                  # open: reject fast
        t[0] = 5.1
        assert br.state == CIRCUIT_HALF_OPEN
        assert br.allow()                      # exactly ONE probe
        assert not br.allow()                  # second caller still held
        br.record_success()
        assert br.state == CIRCUIT_CLOSED and br.allow()

    def test_failed_probe_restarts_cooldown(self):
        t = [0.0]
        br = CircuitBreaker(failure_threshold=1, reset_seconds=5.0,
                            clock=lambda: t[0])
        br.record_failure()
        assert br.state == CIRCUIT_OPEN
        t[0] = 5.1
        assert br.allow()                      # half-open probe
        br.record_failure()                    # probe failed
        assert br.state == CIRCUIT_OPEN
        t[0] = 9.0                             # inside the NEW window
        assert not br.allow()
        t[0] = 10.3
        assert br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failure_threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()                    # consecutive run broken
        br.record_failure()
        br.record_failure()
        assert br.state == CIRCUIT_CLOSED


class TestRemoteBackendResilience:
    @staticmethod
    def _dead_address() -> str:
        # a port nothing listens on anymore
        srv = CacheServer()
        srv.start()
        addr = srv.address
        srv.stop()
        return addr

    def test_unreachable_server_never_raises(self):
        addr = self._dead_address()
        be = RemoteCacheBackend(addr, timeout_seconds=0.5,
                                failure_threshold=3, reset_seconds=60.0)
        try:
            for _ in range(4):
                assert be.get("k") is None
                assert not be.put("k", b"v")
            assert be.breaker.state == CIRCUIT_OPEN
            assert be.errors >= 3
            # open circuit: requests are rejected without touching a socket
            t0 = time.perf_counter()
            assert be.get("k") is None
            assert time.perf_counter() - t0 < 0.1
        finally:
            be.close()

    def test_oversized_payload_refused_client_side(self, cache_server):
        from pinot_tpu.utils.netframe import MAX_FRAME
        be = RemoteCacheBackend(cache_server.address)
        try:
            class _Huge(bytes):
                def __len__(self):
                    return MAX_FRAME + 1
            assert not be.put("k", _Huge())
            assert be.breaker.state == CIRCUIT_CLOSED  # no failure recorded
        finally:
            be.close()

    def test_breaker_state_exported_as_gauge(self):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("fabric_test")
        be = RemoteCacheBackend(self._dead_address(), timeout_seconds=0.3,
                                failure_threshold=1, reset_seconds=60.0,
                                metrics=m, labels={"role": "t"})
        try:
            be.get("k")
            assert be.breaker.state == CIRCUIT_OPEN
            text = m.prometheus_text()
            assert "remote_cache_breaker_state" in text
            assert 'remote_cache_breaker_state{role="t"} 2' in text
        finally:
            be.close()


# ---------------------------------------------------------------------------
class TestTieredCache:
    def test_l2_hit_backfills_l1(self, cache_server):
        str_key = lambda k: str(k)  # noqa: E731
        a = TieredCache(LruTtlCache(1 << 20, 60),
                        RemoteCacheBackend(cache_server.address), str_key)
        b = TieredCache(LruTtlCache(1 << 20, 60),
                        RemoteCacheBackend(cache_server.address), str_key)
        try:
            a.put("k", b"shared")
            # b never stored it: L1 miss, L2 hit, L1 back-fill
            payload, tier = b.get_with_tier("k")
            assert payload == b"shared" and tier == "L2"
            assert b.l1.get("k") == b"shared"
            payload, tier = b.get_with_tier("k")
            assert tier == "L1"                # RTT paid exactly once
        finally:
            a.close()
            b.close()

    def test_non_shareable_keys_stay_local(self, cache_server):
        tc = TieredCache(LruTtlCache(1 << 20, 60),
                         RemoteCacheBackend(cache_server.address),
                         lambda k: None)       # nothing is shareable
        try:
            tc.put("k", b"private")
            assert tc.get("k") == b"private"   # L1 serves it
            assert cache_server.cache.stats.puts == 0  # never hit the wire
        finally:
            tc.close()

    def test_backfill_inherits_remaining_l2_ttl(self, cache_server):
        """An L2 hit back-fills L1 with the entry's REMAINING TTL — a
        fresh full TTL would stretch the staleness budget up to 2x
        (TTL is the only freshness bound for cache_realtime tables)."""
        a = RemoteCacheBackend(cache_server.address)
        b = TieredCache(LruTtlCache(1 << 20, 60),
                        RemoteCacheBackend(cache_server.address), str)
        try:
            a.put("k", b"v", ttl_seconds=0.15)
            payload, tier = b.get_with_tier("k")
            assert payload == b"v" and tier == "L2"
            time.sleep(0.2)
            # without TTL inheritance this would live 60s in b's L1
            assert b.l1.get("k") is None
        finally:
            a.close()
            b.close()

    def test_local_clear_spares_the_shared_tier(self, cache_server):
        tc = TieredCache(LruTtlCache(1 << 20, 60),
                         RemoteCacheBackend(cache_server.address), str)
        try:
            tc.put("k", b"v")
            tc.clear()                         # routine local clear
            assert len(tc.l1) == 0
            assert tc.get("k") == b"v"         # L2 still warm
            tc.clear(remote=True)
            assert tc.l2.get("k") is None
        finally:
            tc.close()


class TestTornPayloads:
    def test_concurrent_set_get_one_key_never_torn(self, cache_server):
        """Satellite: hammer one key from writer + reader threads through
        real sockets; every read must be a WHOLE payload, never a splice
        of two writes."""
        patterns = [bytes([0x41 + i]) * 4096 for i in range(4)]
        be = RemoteCacheBackend(cache_server.address, pool_size=4)
        be.put("k", patterns[0])
        stop = threading.Event()
        errs = []

        def writer(idx):
            i = idx
            while not stop.is_set():
                be.put("k", patterns[i % len(patterns)])
                i += 1

        def reader():
            while not stop.is_set():
                got = be.get("k")
                if got is not None and got not in patterns:
                    errs.append(got[:8])
                    return

        threads = [threading.Thread(target=writer, args=(i,), daemon=True)
                   for i in range(2)]
        threads += [threading.Thread(target=reader, daemon=True)
                    for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        be.close()
        assert not errs, f"torn payload observed: {errs}"


# ---------------------------------------------------------------------------
class TestWireCodec:
    """Satellite: payloads crossing the wire use the typed DataTable serde
    (a shared store must never feed pickle.loads), and an undecodable
    entry is a MISS, never an exception."""

    def _resp(self):
        from pinot_tpu.query.reduce import BrokerResponse, ResultTable
        r = BrokerResponse(result_table=ResultTable(
            ["d", "cnt"], ["LONG", "LONG"], [(1, 10), (2, 20)]))
        r.num_servers_queried = 2
        r.num_servers_responded = 2
        r.stats.num_docs_scanned = 42
        return r

    def test_response_roundtrip(self):
        payload = wire_dumps_response(self._resp())
        assert payload is not None and payload[:1] == b"B"
        back = wire_loads_response(payload)
        assert back.result_table.rows == [(1, 10), (2, 20)]
        assert back.result_table.columns == ["d", "cnt"]
        assert back.num_servers_queried == 2
        assert back.stats.num_docs_scanned == 42

    def test_results_roundtrip(self):
        from pinot_tpu.query.results import AggregationResult, ExecutionStats
        res = AggregationResult([3.0], ExecutionStats(num_docs_scanned=7))
        payload = wire_dumps_results([res])
        assert payload is not None and payload[:1] == b"R"
        back = wire_loads_results(payload)
        assert len(back) == 1
        assert back[0].intermediates == [3.0]
        assert back[0].stats.num_docs_scanned == 7

    def test_results_roundtrip_with_server_stats(self):
        from pinot_tpu.cache.core import wire_loads_results_stats
        from pinot_tpu.query.results import AggregationResult, ExecutionStats
        res = AggregationResult([1.0], ExecutionStats())
        extra = ExecutionStats(num_segments_pruned=5)
        payload = wire_dumps_results([res], extra_stats=extra)
        back, stats = wire_loads_results_stats(payload)
        assert len(back) == 1
        assert stats.num_segments_pruned == 5

    def test_undecodable_entries_fall_through(self):
        import pickle
        for garbage in (b"", b"Rjunk", b"Bjunk", b"\x00\x01\x02",
                        pickle.dumps({"poisoned": True})):
            assert wire_loads_results(garbage) is None
            assert wire_loads_response(garbage) is None

    def test_unencodable_objects_skip_caching(self):
        assert wire_dumps_results([object()]) is None
        assert wire_dumps_response(object()) is None

    def test_tiered_segment_cache_treats_garbage_as_miss(self, cache_server,
                                                         tmp_path):
        seg = _build(tmp_path, "wc0", range(10), range(10))
        backend = TieredCache(LruTtlCache(1 << 20, 60),
                              RemoteCacheBackend(cache_server.address),
                              segment_remote_key)
        sc = SegmentResultCache(backend=backend)
        fp = QueryContext.from_sql("SELECT SUM(m) FROM t").fingerprint()
        rkey = segment_remote_key((seg.name, segment_version(seg), fp))
        assert rkey is not None                # crc-versioned: shareable
        backend.l2.put(rkey, b"corrupted entry")
        assert sc.get(seg, fp) is None         # miss, not an exception
        backend.close()

    def test_generation_stamped_segments_never_shared(self):
        # non-crc versions are process-local counters: sharing them would
        # alias different contents across instances
        assert segment_remote_key(("s", ("gen", 3), "fp")) is None
        assert segment_remote_key(("s", ("id", 12345), "fp")) is None
        assert segment_remote_key(("s", ("crc", 99), "fp")) is not None


# ---------------------------------------------------------------------------
class TestEpochMemoization:
    """Satellite: epoch() hashes the segment set once per mutation, not
    once per cacheable query."""

    def _route(self):
        from pinot_tpu.broker.routing import (RoutingTable, SegmentInfo,
                                              TableRoute)
        tr = TableRoute("t_OFFLINE")
        tr.segments["s0"] = SegmentInfo("s0", ["srv0"], version=1)
        return RoutingTable(offline=tr), tr, SegmentInfo

    def test_no_mutation_hashes_once(self):
        rt, _, _ = self._route()
        e1 = rt.epoch()
        e2 = rt.epoch()
        assert e1 == e2
        assert rt.epoch_computes == 1

    def test_every_mutation_kind_invalidates(self):
        rt, tr, SegmentInfo = self._route()
        seen = {rt.epoch()}
        tr.segments["s1"] = SegmentInfo("s1", ["srv0"], version=2)   # set
        seen.add(rt.epoch())
        del tr.segments["s1"]                                        # del
        seen.add(rt.epoch())
        tr.segments.update(s2=SegmentInfo("s2", ["srv0"], version=3))
        seen.add(rt.epoch())
        tr.segments.pop("s2")
        seen.add(rt.epoch())
        tr.segments.clear()
        seen.add(rt.epoch())
        assert rt.epoch_computes == 6          # one hash per mutation
        assert len(seen) == 4  # {s0}, {s0,s1}, {s0,s2}, {} (adds repeat)

    def test_time_boundary_invalidates(self):
        rt, _, _ = self._route()
        e1 = rt.epoch()
        rt.time_boundary = 42
        assert rt.epoch() != e1
        assert rt.epoch_computes == 2

    def test_suffix_addressed_route_keeps_memo(self):
        """get_route('t_OFFLINE') must return a cached single-side view —
        a fresh wrapper per call would carry an empty memo and re-hash
        every query."""
        from pinot_tpu.broker.routing import (BrokerRoutingManager,
                                              RoutingTable, SegmentInfo,
                                              TableRoute)
        mgr = BrokerRoutingManager()
        tr = TableRoute("t_OFFLINE")
        tr.segments["s0"] = SegmentInfo("s0", ["srv"], version=1)
        mgr.set_route("t", RoutingTable(offline=tr))
        view = mgr.get_route("t_OFFLINE")
        assert mgr.get_route("t_OFFLINE") is view
        e = view.epoch()
        assert mgr.get_route("t_OFFLINE").epoch() == e
        assert view.epoch_computes == 1
        # mutations flow through the SHARED TableRoute into the view
        tr.segments["s1"] = SegmentInfo("s1", ["srv"], version=2)
        assert mgr.get_route("t_OFFLINE").epoch() != e
        # set_route drops the stale view
        mgr.set_route("t", RoutingTable(offline=TableRoute("t_OFFLINE")))
        assert mgr.get_route("t_OFFLINE") is not view

    def test_concurrent_mutations_never_lose_an_invalidation(self):
        """mutation_version bumps must be atomic: a lost increment would
        leave the memo valid for a segment set it no longer matches."""
        from pinot_tpu.broker.routing import (RoutingTable, SegmentInfo,
                                              TableRoute)
        tr = TableRoute("t_OFFLINE")
        rt = RoutingTable(offline=tr)
        n_threads, per_thread = 4, 200
        barrier = threading.Barrier(n_threads)

        def mutate(tid):
            barrier.wait()
            for i in range(per_thread):
                tr.segments[f"s{tid}_{i}"] = SegmentInfo(
                    f"s{tid}_{i}", ["srv"], version=i)
        threads = [threading.Thread(target=mutate, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        e_settled = rt.epoch()
        # every one of the 800 bumps was observed: the memoized epoch
        # reflects the full final segment set
        tr2 = TableRoute("t_OFFLINE")
        for k, v in tr.segments.items():
            tr2.segments[k] = v
        assert RoutingTable(offline=tr2).epoch() == e_settled

    def test_offline_epoch_survives_realtime_mutation(self):
        from pinot_tpu.broker.routing import (RoutingTable, SegmentInfo,
                                              TableRoute)
        off, rt_side = TableRoute("t_OFFLINE"), TableRoute("t_REALTIME")
        off.segments["o0"] = SegmentInfo("o0", ["srv0"], version=1)
        rt = RoutingTable(offline=off, realtime=rt_side)
        eo = rt.offline_epoch()
        n = rt.epoch_computes
        rt_side.segments["r0"] = SegmentInfo("r0", ["srv1"], version=9)
        assert rt.offline_epoch() == eo        # key stays addressable
        assert rt.epoch_computes == n          # and was not re-hashed
        assert rt.epoch() != eo                # but the FULL epoch moved


# ---------------------------------------------------------------------------
class TestFingerprintLog:
    def test_bounded_with_recency_refresh(self):
        fl = FingerprintLog(max_plans_per_table=3)
        for i in range(3):
            fl.record("t", f"fp{i}", f"sql{i}")
        fl.record("t", "fp0", "sql0")          # refresh oldest
        fl.record("t", "fp3", "sql3")          # evicts fp1, NOT fp0
        fps = [fp for fp, _, _ in fl.plans("t")]
        assert fps == ["fp2", "fp0", "fp3"]
        assert len(fl) == 3

    def test_extra_filter_travels(self):
        fl = FingerprintLog()
        fl.record("t", "fp", "SELECT 1", extra_filter="ts <= 99")
        assert fl.plans("t") == [("fp", "SELECT 1", "ts <= 99")]


# ---------------------------------------------------------------------------
@pytest.fixture()
def fabric_cluster(tmp_path):
    """Two brokers + two servers sharing one in-process cache server,
    with fast breaker knobs for the fault-injection tests."""
    cfg = PinotConfiguration(overrides={
        "pinot.cache.remote.timeout.seconds": 1.0,
        "pinot.cache.remote.breaker.reset.seconds": 0.3,
    })
    c = MiniCluster(num_servers=2, result_cache=True, num_brokers=2,
                    cache_server=True, config=cfg)
    c.start()
    c.add_table("t")
    for i in range(4):
        c.add_segment("t", _build(tmp_path, f"f{i}", range(100), [i] * 100),
                      server_idx=i % 2)
    yield c, tmp_path
    c.stop()


class TestFabricSharing:
    def test_broker_b_hits_what_only_broker_a_executed(self, fabric_cluster):
        c, _ = fabric_cluster
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE d < 50"
        cold = c.brokers[0].handle(sql)
        assert not cold.exceptions and not cold.cache_hit
        warm = c.brokers[1].handle(sql)        # this broker never executed
        assert warm.cache_hit
        assert warm.result_table.rows == cold.result_table.rows
        # the hit came over the wire: broker B's L2 client saw it
        assert c.brokers[1].result_cache._cache.l2.hits >= 1

    def test_server_replica_serves_partials_it_never_scanned(
            self, fabric_cluster):
        c, tmp_path = fabric_cluster
        sql = "SELECT SUM(m) FROM t"
        c.brokers[0].handle(sql)               # all segments cached, L2 too
        seg = _build(tmp_path, "f0", range(100), [0] * 100)  # f0's content
        sc1 = c.servers[1].executor.segment_cache
        fp = QueryContext.from_sql(sql).fingerprint()
        # server 1 never scanned f0 (it lives on server 0), yet its
        # tiered cache serves the partial from the shared tier
        l2_hits = sc1._cache.l2.hits
        assert sc1.get(seg, fp) is not None
        assert sc1._cache.l2.hits == l2_hits + 1

    def test_warmup_on_replica_load(self, fabric_cluster):
        c, tmp_path = fabric_cluster
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE d >= 10"
        c.brokers[0].handle(sql)               # logs the plan on both servers
        # replicate f0 (server 0's segment) onto server 1: the load-time
        # warmup replays the log and finds the partial already shared
        seg = _build(tmp_path, "f0", range(100), [0] * 100)
        warm = c.servers[1].executor.warmup
        before = warm.entries_warmed
        c.servers[1].data_manager.table("t_OFFLINE").add_segment(seg)
        assert warm.entries_warmed > before
        assert warm.segments_warmed >= 1


class TestWarmupAcceptance:
    def test_fresh_segment_first_query_hits_tier2(self, tmp_path):
        """Loading an immutable segment replays the fingerprint log, so
        its FIRST routed query is a tier-2 hit, not a scan."""
        c = MiniCluster(num_servers=1)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", _build(tmp_path, "w0", range(100), [1] * 100),
                          server_idx=0)
            sql = "SELECT d, SUM(m) FROM t GROUP BY d ORDER BY d LIMIT 5"
            c.query(sql)                       # caches w0 + logs the plan
            warm = c.servers[0].executor.warmup
            assert warm.entries_warmed == 0    # nothing replayed yet
            c.add_segment("t", _build(tmp_path, "w1", range(50), [2] * 50),
                          server_idx=0)
            assert warm.entries_warmed >= 1    # replayed on load
            sc = c.servers[0].executor.segment_cache
            hits0, misses0 = sc.stats.hits, sc.stats.misses
            r = c.query(sql)                   # first query routed at w1
            assert not r.exceptions
            assert sc.stats.hits == hits0 + 2  # BOTH segments hit
            assert sc.stats.misses == misses0  # w1 never missed
        finally:
            c.stop()

    def test_replace_keeps_warmed_new_version(self, tmp_path):
        """A refresh-push replaces the segment right after warmup ran on
        the new version; the replace purge must spare those entries or
        the rollout starts cold anyway."""
        c = MiniCluster(num_servers=1)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", _build(tmp_path, "rw0", range(100), [1] * 100),
                          server_idx=0)
            sql = "SELECT SUM(m) FROM t"
            c.query(sql)                       # cache + log the plan
            out = str(tmp_path / "rw0v2")      # same name, new content
            SegmentCreator(_table_config(), _schema()).build(
                {"d": np.arange(100, dtype=np.int64),
                 "m": np.full(100, 5, np.int64)}, out, "rw0")
            seg2 = load_segment(out)
            c.add_segment("t", seg2, server_idx=0)  # warm, then replace
            sc = c.servers[0].executor.segment_cache
            fp = QueryContext.from_sql(sql).fingerprint()
            assert sc.get(seg2, fp) is not None  # warmup survived the purge
            r = c.query(sql)
            assert not r.exceptions
            assert r.rows[0][0] == 500           # and it is the NEW data
        finally:
            c.stop()

    def test_zero_knobs_disable_warmup(self, tmp_path):
        cfg = PinotConfiguration(overrides={
            "pinot.server.segment.warmup.max.plans": 0})
        c = MiniCluster(num_servers=1, config=cfg)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", _build(tmp_path, "z0", range(10), [1] * 10),
                          server_idx=0)
            c.query("SELECT SUM(m) FROM t")
            assert len(c.servers[0].executor.fingerprint_log) == 0
            c.add_segment("t", _build(tmp_path, "z1", range(10), [2] * 10),
                          server_idx=0)
            assert c.servers[0].executor.warmup.entries_warmed == 0
        finally:
            c.stop()

    def test_warmup_disabled_by_config(self, tmp_path):
        cfg = PinotConfiguration(overrides={
            "pinot.server.segment.warmup.enabled": False})
        c = MiniCluster(num_servers=1, config=cfg)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", _build(tmp_path, "wd0", range(10), [1] * 10),
                          server_idx=0)
            c.query("SELECT SUM(m) FROM t")
            c.add_segment("t", _build(tmp_path, "wd1", range(10), [2] * 10),
                          server_idx=0)
            assert c.servers[0].executor.warmup.entries_warmed == 0
        finally:
            c.stop()


class TestFaultInjection:
    def test_outage_degrades_to_local_only_with_zero_failures(
            self, fabric_cluster):
        """Satellite + acceptance: kill the cache server mid-query-loop —
        zero failed queries, breaker opens (visible in metrics), L1 keeps
        serving repeats; a restarted server closes the breaker again."""
        c, _ = fabric_cluster
        broker = c.brokers[0]
        l2 = broker.result_cache._cache.l2

        queries = [f"SELECT COUNT(*), SUM(m) FROM t WHERE d < {n}"
                   for n in range(2, 12)]
        for sql in queries[:4]:                # healthy fabric
            assert not broker.handle(sql).exceptions
        assert l2.breaker.state == CIRCUIT_CLOSED

        port = c.cache_server.port
        c.cache_server.stop()                  # ---- outage ----
        for sql in queries[4:]:                # fresh plans force L2 traffic
            r = broker.handle(sql)
            assert not r.exceptions, r.exceptions
        assert l2.breaker.state == CIRCUIT_OPEN
        # L1-only operation: repeats still hit locally
        assert broker.handle(queries[5]).cache_hit
        from pinot_tpu.utils.metrics import get_registry
        assert "remote_cache_breaker_state" in \
            get_registry("broker").prometheus_text()

        # ---- recovery: same port, breaker probes half-open -> closed ----
        restarted = CacheServer(port=port, max_bytes=8 << 20)
        restarted.start()
        c.cache_server = restarted             # fixture stop() reaps it
        time.sleep(0.35)                       # past the reset window
        assert l2.breaker.state == CIRCUIT_HALF_OPEN
        r = broker.handle("SELECT SUM(m) FROM t WHERE d > 90")  # probe rides
        assert not r.exceptions
        assert l2.breaker.state == CIRCUIT_CLOSED
        # the fabric is shared again: broker B hits broker A's fresh entry
        assert c.brokers[1].handle(
            "SELECT SUM(m) FROM t WHERE d > 90").cache_hit

    def test_server_side_tier_degrades_too(self, fabric_cluster):
        c, tmp_path = fabric_cluster
        c.cache_server.stop()
        # segment loads (warmup replay) and queries keep working L1-only
        c.add_segment("t", _build(tmp_path, "deg0", range(30), [5] * 30),
                      server_idx=0)
        r = c.brokers[0].handle("SELECT COUNT(*) FROM t")
        assert not r.exceptions
        assert r.rows[0][0] == 430


# ---------------------------------------------------------------------------
class TestHybridOfflinePartial:
    """Satellite: the offline side of a hybrid table is cached against the
    OFFLINE epoch; only the realtime side re-scatters."""

    def _hybrid(self, tmp_path_factory, result_cache=True):
        from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                      TableConfig, TableType)
        schema = Schema("hy", [
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
            FieldSpec("val", DataType.INT, FieldType.METRIC)])
        tc = TableConfig("hy", TableType.OFFLINE)
        tc.retention.time_column = "ts"

        def build(tmp, arrs, name):
            out = str(tmp / name)
            SegmentCreator(tc, schema).build(arrs, out, name)
            return load_segment(out)

        off = build(tmp_path_factory.mktemp("hy_off"), {
            "ts": np.arange(0, 100, dtype=np.int64),
            "val": np.ones(100, dtype=np.int32)}, "hy_off")
        rt = build(tmp_path_factory.mktemp("hy_rt"), {
            "ts": np.arange(80, 200, dtype=np.int64),
            "val": np.full(120, 2, dtype=np.int32)}, "hy_rt")
        c = MiniCluster(num_servers=2, result_cache=result_cache)
        c.start()
        c.add_table("hy", "OFFLINE", time_column="ts")
        c.add_table("hy", "REALTIME", time_column="ts", time_boundary=99)
        c.add_segment("hy", off, 0, "OFFLINE")    # offline ONLY on server 0
        c.add_segment("hy", rt, 1, "REALTIME")    # realtime ONLY on server 1
        return c

    def test_offline_side_served_from_cache(self, tmp_path_factory):
        c = self._hybrid(tmp_path_factory)
        try:
            sql = "SELECT COUNT(*), SUM(val) FROM hy"
            first = c.query(sql)
            assert not first.exceptions
            assert first.rows[0] == (200, pytest.approx(300))
            assert not first.cache_hit         # whole-result uncacheable
            # sever the OFFLINE server: if the cached offline partial is
            # real, the next hybrid query still answers completely
            c.servers[0].transport.stop()
            c._connections["server_0"].close()
            again = c.query(sql)
            assert not again.exceptions, again.exceptions
            assert again.rows == first.rows
            # bypass must re-scatter to the dead offline server and fail
            r = c.query(sql + " OPTION(skipCache=true)")
            assert r.exceptions
        finally:
            c.stop()

    def test_realtime_side_stays_fresh(self, tmp_path_factory):
        from pinot_tpu.ingest.mutable_segment import MutableSegment
        from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                      TableConfig, TableType)
        c = self._hybrid(tmp_path_factory)
        try:
            schema = Schema("hy", [
                FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
                FieldSpec("val", DataType.INT, FieldType.METRIC)])
            mut = MutableSegment("hy__0__0__1",
                                 TableConfig("hy", TableType.REALTIME),
                                 schema)
            mut.index({"ts": 300, "val": 7})
            c.servers[1].data_manager.table("hy_REALTIME").add_segment(mut)
            rt = c.routing.get_route("hy").realtime
            from pinot_tpu.broker.routing import SegmentInfo
            rt.segments[mut.name] = SegmentInfo(
                mut.name, ["server_1"], version=0)
            sql = "SELECT COUNT(*), SUM(val) FROM hy"
            n1 = c.query(sql).rows[0][0]
            mut.index({"ts": 301, "val": 7})   # append: no epoch move
            n2 = c.query(sql).rows[0][0]       # offline from cache, RT fresh
            assert n2 == n1 + 1
        finally:
            c.stop()

    def test_incomplete_offline_plan_not_cached(self, tmp_path_factory):
        """A segment with no placeable replica is silently dropped from
        the plan, and placement is outside the epoch — a partial missing
        its rows must NOT be cached as complete."""
        from pinot_tpu.broker.routing import SegmentInfo
        c = self._hybrid(tmp_path_factory)
        try:
            rt = c.routing.get_route("hy")
            rt.offline.segments["ghost"] = SegmentInfo("ghost", [], version=7)
            sql = "SELECT COUNT(*) FROM hy"
            r = c.query(sql)
            assert not r.exceptions        # routing tolerates the drop
            fp = QueryContext.from_sql(sql).fingerprint()
            assert c.broker.result_cache.get_offline_partial(
                fp, "hy", rt.offline_epoch()) is None
        finally:
            c.stop()

    def test_disabled_by_knob(self, tmp_path_factory):
        c = self._hybrid(tmp_path_factory)
        try:
            c.broker.config = PinotConfiguration(overrides={
                "pinot.broker.result.cache.hybrid.offline": False})
            sql = "SELECT COUNT(*) FROM hy"
            c.query(sql)
            c.servers[0].transport.stop()
            c._connections["server_0"].close()
            assert c.query(sql).exceptions     # nothing was cached
        finally:
            c.stop()


# ---------------------------------------------------------------------------
class TestRemoteCompression:
    """Remote-tier payload compression (ISSUE 4 satellite): payloads at/
    above the threshold ride the wire codec-wrapped, decode transparently
    on GET, and corrupt entries degrade to miss — never an exception."""

    def test_transparent_roundtrip_and_smaller_wire_bytes(self, cache_server):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("compress_test")
        be = RemoteCacheBackend(cache_server.address, metrics=m,
                                compress_threshold=1024)
        try:
            payload = b"PDT1" + b"abcdefgh" * 4096  # compressible, 32KB+
            assert be.put("big", payload)
            assert be.get("big") == payload
            stored = cache_server.cache.size_bytes
            assert 0 < stored < len(payload) // 2
            meter = m.meter("remote_cache_compressed_bytes")
            assert 0 < meter < len(payload)
            # below-threshold payloads ship raw
            small = b"PDT1" + b"x" * 100
            assert be.put("small", small)
            assert be.get("small") == small
            assert cache_server.cache.size_bytes == stored + len(small)
        finally:
            be.close()

    def test_incompressible_payload_ships_raw(self, cache_server):
        import os as _os
        be = RemoteCacheBackend(cache_server.address, compress_threshold=64)
        try:
            noise = _os.urandom(4096)  # wrapper would only grow it
            assert be.put("noise", noise)
            assert be.get("noise") == noise
            assert cache_server.cache.size_bytes == len(noise)
        finally:
            be.close()

    def test_torn_compressed_entry_degrades_to_miss(self, cache_server):
        be = RemoteCacheBackend(cache_server.address, compress_threshold=64)
        raw = RemoteCacheBackend(cache_server.address)  # no compression
        try:
            assert be.put("k", b"PDT1" + b"data" * 1024)
            # corrupt the stored entry in place: keep the wrapper magic,
            # truncate the codec body
            stored = cache_server.cache.get("k")
            assert stored[:4] == b"PZC1"
            cache_server.cache.put("k", stored[: len(stored) // 2])
            assert be.get("k") is None          # miss, not an exception
            # uncompressed entries are untouched by the unwrap path
            assert raw.put("plain", b"PDT1plain")
            assert be.get("plain") == b"PDT1plain"
        finally:
            be.close()
            raw.close()

    def test_config_wires_threshold_into_tiered_backend(self):
        from pinot_tpu.cache.tiered import tiered_backend_from_config
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = PinotConfiguration(overrides={
            "pinot.cache.server.compress.threshold.bytes": 2048})
        t = tiered_backend_from_config(
            cfg, "pinot.server.segment.cache", "seg", lambda k: None)
        try:
            assert t.l2.compress_threshold == 2048
        finally:
            t.close()
