"""Consistent-hash cache ring (cache/ring.py): key stability under node
add/remove, per-node breaker isolation, and one-node-death degrading only
its key range to L1-only with zero failed queries (ISSUE 8)."""
import os

import numpy as np
import pytest

from pinot_tpu.cache.remote import CIRCUIT_CLOSED, CIRCUIT_OPEN, CacheServer
from pinot_tpu.cache.ring import ConsistentHashRing, RingRemoteCacheBackend
from pinot_tpu.utils.failpoints import failpoints


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


KEYS = [f"tbl:{i}:fp{i * 7}" for i in range(400)]


class TestRing:
    def test_deterministic_and_total(self):
        ring = ConsistentHashRing(["a:1", "b:1", "c:1"])
        m1 = {k: ring.node_for(k) for k in KEYS}
        m2 = {k: ring.node_for(k) for k in KEYS}
        assert m1 == m2
        assert set(m1.values()) == {"a:1", "b:1", "c:1"}

    def test_spread_is_roughly_even(self):
        ring = ConsistentHashRing(["a:1", "b:1", "c:1"], vnodes=64)
        counts = {}
        for k in KEYS:
            counts[ring.node_for(k)] = counts.get(ring.node_for(k), 0) + 1
        # virtual nodes keep every node within a loose band of fair share
        for node, n in counts.items():
            assert 40 <= n <= 260, counts

    def test_remove_node_moves_only_its_range(self):
        """The no-rehash-storm property: removing one node re-maps ONLY
        the keys it owned; every other key keeps its node (its warm
        remote entries stay addressable)."""
        ring = ConsistentHashRing(["a:1", "b:1", "c:1"])
        before = {k: ring.node_for(k) for k in KEYS}
        ring.remove_node("b:1")
        after = {k: ring.node_for(k) for k in KEYS}
        for k in KEYS:
            if before[k] != "b:1":
                assert after[k] == before[k], k
            else:
                assert after[k] in ("a:1", "c:1")

    def test_add_node_steals_bounded_share(self):
        ring = ConsistentHashRing(["a:1", "b:1"])
        before = {k: ring.node_for(k) for k in KEYS}
        ring.add_node("c:1")
        moved = sum(1 for k in KEYS if ring.node_for(k) != before[k])
        stolen = sum(1 for k in KEYS if ring.node_for(k) == "c:1")
        assert moved == stolen  # only moves TO the new node
        assert 0 < moved < len(KEYS) * 0.6, moved

    def test_empty_ring(self):
        assert ConsistentHashRing([]).node_for("x") is None


@pytest.fixture()
def two_servers():
    servers = [CacheServer(ttl_seconds=60.0) for _ in range(2)]
    for s in servers:
        s.start()
    yield servers
    for s in servers:
        s.stop()


def _ring_client(servers, **kwargs):
    return RingRemoteCacheBackend([s.address for s in servers],
                                  timeout_seconds=0.5,
                                  failure_threshold=1,
                                  reset_seconds=60.0, **kwargs)


class TestRingBackend:
    def test_round_trip_spreads_over_nodes(self, two_servers):
        client = _ring_client(two_servers)
        try:
            for i in range(60):
                assert client.put(f"k{i}", f"v{i}".encode())
            for i in range(60):
                assert client.get(f"k{i}") == f"v{i}".encode()
            sizes = [len(s.cache) for s in two_servers]
            assert all(n > 0 for n in sizes), sizes
            assert sum(sizes) == 60
        finally:
            client.close()

    def test_dead_node_degrades_only_its_range(self, two_servers):
        """Kill one cache server: its key range misses (L1-only
        semantics for the mount) while the other node's range keeps
        serving — and nothing raises into the caller."""
        client = _ring_client(two_servers)
        try:
            for i in range(60):
                assert client.put(f"k{i}", f"v{i}".encode())
            dead = two_servers[0]
            dead_addr = dead.address
            dead.stop()
            served = missed = 0
            for i in range(60):
                key = f"k{i}"
                got = client.get(key)  # must never raise
                if client.ring.node_for(key) == dead_addr:
                    assert got is None
                    missed += 1
                else:
                    assert got == f"v{i}".encode()
                    served += 1
            assert served > 0 and missed > 0
            # per-node breakers: the dead node's circuit opened, the
            # survivor's stayed closed
            assert client.breaker_of(dead_addr).state == CIRCUIT_OPEN
            live_addr = two_servers[1].address
            assert client.breaker_of(live_addr).state == CIRCUIT_CLOSED
        finally:
            client.close()

    def test_ring_failpoint_fails_one_node_only(self, two_servers):
        client = _ring_client(two_servers)
        target = two_servers[0].address
        try:
            for i in range(40):
                client.put(f"k{i}", b"x")
            with failpoints.armed("cache.ring.node", drop=True,
                                  where={"node": target}):
                for i in range(40):
                    got = client.get(f"k{i}")
                    if client.ring.node_for(f"k{i}") == target:
                        assert got is None
                    else:
                        assert got == b"x"
        finally:
            client.close()

    def test_membership_resize(self, two_servers):
        extra = CacheServer(ttl_seconds=60.0)
        extra.start()
        client = _ring_client(two_servers)
        try:
            for i in range(40):
                client.put(f"k{i}", b"y")
            before = {f"k{i}": client.ring.node_for(f"k{i}")
                      for i in range(40)}
            client.add_node(extra.address)
            surviving = [k for k, n in before.items()
                         if client.ring.node_for(k) == n]
            # unmoved ranges still hit their warm node
            for k in surviving:
                assert client.get(k) == b"y"
            # the new node actually serves its stolen range
            moved = [k for k in before if k not in surviving]
            for k in moved:
                client.put(k, b"z")
                assert client.get(k) == b"z"
            client.remove_node(extra.address)
            assert extra.address not in client.ring.nodes
        finally:
            client.close()
            extra.stop()


class TestClusterRingFabric:
    def test_cluster_ring_node_kill_zero_failed_queries(self, tmp_path):
        """MiniCluster with a 2-node cache ring: queries warm BOTH
        nodes' ranges; killing one node leaves every query answering
        (the dead range recomputes / serves L1) with zero exceptions."""
        from pinot_tpu.cache.ring import RingRemoteCacheBackend as Ring
        from pinot_tpu.cluster.mini import MiniCluster
        from pinot_tpu.models.schema import Schema
        from pinot_tpu.models.table_config import TableConfig
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment

        schema = Schema.from_dict({
            "schemaName": "cr",
            "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
            "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
        creator = SegmentCreator(TableConfig.from_dict(
            {"tableName": "cr", "tableType": "OFFLINE"}), schema)
        cluster = MiniCluster(num_servers=2, result_cache=True,
                              cache_servers=2)
        cluster.start()
        try:
            assert len(cluster.cache_servers) == 2
            # the broker's L2 mount is a ring over both nodes
            l2 = cluster.broker.result_cache._cache.l2
            assert isinstance(l2, Ring)
            cluster.add_table("cr")
            for i in range(3):
                rng = np.random.default_rng(i)
                d = os.path.join(str(tmp_path), f"cr_{i}")
                creator.build(
                    {"k": rng.integers(0, 9, 200).astype(np.int64),
                     "v": rng.integers(0, 50, 200).astype(np.int64)},
                    d, f"cr_{i}")
                cluster.add_segment("cr", load_segment(d),
                                    server_idx=i % 2)
            queries = [f"SELECT COUNT(*), SUM(v) FROM cr WHERE k < {i}"
                       for i in range(2, 9)]
            truth = {}
            for q in queries:
                resp = cluster.query(q)
                assert not resp.exceptions
                truth[q] = resp.rows
            # entries landed on both ring nodes
            sizes = [len(cs.cache) for cs in cluster.cache_servers]
            assert all(n > 0 for n in sizes), sizes
            cluster.cache_servers[0].stop()
            for q in queries:
                resp = cluster.query(q)  # zero failed queries
                assert not resp.exceptions, resp.exceptions
                assert resp.rows == truth[q]
        finally:
            cluster.stop()
