"""CLP log-compression: codec round-trip, forward index, query integration
(the y-scope extension; ref CLPForwardIndexReaderV2 + ClpRewriterTest)."""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment import clp
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment

MESSAGES = [
    "INFO  Task task_1234 assigned to container: [container_e3243], operation took 0.335 seconds",
    "ERROR Connection to 10.0.23.1:8080 refused after 3 retries",
    "WARN  GC pause of 1.21 seconds detected at offset 987654321",
    "INFO  Task task_1234 assigned to container: [container_e3243], operation took 0.335 seconds",
    "DEBUG user=alice id=42 logged in from 192.168.0.7",
    "plain message with no variables at all",
    "edge cases: 007 0x1F 1.2.3 -17 +5 3.14000",
]


class TestCodec:
    @pytest.mark.parametrize("msg", MESSAGES)
    def test_roundtrip(self, msg):
        lt, dv, ev = clp.encode_message(msg)
        assert clp.decode_message(lt, dv, ev) == msg

    def test_template_extraction(self):
        lt1, _, ev1 = clp.encode_message("took 5 seconds")
        lt2, _, ev2 = clp.encode_message("took 93 seconds")
        assert lt1 == lt2  # same template
        assert ev1 == [5] and ev2 == [93]

    def test_float_encoding(self):
        lt, dv, ev = clp.encode_message("pause of 1.21 seconds")
        assert clp.FLOAT_PH in lt
        assert dv == []
        assert len(ev) == 1

    def test_nonroundtrip_stays_dict_var(self):
        # leading zeros would not survive int round-trip
        lt, dv, ev = clp.encode_message("code 007")
        assert dv == ["007"] and ev == []

    def test_forward_index_roundtrip(self):
        buf = clp.write_clp_column(MESSAGES * 10)
        r = clp.CLPForwardIndexReader(buf)
        assert r.num_docs == len(MESSAGES) * 10
        out = r.decode_all()
        assert out.tolist() == MESSAGES * 10
        # logtype dictionary is shared: duplicates collapse
        assert len(r.logtypes) < len(MESSAGES) * 10

    def test_compression_wins_on_repetitive_logs(self):
        msgs = [f"INFO request {i} served in {i % 100} ms from host h{i % 4}"
                for i in range(10_000)]
        raw = sum(len(m) for m in msgs)
        buf = clp.pack_compressed(clp.write_clp_column(msgs))
        assert len(buf) < raw * 0.4  # templates + chunk codec beat raw text


class TestClpColumn:
    @pytest.fixture(scope="class")
    def seg(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("clp")
        schema = Schema("logs", [
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
            FieldSpec("message", DataType.STRING),
        ])
        tc = TableConfig("logs", TableType.OFFLINE)
        tc.indexing.clp_columns = ["message"]
        msgs = [MESSAGES[i % len(MESSAGES)] for i in range(500)]
        SegmentCreator(tc, schema).build(
            {"ts": np.arange(500, dtype=np.int64), "message": msgs},
            str(tmp / "seg"), "logs_0")
        return load_segment(str(tmp / "seg")), msgs

    def test_values_decode(self, seg):
        s, msgs = seg
        vals = s.data_source("message").values()
        assert vals.tolist() == msgs

    def test_like_query_on_clp_column(self, seg):
        s, msgs = seg
        ex = QueryExecutor([s], use_tpu=False)
        r = ex.execute("SELECT COUNT(*) FROM logs WHERE message LIKE '%refused%'")
        want = sum(1 for m in msgs if "refused" in m)
        assert r.rows[0][0] == want

    def test_select_clp_column(self, seg):
        s, msgs = seg
        ex = QueryExecutor([s], use_tpu=False)
        r = ex.execute("SELECT message FROM logs WHERE ts = 1 LIMIT 1")
        assert r.rows[0][0] == msgs[1]

    def test_storage_smaller_than_plain(self, seg, tmp_path):
        s, msgs = seg
        schema = Schema("logs", [
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
            FieldSpec("message", DataType.STRING),
        ])
        tc = TableConfig("logs", TableType.OFFLINE)
        tc.indexing.no_dictionary_columns = ["message"]
        tc.indexing.compression = "PASS_THROUGH"
        SegmentCreator(tc, schema).build(
            {"ts": np.arange(500, dtype=np.int64), "message": msgs},
            str(tmp_path / "plain"), "logs_plain")
        import os
        clp_size = sum(os.path.getsize(os.path.join(r, f))
                       for r, _, fs in os.walk(str(s.dir.path)) for f in fs) \
            if hasattr(s.dir, "path") else None
        # direct buffer comparison instead: clp buffer vs raw var buffer
        plain = load_segment(str(tmp_path / "plain"))
        from pinot_tpu.segment import index_types as it
        clp_buf = s.dir.get_buffer("message", it.CLP)
        raw_buf = plain.dir.get_buffer("message", it.FORWARD)
        assert len(bytes(clp_buf)) < len(bytes(raw_buf))


class TestClpIngestion:
    def test_enricher_and_clpdecode_transform(self, tmp_path):
        schema = Schema("logs", [
            FieldSpec("message_logtype", DataType.STRING),
            FieldSpec("message_dictionaryVars", DataType.STRING,
                      single_value=False),
            FieldSpec("message_encodedVars", DataType.LONG,
                      single_value=False),
        ])
        tc = TableConfig("logs", TableType.OFFLINE)
        enrich = clp.clp_enricher(["message"])
        rows = {"message_logtype": [], "message_dictionaryVars": [],
                "message_encodedVars": []}
        for m in MESSAGES:
            rec = {"message": m}
            enrich(rec)
            rows["message_logtype"].append(rec["message_logtype"])
            rows["message_dictionaryVars"].append(
                rec["message_dictionaryVars"] or ["\x00"])
            rows["message_encodedVars"].append(
                rec["message_encodedVars"] or [0])
        SegmentCreator(tc, schema).build(rows, str(tmp_path / "seg"), "l0")
        seg = load_segment(str(tmp_path / "seg"))
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute(
            "SELECT clpDecode(message_logtype, message_dictionaryVars, "
            "message_encodedVars) FROM logs LIMIT 10")
        decoded = [row[0] for row in r.rows]
        # messages whose var lists were non-empty round-trip exactly
        for got, want in zip(decoded, MESSAGES):
            lt, dv, ev = clp.encode_message(want)
            if dv and ev:
                assert got == want
