"""CLP log-analytics subsystem (ISSUE 17): device-side LIKE/regex
pushdown over CLP columns, realtime log ingestion, minion compaction.

  * codec properties — seeded random messages (unicode, floats,
    non-roundtrip digit tokens, empty/whitespace edges) round-trip
    through encode/decode AND write_clp_column/CLPForwardIndexReader;
    `get(doc_id)` random access matches `decode_all`
  * device parity — LIKE/regex filters over CLP columns answer
    BIT-IDENTICALLY to the host decode path through the real engine,
    across a pushdown matrix (substring, multi-piece, anchors, floats,
    ints, IPs, unicode); served queries meter `clp_served`, fallbacks
    meter `clp_fallback{reason=}` with EXACT structured reasons
  * retraces — fingerprint-equal queries with different pattern
    constants share one kernel (constants resolve at staging, the
    pattern never enters the plan): ZERO steady-state retraces
  * realtime — a MutableSegment with `indexing.clp_columns` encodes at
    ingest (template store, not raw strings), answers host queries,
    seals into a CLP segment the device leg serves
  * compaction — `ClpCompactionTask` generator/executor converge plain
    log segments onto CLP form; a SimulatedCrash at `minion.clp.compact`
    leaves sources serving and the re-leased task re-encodes
    BYTE-IDENTICAL output
  * minion fairness — tenant-weighted lease clocks (weight 3 leases 3x
    weight 1 under contention; weight 1.0 degenerates to round-robin)
  * auto star-tree — the workload-driven generator schedules builds
    only for tables the /debug/workload rollup shows as hot
"""
import time

import numpy as np
import pytest

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.controller.task_manager import PENDING, TaskManager, TaskQueue
from pinot_tpu.controller.tasks import TaskConfig, TaskContext, run_task
from pinot_tpu.health.workload import WorkloadRegistry
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import clp_device, kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment import clp
from pinot_tpu.segment import index_types as it
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import SimulatedCrash, failpoints

MESSAGES = [
    "INFO task 1234 started on host web-01 in 0.5s",
    "WARN task 9999 slow on host web-02 in 12.75s",
    "ERROR task 1234 failed on host web-01: code=500",
    "INFO user alice logged in from 10.0.0.1",
    "INFO user bob42 logged in from 10.0.0.2",
    "disk /dev/sda1 at 93% capacity",
    "disk /dev/sdb2 at 17% capacity",
    "GC pause 45 ms in region r7",
    "GC pause 450 ms in region r12",
    "",
    "ERROR task 777 failed on host db-01: code=503",
    "checkpoint written to /data/ckpt/000123 bytes=4096",
    "retrying connection to 10.0.0.1 attempt 3",
    "negative value -17 seen at offset -3.5",
    "unicode héllo wörld 42 done",
]


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def log_schema(name="logs"):
    return Schema(name, [
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("message", DataType.STRING),
    ])


def build_log_seg(tmp, name, msgs, clp_col=True, table="logs"):
    tc = TableConfig(table, TableType.OFFLINE)
    if clp_col:
        tc.indexing.clp_columns = ["message"]
    out = str(tmp / name)
    SegmentCreator(tc, log_schema(table)).build(
        {"ts": np.arange(len(msgs), dtype=np.int64), "message": list(msgs)},
        out, name)
    return out


def _engine(name, **overrides):
    return TpuOperatorExecutor(
        config=PinotConfiguration(overrides=overrides),
        metrics_labels={"clp_test": name})


def _meter(eng, name, reason=None):
    labels = {"clp_test": eng._labels["clp_test"]}
    if reason is not None:
        labels["reason"] = reason
    return eng._metrics.meter(name, labels=labels)


# ---------------------------------------------------------------------------
# codec properties
# ---------------------------------------------------------------------------
class TestCodecProperties:
    _WORDS = ["alpha", "beta", "état", "GET", "host", "wörld", "retry",
              "x", "[queue]", "a=b"]

    @classmethod
    def _rand_msg(cls, rng):
        parts = []
        for _ in range(int(rng.integers(0, 9))):
            kind = int(rng.integers(0, 7))
            if kind == 0:
                parts.append(str(cls._WORDS[int(
                    rng.integers(0, len(cls._WORDS)))]))
            elif kind == 1:   # int64-range -> encoded var
                parts.append(str(int(rng.integers(-10**12, 10**12))))
            elif kind == 2:   # repr-roundtrip float -> encoded var
                parts.append(repr(round(float(rng.random()) * 100, 3)))
            elif kind == 3:   # leading zero: no int round-trip -> dict var
                parts.append("0" + str(int(rng.integers(0, 999))))
            elif kind == 4:   # ip-ish multi-dot token -> dict var
                parts.append(".".join(str(int(v))
                                      for v in rng.integers(0, 256, 4)))
            elif kind == 5:   # beyond int64 -> dict var
                parts.append(str(int(rng.integers(1, 9)) * 10**20))
            else:             # mixed alnum -> dict var
                parts.append(f"req-{int(rng.integers(0, 10**6))}")
        seps = [" ", "  ", "=", ": ", ", "]
        out = ""
        for p in parts:
            out += p + seps[int(rng.integers(0, len(seps)))]
        return out

    def test_random_messages_roundtrip(self):
        rng = np.random.default_rng(1717)
        msgs = [self._rand_msg(rng) for _ in range(300)]
        msgs += ["", "   ", "===", "no digits at all", "\t tab \t lead"]
        for m in msgs:
            lt, dv, ev = clp.encode_message(m)
            assert clp.decode_message(lt, dv, ev) == m
        reader = clp.CLPForwardIndexReader(clp.write_clp_column(msgs))
        assert reader.num_docs == len(msgs)
        assert list(reader.decode_all()) == msgs

    def test_get_matches_decode_all(self):
        reader = clp.CLPForwardIndexReader(clp.write_clp_column(MESSAGES))
        dec = list(reader.decode_all())
        # random access, out of order
        order = np.random.default_rng(3).permutation(len(MESSAGES))
        for i in order:
            assert reader.get(int(i)) == dec[int(i)] == MESSAGES[int(i)]


# ---------------------------------------------------------------------------
# device parity through the real engine
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    """Three CLP segments with rotated corpora (distinct doc counts so
    shape buckets get exercised) + the concatenated host truth."""
    tmp = tmp_path_factory.mktemp("clp_device")
    out, all_msgs = [], []
    for si in range(3):
        msgs = [MESSAGES[(i + si) % len(MESSAGES)]
                for i in range(100 + si * 7)]
        out.append(load_segment(build_log_seg(tmp, f"seg{si}", msgs)))
        all_msgs.extend(msgs)
    return out, all_msgs


#: LIKE patterns the planner pushes to the device (matrix: bare
#: substring, template+var, anchors, floats, IPs, unicode, full-message)
PUSHED = [
    "%failed%", "%web-01%", "INFO%", "%capacity", "%task 1234 failed%",
    "%10.0.0.1%", "%héllo%", "%code=500", "%", "%user alice%",
    "%pause 45 ms%", "%pause 450 ms%", "%in 0.5s%", "%attempt 3",
    "GC pause 45 ms in region r7",
]

#: LIKE/regex patterns that take the host path, with their EXACT
#: structured fallback reason
FALLBACKS = [
    ("%task 12%", True, "wildcard"),       # digit partial token
    ("%e%", True, "wildcard"),             # sub-token needle, enc chars
    ("%-17%", True, "wildcard"),           # sign char partial
    ("%ali%ce%", True, "partial"),         # facing partials
    ("%task%failed%code=500", True, "partial"),  # facing across pieces
    ("task 12_4", True, "charWildcard"),   # single-char wildcard
    ("user (alice|bob)", False, "regex"),  # regex alternation
]


class TestDeviceParity:
    def test_like_matrix_parity_and_meters(self, segs):
        loaded, all_msgs = segs
        eng = _engine("parity")
        dev = QueryExecutor(loaded, use_tpu=True, engine=eng)
        host = QueryExecutor(loaded, use_tpu=False)
        for pat in PUSHED + [p for p, is_like, _ in FALLBACKS if is_like]:
            sql = f"SELECT COUNT(*) FROM logs WHERE message LIKE '{pat}'"
            a, b = dev.execute(sql), host.execute(sql)
            assert not a.exceptions and not b.exceptions, pat
            assert a.result_table.rows[0][0] == \
                b.result_table.rows[0][0], pat
        # every pushed pattern served device-side; each host-path
        # pattern metered its exact structured reason
        assert _meter(eng, "clp_served") == len(PUSHED)
        for pat, is_like, reason in FALLBACKS:
            if is_like:
                assert _meter(eng, "clp_fallback", reason=reason) >= 1, pat

    def test_regexp_like_fallback_reason(self, segs):
        loaded, _ = segs
        eng = _engine("regex_fb")
        dev = QueryExecutor(loaded, use_tpu=True, engine=eng)
        host = QueryExecutor(loaded, use_tpu=False)
        sql = ("SELECT COUNT(*) FROM logs "
               "WHERE REGEXP_LIKE(message, 'user (alice|bob)')")
        a, b = dev.execute(sql), host.execute(sql)
        assert not a.exceptions and not b.exceptions
        assert a.result_table.rows[0][0] == b.result_table.rows[0][0]
        assert _meter(eng, "clp_fallback", reason="regex") >= 1
        assert _meter(eng, "clp_served") == 0

    def test_mixed_shapes_parity(self, segs):
        """CLP leaves composed with ordinary predicates, OR trees and
        GROUP BY answer identically to the host path."""
        loaded, _ = segs
        dev = QueryExecutor(loaded, use_tpu=True, engine=_engine("mixed"))
        host = QueryExecutor(loaded, use_tpu=False)
        for sql in [
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%' "
            "AND ts < 50",
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%' "
            "OR message LIKE 'INFO%'",
            "SELECT ts, COUNT(*) FROM logs WHERE message LIKE '%failed%' "
            "GROUP BY ts ORDER BY ts LIMIT 5",
        ]:
            a, b = dev.execute(sql), host.execute(sql)
            assert not a.exceptions and not b.exceptions, sql
            assert sorted(map(str, a.result_table.rows)) == \
                sorted(map(str, b.result_table.rows)), sql

    def test_fallback_reasons_exact(self, segs):
        """The planner's structured reasons, asserted pattern by
        pattern (the meter test above only proves >=1 each)."""
        loaded, _ = segs
        for pat, is_like, want in FALLBACKS:
            meta, reason = clp_device.plan_leaf(loaded, "message", pat,
                                                is_like)
            assert meta is None and reason == want, (pat, reason, want)
        for pat in PUSHED:
            meta, reason = clp_device.plan_leaf(loaded, "message", pat,
                                                True)
            assert meta is not None, (pat, reason)
        assert set(r for _, _, r in FALLBACKS) <= \
            set(clp_device.FALLBACK_REASONS)

    def test_knob_disables_the_leg(self, segs):
        loaded, all_msgs = segs
        eng = _engine("knob", **{"pinot.server.clp.enabled": False})
        dev = QueryExecutor(loaded, use_tpu=True, engine=eng)
        r = dev.execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%'")
        assert not r.exceptions
        assert r.result_table.rows[0][0] == \
            sum(1 for m in all_msgs if "failed" in m)
        assert _meter(eng, "clp_served") == 0
        assert _meter(eng, "clp_fallback", reason="disabled") >= 1

    def test_non_resident_tier_still_serves(self, segs):
        """pinot.server.clp.hbm.resident=false: pseudo-columns take the
        legacy whole-block upload path, answers unchanged."""
        loaded, all_msgs = segs
        eng = _engine("nonres", **{"pinot.server.clp.hbm.resident": False})
        dev = QueryExecutor(loaded, use_tpu=True, engine=eng)
        r = dev.execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%web-01%'")
        assert not r.exceptions
        assert r.result_table.rows[0][0] == \
            sum(1 for m in all_msgs if "web-01" in m)
        assert _meter(eng, "clp_served") == 1


class TestZeroRetrace:
    def test_pattern_constants_share_one_kernel(self, segs):
        """The pattern never enters the DeviceLeaf: fingerprint-equal
        queries whose LIKE constants differ resolve their LUTs at
        staging and replay the SAME compiled kernel — zero retraces
        once the shape is warm."""
        loaded, all_msgs = segs
        eng = _engine("retrace")
        dev = QueryExecutor(loaded, use_tpu=True, engine=eng)
        sql = "SELECT COUNT(*) FROM logs WHERE message LIKE '%web-01%'"
        assert not dev.execute(sql).exceptions  # warm the shape bucket
        t0 = kernels.trace_count()
        for needle in ["web-02", "db-01", "capacity", "alice"]:
            r = dev.execute("SELECT COUNT(*) FROM logs "
                            f"WHERE message LIKE '%{needle}%'")
            assert not r.exceptions
            assert r.result_table.rows[0][0] == \
                sum(1 for m in all_msgs if needle in m)
        assert kernels.trace_count() == t0


# ---------------------------------------------------------------------------
# realtime log ingestion
# ---------------------------------------------------------------------------
class TestMutableClpIngestion:
    def _mutable(self):
        from pinot_tpu.ingest import MutableSegment
        tc = TableConfig("logs", TableType.REALTIME)
        tc.indexing.clp_columns = ["message"]
        return MutableSegment("logs__0__0__1", tc, log_schema())

    def test_ingest_encodes_and_queries(self):
        seg = self._mutable()
        n = 200
        for i in range(n):
            seg.index({"ts": i, "message": MESSAGES[i % len(MESSAGES)]})
        seg.index({"ts": n, "message": None})
        assert seg.num_docs == n + 1
        # ingest stored TEMPLATES: cardinality is the logtype count, an
        # order of magnitude under the doc count
        card = seg.metadata.columns["message"].cardinality
        assert 0 < card <= len(MESSAGES)
        r = QueryExecutor([seg], use_tpu=False).execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%'")
        want = sum(1 for i in range(n)
                   if "failed" in MESSAGES[i % len(MESSAGES)])
        assert r.rows[0][0] == want

    def test_seal_builds_clp_segment_device_serves(self, tmp_path):
        seg = self._mutable()
        msgs = [MESSAGES[i % len(MESSAGES)] for i in range(150)]
        for i, m in enumerate(msgs):
            seg.index({"ts": i, "message": m})
        # the seal path: to_columns() -> SegmentCreator under the SAME
        # table config (realtime_manager wires exactly this)
        out = str(tmp_path / "sealed")
        SegmentCreator(seg.table_config, seg.schema).build(
            seg.to_columns(), out, "logs__0__0__1")
        sealed = load_segment(out)
        assert it.CLP in sealed.metadata.columns["message"].indexes
        assert list(sealed.data_source("message").values()) == msgs
        eng = _engine("sealed")
        r = QueryExecutor([sealed], use_tpu=True, engine=eng).execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%web-01%'")
        assert not r.exceptions
        assert r.result_table.rows[0][0] == \
            sum(1 for m in msgs if "web-01" in m)
        assert _meter(eng, "clp_served") == 1


# ---------------------------------------------------------------------------
# minion compaction
# ---------------------------------------------------------------------------
def compaction_state(tmp, n_segments=2):
    """Plain (non-CLP) sealed log segments under a table whose config
    declares clp_columns — the generator's work list."""
    cfg = TableConfig("logs")
    cfg.indexing.clp_columns = ["message"]
    cfg.task_configs = {"ClpCompactionTask": {}}
    state = ClusterState()
    state.add_table(cfg, log_schema())
    for i in range(n_segments):
        msgs = [MESSAGES[(j + i) % len(MESSAGES)] for j in range(80)]
        d = build_log_seg(tmp, f"s{i}", msgs, clp_col=False)
        m = load_segment(d).metadata
        state.upsert_segment(SegmentState(
            f"s{i}", "logs_REALTIME", [], dir_path=d, num_docs=80,
            start_time=m.start_time, end_time=m.end_time))
    return state


def _manager(state):
    return TaskManager(state, config=PinotConfiguration(overrides={
        "pinot.controller.task.generators.enabled": True,
        "pinot.controller.task.retry.backoff.seconds": 0.0}))


class TestClpCompaction:
    def test_generator_converges_and_device_serves(self, tmp_path):
        state = compaction_state(tmp_path)
        tm = _manager(state)
        assert tm.run_once()["generated"] == 1
        task = tm.queue.lease("w0")
        res = run_task(
            TaskConfig(task.task_type, task.table, list(task.segments),
                       dict(task.params), task_id=task.task_id),
            TaskContext(state, str(tmp_path / "out"),
                        task_id=task.task_id))
        assert sorted(res["compactedSegments"]) == ["s0_clp", "s1_clp"]
        assert res["clpColumns"] == ["message"]
        tm.queue.complete(task.task_id, "w0", res)
        names = {s.name for s in state.table_segments("logs_REALTIME")}
        assert names == {"s0_clp", "s1_clp"}
        rebuilt = [load_segment(state.segments["logs_REALTIME"][n].dir_path)
                   for n in sorted(names)]
        for seg in rebuilt:
            assert it.CLP in seg.metadata.columns["message"].indexes
            assert seg.num_docs == 80
        # compacted segments serve the DEVICE pushdown leg; parity with
        # a host scan over the ORIGINAL plain segments
        eng = _engine("compact_serve")
        r = QueryExecutor(rebuilt, use_tpu=True, engine=eng).execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%'")
        assert not r.exceptions
        assert _meter(eng, "clp_served") == 1
        orig = [load_segment(str(tmp_path / f"s{i}")) for i in range(2)]
        want = QueryExecutor(orig, use_tpu=False).execute(
            "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%'")
        assert r.result_table.rows[0][0] == want.rows[0][0]
        # second tick: it.CLP metadata marker -> nothing left to do
        assert tm.run_once()["generated"] == 0

    def test_no_clp_columns_generates_nothing(self, tmp_path):
        state = compaction_state(tmp_path)
        state.tables["logs"].indexing.clp_columns = []
        assert _manager(state).run_once()["generated"] == 0

    def _run_flow(self, tmp_path, tag, chaos):
        """generate -> lease -> (crash -> expire -> re-lease) -> encode;
        returns the compacted segments' raw CLP buffers."""
        tmp = tmp_path / tag
        tmp.mkdir()
        state = compaction_state(tmp)
        tm = _manager(state)
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        task = tm.queue.lease("w0", lease_ttl_s=0.01)
        cfg = TaskConfig(task.task_type, task.table, list(task.segments),
                         dict(task.params), task_id=task.task_id)
        ctx = TaskContext(state, str(tmp / "out"), task_id=task.task_id)
        if chaos:
            failpoints.arm("minion.clp.compact",
                           error=SimulatedCrash("chaos kill"), times=1)
            with pytest.raises(SimulatedCrash):
                run_task(cfg, ctx)
            # crash fired BEFORE any re-encode: sources untouched and
            # still answering via the host decode path
            segs = [load_segment(s.dir_path)
                    for s in state.table_segments("logs_REALTIME")]
            assert {s.name for s in segs} == {"s0", "s1"}
            r = QueryExecutor(segs, use_tpu=False).execute(
                "SELECT COUNT(*) FROM logs WHERE message LIKE '%failed%'")
            assert r.rows[0][0] > 0
            # worker vanished: lease expiry requeues, another picks it up
            time.sleep(0.02)
            assert tm.queue.expire_leases() == [entry.task_id]
            task = tm.queue.lease("w1")
            assert task.task_id == entry.task_id
        res = run_task(cfg, ctx)
        tm.queue.complete(task.task_id, task.worker, res)
        assert sorted(res["compactedSegments"]) == ["s0_clp", "s1_clp"]
        return {
            n: bytes(load_segment(
                state.segments["logs_REALTIME"][n].dir_path
            ).dir.get_buffer("message", it.CLP))
            for n in res["compactedSegments"]}

    def test_crashed_compaction_releases_and_reencodes_byte_identical(
            self, tmp_path):
        baseline = self._run_flow(tmp_path, "nochaos", chaos=False)
        chaosed = self._run_flow(tmp_path, "chaos", chaos=True)
        assert baseline == chaosed  # CLP buffer BYTES, not just answers


# ---------------------------------------------------------------------------
# tenant-weighted minion lease
# ---------------------------------------------------------------------------
class TestTenantWeightedLease:
    def _fill(self, q, n_a=6, n_b=2):
        for i in range(n_a):
            q.submit(TaskConfig("PurgeTask", "A_OFFLINE", [f"a{i}"]))
        for i in range(n_b):
            q.submit(TaskConfig("PurgeTask", "B_OFFLINE", [f"b{i}"]))

    def test_weighted_shares(self):
        """Weight 3 vs 1: under contention table A leases 3x as often —
        the deterministic virtual-clock sequence, not just the ratio."""
        q = TaskQueue(tenant_weight_of=lambda t: 3.0
                      if t.startswith("A") else 1.0)
        self._fill(q)
        got = [q.lease("w").table[0] for _ in range(8)]
        assert got == ["A", "B", "A", "A", "A", "B", "A", "A"]

    def test_default_weight_is_round_robin(self):
        q = TaskQueue()  # no weight provider: plain round-robin
        self._fill(q, n_a=3, n_b=3)
        got = [q.lease("w").table[0] for _ in range(6)]
        assert got == ["A", "B", "A", "B", "A", "B"]

    def test_manager_reads_tenant_config_weight(self, tmp_path):
        """TaskManager wires TableConfig.tenants.weight into the queue's
        weight provider."""
        state = ClusterState()
        cfg_a, cfg_b = TableConfig("A"), TableConfig("B")
        cfg_a.tenants.weight = 3.0
        state.add_table(cfg_a, log_schema("A"))
        state.add_table(cfg_b, log_schema("B"))
        tm = TaskManager(state)
        assert tm._tenant_weight("A_OFFLINE") == 3.0
        assert tm._tenant_weight("B_REALTIME") == 1.0
        assert tm._tenant_weight("unknown_OFFLINE") == 1.0


# ---------------------------------------------------------------------------
# workload-driven star-tree scheduling
# ---------------------------------------------------------------------------
ST_TREE_CFG = {"dimensionsSplitOrder": ["d"],
               "functionColumnPairs": ["SUM__m"],
               "maxLeafRecords": 5}


def startree_state(tmp):
    schema = Schema("ct", [
        FieldSpec("d", DataType.STRING),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])
    cfg = TableConfig("ct")
    cfg.task_configs = {"AutoStarTreeTask": {
        "starTreeIndexConfigs": [ST_TREE_CFG],
        "minCostMs": 100.0, "minQueries": 2}}
    state = ClusterState()
    state.add_table(cfg, schema)
    rng = np.random.default_rng(7)
    cols = {"d": [f"k{v}" for v in rng.integers(0, 5, 100)],
            "ts": np.arange(100, dtype=np.int64),
            "m": rng.integers(0, 50, 100).astype(np.int64)}
    d = str(tmp / "s0")
    SegmentCreator(TableConfig("ct"), schema).build(cols, d, "s0")
    m = load_segment(d).metadata
    state.upsert_segment(SegmentState(
        "s0", "ct_REALTIME", [], dir_path=d, num_docs=100,
        start_time=m.start_time, end_time=m.end_time))
    return state


class TestAutoStarTree:
    def test_cold_workload_schedules_nothing(self, tmp_path):
        tm = _manager(startree_state(tmp_path))
        tm.workload_provider = lambda: WorkloadRegistry("t_cold")
        assert tm.run_once()["generated"] == 0

    def test_hot_fingerprint_schedules_build(self, tmp_path):
        tm = _manager(startree_state(tmp_path))
        reg = WorkloadRegistry("t_hot")
        tm.workload_provider = lambda: reg
        # one cheap query: below both floors -> still nothing
        reg.record(tenant="t", table="ct_REALTIME", fingerprint="fp",
                   cpu_ms=10.0)
        assert tm.run_once()["generated"] == 0
        # repeated expensive fingerprint -> hot -> a build is scheduled
        for _ in range(2):
            reg.record(tenant="t", table="ct_REALTIME", fingerprint="fp",
                       cpu_ms=500.0)
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        assert entry.task_type == "StarTreeBuildTask"
        assert entry.segments == ["s0"]

    def test_other_tables_heat_does_not_leak(self, tmp_path):
        """A hot fingerprint on an UNRELATED table must not trigger this
        table's builds."""
        tm = _manager(startree_state(tmp_path))
        reg = WorkloadRegistry("t_leak")
        tm.workload_provider = lambda: reg
        for _ in range(3):
            reg.record(tenant="t", table="other_REALTIME",
                       fingerprint="fp", cpu_ms=900.0)
        assert tm.run_once()["generated"] == 0


# ---------------------------------------------------------------------------
# bench --logs smoke (satellite d/f: the mixed-tenant OLAP-SLO scenario
# rides in tier-1 at smoke scale)
# ---------------------------------------------------------------------------
class TestBenchSmoke:
    def test_logs_bench_smoke(self, tmp_path):
        """The --logs acceptance scenario at smoke scale: pushdown A/B
        with bit-exact parity + clp_served metering, constant-different
        LIKE coalescing with ZERO steady-state retraces, realtime CLP
        ingestion with exactly-once convergence through a seeded
        mid-batch consumer kill, and the mixed-tenant window where the
        weighted OLAP fleet keeps serving beside log LIKE traffic."""
        import importlib
        import json
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_logs_smoke.json")
        bench.logs_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["clp_served"] >= 5
        assert data["coalesce"]["retraces_steady"] == 0
        assert data["coalesce"]["batch_size_max"] >= 2
        assert data["ingest"]["exact"][0] == data["ingest"]["exact"][1]
        assert data["ingest"]["failed_queries"] == 0
        assert data["chaos"]["crashed"] and data["chaos"]["converged"]
        assert data["chaos"]["failed_queries"] == 0
        assert data["mixed_tenants"]["failed_queries"] == 0
        assert data["mixed_tenants"]["olap_queries"] > 0
        assert data["mixed_tenants"]["log_queries"] > 0
