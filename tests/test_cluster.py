"""Cluster integration: broker -> TCP -> servers -> reduce (the embedded
ClusterTest analog, SURVEY.md §4.4) plus DataTable serde round-trips."""
import json
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.query.results import (
    AggregationResult, DistinctResult, ExecutionStats, GroupByResult,
    SelectionResult)
from pinot_tpu.query.aggregation.sketches import HyperLogLog, TDigest
from pinot_tpu.server import datatable
from tests.queries.harness import (
    build_segments, synthetic_columns, synthetic_schema, synthetic_table_config)

NUM_DOCS = 1000


class TestDataTableSerde:
    def test_aggregation_roundtrip(self):
        hll = HyperLogLog(8)
        hll.add_array(np.arange(500))
        td = TDigest(100.0)
        td.add_array(np.random.default_rng(0).random(1000))
        r = AggregationResult(
            [1.5, 42, (3.0, 7), {"a": 1}, {1, 2, 3}, hll, td, None, "x"],
            ExecutionStats(num_docs_scanned=10, total_docs=100))
        buf = datatable.serialize_results([r])
        [out], exc, _ = datatable.deserialize_results(buf)
        assert exc == []
        assert out.intermediates[0] == 1.5
        assert out.intermediates[1] == 42
        assert tuple(out.intermediates[2]) == (3.0, 7)
        assert out.intermediates[3] == {"a": 1}
        assert out.intermediates[4] == {1, 2, 3}
        assert out.intermediates[5].cardinality() == hll.cardinality()
        assert abs(out.intermediates[6].quantile(0.5) - td.quantile(0.5)) < 1e-9
        assert out.intermediates[7] is None
        assert out.intermediates[8] == "x"
        assert out.stats.num_docs_scanned == 10
        assert out.stats.total_docs == 100

    def test_group_by_roundtrip(self):
        r = GroupByResult({("a", 1): [1.0, 2], ("b", 2): [3.0, 4]},
                          ExecutionStats(), num_groups_limit_reached=True)
        buf = datatable.serialize_results([r])
        [out], _, _ = datatable.deserialize_results(buf)
        assert out.groups == r.groups
        assert out.num_groups_limit_reached is True

    def test_selection_roundtrip(self):
        r = SelectionResult([(1, "x"), (2, "y")],
                            order_values=[(1,), (2,)],
                            columns=["a", "b"], stats=ExecutionStats())
        buf = datatable.serialize_results([r])
        [out], _, _ = datatable.deserialize_results(buf)
        assert out.rows == r.rows
        assert out.order_values == r.order_values
        assert out.columns == ["a", "b"]

    def test_exceptions(self):
        buf = datatable.serialize_results(
            [], [{"errorCode": 190, "message": "no table"}])
        results, exc, _ = datatable.deserialize_results(buf)
        assert results == []
        assert exc == [{"errorCode": 190, "message": "no table"}]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cluster")
    data = [synthetic_columns(NUM_DOCS, seed=7 + i) for i in range(4)]
    segs = build_segments(tmp, synthetic_schema(), synthetic_table_config(), data)
    c = MiniCluster(num_servers=2)
    c.start(with_http=True)
    c.add_table("testTable")
    for i, seg in enumerate(segs):
        c.add_segment("testTable", seg, server_idx=i % 2)
    yield c, data
    c.stop()


class TestMiniCluster:
    def test_count_star(self, cluster):
        c, data = cluster
        resp = c.query("SELECT COUNT(*) FROM testTable")
        assert resp.rows[0][0] == NUM_DOCS * 4
        assert resp.num_servers_queried == 2
        assert resp.num_servers_responded == 2
        assert resp.stats.num_segments_processed == 4

    def test_filtered_agg_across_servers(self, cluster):
        c, data = cluster
        v = np.concatenate([d["intCol"] for d in data])
        resp = c.query("SELECT SUM(intCol), MAX(intCol) FROM testTable "
                       "WHERE intCol >= 500")
        assert resp.rows[0][0] == pytest.approx(float(v[v >= 500].sum()))
        assert resp.rows[0][1] == pytest.approx(float(v.max()))

    def test_group_by_across_servers(self, cluster):
        c, data = cluster
        g = np.concatenate([np.asarray(d["groupCol"]) for d in data])
        resp = c.query("SELECT groupCol, COUNT(*) FROM testTable "
                       "GROUP BY groupCol ORDER BY groupCol LIMIT 100")
        from collections import Counter
        counts = Counter(g.tolist())
        assert {r[0]: r[1] for r in resp.rows} == dict(counts)

    def test_distinctcount_merge(self, cluster):
        c, data = cluster
        s = np.concatenate([np.asarray(d["stringCol"]) for d in data])
        resp = c.query("SELECT DISTINCTCOUNT(stringCol) FROM testTable")
        assert resp.rows[0][0] == len(np.unique(s))

    def test_selection_order_by(self, cluster):
        c, data = cluster
        v = np.concatenate([d["intCol"] for d in data])
        resp = c.query("SELECT intCol FROM testTable ORDER BY intCol DESC LIMIT 5")
        assert [r[0] for r in resp.rows] == np.sort(v)[::-1][:5].tolist()

    def test_unknown_table(self, cluster):
        c, _ = cluster
        resp = c.query("SELECT COUNT(*) FROM nope")
        assert resp.exceptions and resp.exceptions[0]["errorCode"] == 190

    def test_parse_error(self, cluster):
        c, _ = cluster
        resp = c.query("SELEC broken")
        assert resp.exceptions and resp.exceptions[0]["errorCode"] == 150

    def test_http_endpoint(self, cluster):
        c, _ = cluster
        req = urllib.request.Request(
            f"http://127.0.0.1:{c.http.port}/query/sql",
            data=json.dumps({"sql": "SELECT COUNT(*) FROM testTable"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as f:
            body = json.loads(f.read())
        assert body["resultTable"]["rows"][0][0] == NUM_DOCS * 4
        assert body["numServersResponded"] == 2


class TestHybridTable:
    def test_time_boundary_split(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("hybrid")
        from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                      TableConfig, TableType)
        schema = Schema("hybrid", [
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
            FieldSpec("val", DataType.INT, FieldType.METRIC),
        ])
        tc = TableConfig("hybrid", TableType.OFFLINE)
        tc.retention.time_column = "ts"
        # offline: ts 0..99 (incl. overlap with realtime), realtime: ts 80..199
        off = build_segments(tmp, schema, tc, [{
            "ts": np.arange(0, 100, dtype=np.int64),
            "val": np.ones(100, dtype=np.int32)}])[0]
        rt = build_segments(tmp_path_factory.mktemp("hybrid_rt"), schema, tc, [{
            "ts": np.arange(80, 200, dtype=np.int64),
            "val": np.full(120, 2, dtype=np.int32)}])[0]
        c = MiniCluster(num_servers=1)
        c.start()
        try:
            c.add_table("hybrid", "OFFLINE", time_column="ts")
            c.add_table("hybrid", "REALTIME", time_column="ts", time_boundary=99)
            c.add_segment("hybrid", off, 0, "OFFLINE")
            c.add_segment("hybrid", rt, 0, "REALTIME")
            resp = c.query("SELECT COUNT(*), SUM(val) FROM hybrid")
            # offline serves ts <= 99 (100 docs of val 1);
            # realtime serves ts > 99 (100 docs of val 2) — overlap dropped
            assert resp.rows[0][0] == 200
            assert resp.rows[0][1] == pytest.approx(100 * 1 + 100 * 2)
        finally:
            c.stop()


class TestReviewRegressions:
    def test_hybrid_query_with_keywordish_text(self, tmp_path_factory):
        """Time-boundary must not corrupt queries containing keyword-like
        identifiers or literals (travels as structured extraFilter now)."""
        from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                      TableConfig, TableType)
        schema = Schema("hybrid2", [
            FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
            FieldSpec("options", DataType.STRING),
            FieldSpec("msg", DataType.STRING),
        ])
        tc = TableConfig("hybrid2", TableType.OFFLINE)
        tc.retention.time_column = "ts"
        tmp = tmp_path_factory.mktemp("hybrid2")
        off = build_segments(tmp, schema, tc, [{
            "ts": np.arange(0, 100, dtype=np.int64),
            "options": ["yes" if i % 2 else "no" for i in range(100)],
            "msg": ["rate limit hit" if i % 4 == 0 else "ok" for i in range(100)],
        }])[0]
        rt = build_segments(tmp_path_factory.mktemp("hybrid2rt"), schema, tc, [{
            "ts": np.arange(100, 200, dtype=np.int64),
            "options": ["yes"] * 100,
            "msg": ["ok"] * 100,
        }])[0]
        c = MiniCluster(num_servers=1)
        c.start()
        try:
            c.add_table("hybrid2", "OFFLINE", time_column="ts")
            c.add_table("hybrid2", "REALTIME", time_column="ts", time_boundary=99)
            c.add_segment("hybrid2", off, 0, "OFFLINE")
            c.add_segment("hybrid2", rt, 0, "REALTIME")
            r = c.query("SELECT options FROM hybrid2 LIMIT 500")
            assert not r.exceptions, r.exceptions
            assert len(r.rows) == 200
            r = c.query("SELECT COUNT(*) FROM hybrid2 WHERE msg = 'rate limit hit'")
            assert not r.exceptions, r.exceptions
            assert r.rows[0][0] == 25
        finally:
            c.stop()

    def test_all_pruned_stats_survive_wire(self, cluster):
        c, _ = cluster
        resp = c.query("SELECT COUNT(*) FROM testTable WHERE intCol > 99999")
        assert resp.stats.num_segments_pruned == 4
        assert resp.stats.total_docs == NUM_DOCS * 4

    def test_segment_refresh_invalidates_device_cache(self, tmp_path_factory):
        """A refreshed segment (same name, new data) must not serve stale
        HBM blocks."""
        from pinot_tpu.ops.engine import TpuOperatorExecutor
        from pinot_tpu.query.executor import QueryExecutor
        tmp = tmp_path_factory.mktemp("refresh")
        data1 = {"intCol": np.full(512, 1, dtype=np.int32),
                 "longCol": np.arange(512, dtype=np.int64),
                 "floatCol": np.ones(512, dtype=np.float32),
                 "doubleCol": np.ones(512),
                 "stringCol": ["a"] * 512, "groupCol": ["g"] * 512,
                 "rawIntCol": np.full(512, 1, dtype=np.int32)}
        data2 = dict(data1)
        data2["intCol"] = np.full(512, 2, dtype=np.int32)
        seg1 = build_segments(tmp, synthetic_schema(), synthetic_table_config(),
                              [data1])[0]
        engine = TpuOperatorExecutor()
        ex1 = QueryExecutor([seg1], use_tpu=True, engine=engine)
        r1 = ex1.execute("SELECT SUM(intCol) FROM testTable")
        assert r1.rows[0][0] == 512
        # refresh: same segment name, new contents, new object
        seg2 = build_segments(tmp_path_factory.mktemp("refresh2"),
                              synthetic_schema(), synthetic_table_config(),
                              [data2])[0]
        ex2 = QueryExecutor([seg2], use_tpu=True, engine=engine)
        r2 = ex2.execute("SELECT SUM(intCol) FROM testTable")
        assert r2.rows[0][0] == 1024


class TestConsumerResilience:
    def test_bad_record_does_not_kill_consumer(self, tmp_path):
        import time as _time
        from pinot_tpu.ingest import InMemoryStream, StreamConfig
        from pinot_tpu.ingest.realtime_manager import RealtimeSegmentDataManager
        from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                      TableConfig, TableType)
        from pinot_tpu.server.data_manager import TableDataManager
        schema = Schema("r", [FieldSpec("id", DataType.LONG),
                              FieldSpec("v", DataType.DOUBLE, FieldType.METRIC)])
        topic = InMemoryStream("bad_topic", 1)
        try:
            tdm = TableDataManager("r_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="bad_topic",
                              flush_threshold_rows=10_000)
            mgr = RealtimeSegmentDataManager(
                TableConfig("r", TableType.REALTIME), schema, sc, 0, tdm,
                str(tmp_path))
            topic.publish({"id": 1, "v": 1.0})
            topic.publish({"id": "not-a-number", "v": 2.0})  # poison
            topic.publish({"id": 3, "v": 3.0})
            mgr.start()
            deadline = _time.time() + 10
            while _time.time() < deadline and mgr.mutable.num_docs < 2:
                _time.sleep(0.05)
            mgr.stop()
            assert mgr.mutable.num_docs == 2  # poison skipped, rest ingested
            assert mgr.error_count == 1
        finally:
            InMemoryStream.delete("bad_topic")
