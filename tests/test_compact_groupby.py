"""Compacted (sparse-key) device group-by.

Dense mixed-radix keys explode as the PRODUCT of cardinalities (three
1000-card dims = 1e9 keys); the compact path scatter-adds over per-segment
OBSERVED key codes instead. Ref: pinot-core
query/aggregation/groupby/DictionaryBasedGroupKeyGenerator.java map-based
modes — VERDICT r3 item 4.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from tests.queries.harness import assert_responses_equal


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("compactgb")
    schema = Schema("t", [
        FieldSpec("a", DataType.INT, FieldType.DIMENSION),
        FieldSpec("b", DataType.INT, FieldType.DIMENSION),
        FieldSpec("c", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(31)
    out = []
    for i in range(2):
        n = 20000
        cols = {
            # ~1000 distinct values per column: the dense key space is
            # ~1e9 >> MAX_DEVICE_GROUPS, but observed tuples <= n
            "a": rng.integers(0, 1000, n).astype(np.int32),
            "b": (rng.integers(0, 1000, n) * 7).astype(np.int32),
            "c": rng.integers(0, 900, n).astype(np.int32),
            "m": rng.integers(0, 1000, n).astype(np.int32),
        }
        d = str(tmp / f"seg_{i}")
        creator.build(cols, d, f"t_{i}")
        out.append(load_segment(d))
    return out


class TestCompactGroupBy:
    SQL = ("SELECT a, b, c, SUM(m), COUNT(*) FROM t "
           "GROUP BY a, b, c ORDER BY a, b, c LIMIT 100000")

    def test_plan_switches_to_compact(self, segs):
        eng = TpuOperatorExecutor()
        ctx = QueryContext.from_sql(self.SQL)
        plan, _ = eng._plan(segs, ctx)
        assert plan.group_compact
        assert plan.num_groups == 0
        # group-only columns drop their id planes (gkey replaces them)
        assert "a" not in plan.dict_cols

    def test_three_col_card1000_parity(self, segs):
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True,
                            engine=TpuOperatorExecutor())
        a = cpu.execute(self.SQL)
        b = tpu.execute(self.SQL)
        assert not a.exceptions and not b.exceptions
        assert_responses_equal(a, b, self.SQL)
        assert len(a.result_table.rows) > 10000  # genuinely sparse+wide
        assert any(k[1] == "gkey" for k in
                   tpu.tpu_engine._block_cache), "compact path not used"

    def test_with_filter_and_min_max(self, segs):
        sql = ("SELECT a, b, c, MIN(m), MAX(m), AVG(m) FROM t "
               "WHERE c BETWEEN 100 AND 700 AND a < 900 "
               "GROUP BY a, b, c ORDER BY a, b, c LIMIT 100000")
        eng = TpuOperatorExecutor()
        ctx = QueryContext.from_sql(sql)
        plan, _ = eng._plan(segs, ctx)
        assert plan.group_compact
        # the filter still needs a/c id planes even in compact mode
        assert "a" in plan.dict_cols and "c" in plan.dict_cols
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        assert_responses_equal(cpu.execute(sql), tpu.execute(sql), sql)

    def test_dense_path_still_used_when_small(self, segs):
        eng = TpuOperatorExecutor()
        ctx = QueryContext.from_sql(
            "SELECT c, COUNT(*) FROM t GROUP BY c LIMIT 1000")
        plan, _ = eng._plan(segs, ctx)
        assert not plan.group_compact and plan.num_groups > 0

    def test_repeat_query_hits_gkey_cache(self, segs):
        eng = TpuOperatorExecutor()
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        tpu.execute(self.SQL)
        hosts_before = len(eng._host_rows)
        tpu.execute(self.SQL)
        assert len(eng._host_rows) == hosts_before  # no re-factorize
