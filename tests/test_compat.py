"""Format-compatibility verifier: golden segments must stay readable.

Ref: compatibility-verifier/ (compCheck.sh runs old/new versions side by
side through yaml-scripted ops). Single-language analog: a segment built
by an EARLIER revision is committed as a fixture
(tests/golden/golden_segment_v1.tar.gz + its expected answers); every
future revision must load it and answer identically — a breaking change
to the on-disk format or query semantics fails here, not in production.
"""
import json
import os
import tarfile

import pytest

from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.loader import load_segment

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


@pytest.fixture(scope="module")
def golden_segment(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("golden")
    with tarfile.open(os.path.join(GOLDEN_DIR,
                                   "golden_segment_v1.tar.gz")) as tar:
        tar.extractall(tmp, filter="data")
    return load_segment(str(tmp / "golden_0"))


@pytest.fixture(scope="module")
def answers():
    with open(os.path.join(GOLDEN_DIR, "golden_answers.json")) as f:
        return json.load(f)


class TestGoldenCompat:
    def test_loads_and_answers(self, golden_segment, answers):
        ex = QueryExecutor([golden_segment], use_tpu=False)
        r = ex.execute("SELECT COUNT(*), SUM(v) FROM golden")
        assert r.rows[0] == (answers["count"], float(answers["sum_v"]))

    def test_index_backed_paths(self, golden_segment, answers):
        ex = QueryExecutor([golden_segment], use_tpu=False)
        assert ex.execute(
            "SELECT DISTINCTCOUNT(name) FROM golden"
        ).rows[0][0] == answers["distinct_names"]
        assert ex.execute(
            "SELECT COUNT(*) FROM golden WHERE v > 500"
        ).rows[0][0] == answers["v_gt_500"]
        assert ex.execute(
            "SELECT COUNT(*) FROM golden WHERE "
            "json_match(tags, '\"k\" = 3')"
        ).rows[0][0] == answers["json_k3"]
        assert ex.execute(
            "SELECT COUNT(*) FROM golden WHERE "
            "text_match(name, 'alpha')"
        ).rows[0][0] == answers["count"]

    def test_device_path_agrees(self, golden_segment, answers):
        dev = QueryExecutor([golden_segment], use_tpu=True)
        r = dev.execute("SELECT COUNT(*), SUM(v) FROM golden")
        assert r.rows[0] == (answers["count"], float(answers["sum_v"]))
