"""Segment completion FSM: exactly one committer across replicas.

Ref: pinot-controller realtime/BlockingSegmentCompletionFSM.java +
SegmentCompletionManager.java — VERDICT r3 item 8. The integration test is
the LLC multi-replica scenario: two servers consume the SAME partition and
exactly one commits each segment; the other keeps its row-identical copy.
"""
import time

import pytest

from pinot_tpu.controller.completion import (
    CATCHUP, COMMIT, DISCARD, HOLD, KEEP, SegmentCompletionManager)
from pinot_tpu.ingest import InMemoryStream, LongMsgOffset, StreamConfig
from pinot_tpu.ingest.realtime_manager import RealtimeSegmentDataManager
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.server.data_manager import TableDataManager


class TestFsmUnit:
    def test_single_replica_commits_immediately(self):
        m = SegmentCompletionManager(num_replicas=1)
        r = m.segment_consumed("s0", "seg__0__0__1", 100)
        assert r.action == COMMIT
        m.segment_commit_end("s0", "seg__0__0__1", 100, "/tmp/x")
        assert m.state_of("seg__0__0__1") == "COMMITTED"

    def test_two_replicas_one_committer(self):
        m = SegmentCompletionManager(num_replicas=2)
        assert m.segment_consumed("s0", "seg", 100).action == HOLD
        r1 = m.segment_consumed("s1", "seg", 100)
        # replica set complete: s1 sees the election result directly
        assert r1.action in (COMMIT, HOLD)
        r0 = m.segment_consumed("s0", "seg", 100)
        actions = {r0.action, r1.action}
        assert COMMIT in actions and HOLD in actions
        committer = "s0" if r0.action == COMMIT else "s1"
        loser = "s1" if committer == "s0" else "s0"
        m.segment_commit_end(committer, "seg", 100, "/d")
        r = m.segment_consumed(loser, "seg", 100)
        assert r.action == KEEP

    def test_laggard_catches_up_then_winner_elected_by_offset(self):
        m = SegmentCompletionManager(num_replicas=2)
        m.segment_consumed("s0", "seg", 80)
        r1 = m.segment_consumed("s1", "seg", 100)
        assert r1.action == COMMIT  # max offset wins
        r0 = m.segment_consumed("s0", "seg", 80)
        assert r0.action == CATCHUP and r0.offset == 100
        m.segment_commit_end("s1", "seg", 100, "/d")
        # the laggard could not reach 100 (e.g. stream truncated): DISCARD
        r0 = m.segment_consumed("s0", "seg", 80)
        assert r0.action == DISCARD
        assert r0.offset == 100 and r0.download_path == "/d"
        # once caught up exactly: KEEP
        assert m.segment_consumed("s0", "seg", 100).action == KEEP

    def test_deadline_elects_with_partial_replica_set(self):
        m = SegmentCompletionManager(num_replicas=2, hold_deadline_s=0.05)
        assert m.segment_consumed("s0", "seg", 50).action == HOLD
        time.sleep(0.07)
        assert m.segment_consumed("s0", "seg", 50).action == COMMIT

    def test_failed_commit_reelects(self):
        m = SegmentCompletionManager(num_replicas=2)
        m.segment_consumed("s0", "seg", 100)
        m.segment_consumed("s1", "seg", 100)
        r0 = m.segment_consumed("s0", "seg", 100)
        committer = "s0" if r0.action == COMMIT else "s1"
        m.segment_commit_end(committer, "seg", 100, success=False)
        # next reporter triggers re-election and someone commits again
        acts = {m.segment_consumed("s0", "seg", 100).action,
                m.segment_consumed("s1", "seg", 100).action}
        assert COMMIT in acts

    def test_controller_assigned_names_are_stable(self):
        m = SegmentCompletionManager(num_replicas=2)
        a = m.segment_name("rt", 0, 3)
        b = m.segment_name("rt", 0, 3)
        assert a == b and a.startswith("rt__0__3__")


# ---------------------------------------------------------------------------
# multi-replica integration: 2 servers, same partition, one committer
# ---------------------------------------------------------------------------

def _schema():
    return Schema("rt", [
        FieldSpec("id", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("score", DataType.DOUBLE, FieldType.METRIC)])


class TestTwoReplicaIntegration:
    def test_exactly_one_committer_per_segment(self, tmp_path):
        topic = InMemoryStream("fsm_topic", num_partitions=1)
        try:
            completion = SegmentCompletionManager(num_replicas=2,
                                                  hold_deadline_s=10.0)
            sc = StreamConfig(stream_type="inmemory", topic="fsm_topic",
                              flush_threshold_rows=100)
            tdms, mgrs, commits = [], [], {"server_0": [], "server_1": []}
            for i in range(2):
                inst = f"server_{i}"
                tdm = TableDataManager("rt_REALTIME")
                mgr = RealtimeSegmentDataManager(
                    TableConfig("rt", TableType.REALTIME), _schema(), sc, 0,
                    tdm, str(tmp_path / inst),
                    on_commit=(lambda name, off, _i=inst:
                               commits[_i].append((name, int(str(off))))),
                    completion_manager=completion, instance_id=inst)
                tdms.append(tdm)
                mgrs.append(mgr)
            for i in range(250):
                topic.publish({"id": i, "score": float(i)})
            for mgr in mgrs:
                mgr.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                if all(len(commits[f"server_{i}"]) >= 2 for i in range(2)):
                    break
                time.sleep(0.1)
            for mgr in mgrs:
                mgr.stop()

            # both replicas checkpointed both segments at the same offsets
            # (a HOLDing winner may consume a few extra rows before its
            # commit, so offsets are >= the flush threshold, not exact)
            assert len(commits["server_0"]) >= 2, commits
            assert len(commits["server_1"]) >= 2, commits
            assert commits["server_0"][:2] == commits["server_1"][:2]
            assert commits["server_0"][0][1] >= 100
            assert commits["server_0"][1][1] >= 200

            # the FSM committed each segment EXACTLY once, with one winner
            for seg_name, _off in commits["server_0"][:2]:
                assert completion.state_of(seg_name) == "COMMITTED"
                fsm = completion._fsms[seg_name]
                assert fsm.committer in ("server_0", "server_1")

            # both replicas answer identically over sealed + consuming rows
            counts = []
            for tdm in tdms:
                sdms = tdm.acquire_segments()
                try:
                    ex = QueryExecutor([s.segment for s in sdms],
                                       use_tpu=False)
                    r = ex.execute("SELECT COUNT(*), SUM(id) FROM rt")
                    counts.append(tuple(r.rows[0]))
                finally:
                    TableDataManager.release_all(sdms)
            assert counts[0] == counts[1]
            assert counts[0][0] == 250
            assert counts[0][1] == pytest.approx(sum(range(250)))
        finally:
            InMemoryStream.delete("fsm_topic")


class TestStaleCommitter:
    """ADVICE r4: a de-elected slow committer must not seal+advance —
    segment_commit_end returns a status and the manager discards stale
    builds, reconciling via KEEP/DISCARD on its next report."""

    def test_commit_end_returns_status(self):
        m = SegmentCompletionManager(num_replicas=1)
        seg = "t__0__0__1"
        assert m.segment_consumed("s0", seg, 100).action == COMMIT
        assert m.segment_commit_end("s0", seg, 100, "/tmp/x") \
            == "COMMIT_SUCCESS"
        # a second (stale) commit attempt is rejected
        assert m.segment_commit_end("s1", seg, 90, "/tmp/y") \
            == "COMMIT_FAILED"

    def test_deelected_committer_gets_failed_then_discard(self):
        m = SegmentCompletionManager(num_replicas=2, hold_deadline_s=0.05)
        seg = "t__0__0__2"
        assert m.segment_consumed("s0", seg, 100).action == HOLD
        assert m.segment_consumed("s1", seg, 100).action == HOLD
        # the tie-broken winner (s0) re-polls and is told to COMMIT
        assert m.segment_consumed("s0", seg, 100).action == COMMIT
        winner, loser = "s0", "s1"
        # winner goes silent past the commit deadline -> re-election
        time.sleep(0.05 * SegmentCompletionManager.COMMIT_TIMEOUT_FACTOR
                   + 0.05)
        r = m.segment_consumed(loser, seg, 100)
        assert r.action == COMMIT
        assert m.segment_commit_end(loser, seg, 100, "/d") \
            == "COMMIT_SUCCESS"
        # the original winner's late commit_end is REJECTED
        assert m.segment_commit_end(winner, seg, 105, "/stale") \
            == "COMMIT_FAILED"
        # and its next report reconciles (offset ahead -> DISCARD)
        r = m.segment_consumed(winner, seg, 105)
        assert r.action == DISCARD
        assert r.download_path == "/d"
