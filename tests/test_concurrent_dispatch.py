"""Concurrent engine dispatch: staged repeat queries from N server
threads overlap on the device instead of serializing behind the engine
lock.

Ref: the reference serves 100k+ QPS through QueryScheduler
(query/scheduler/QueryScheduler.java:134) — VERDICT r3 item 10. The real
win is measured by bench.py's pipelined metric on hardware; this test
pins the concurrency PROPERTY deterministically by substituting a slow
kernel: if dispatch held the engine lock, 8 threads would take ~8x one
dispatch; overlapped they take ~1x.
"""
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment

KERNEL_S = 0.15


@pytest.fixture()
def segs(tmp_path):
    schema = Schema("t", [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC)])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(2)
    out = []
    for i in range(2):
        cols = {"d": rng.integers(0, 10, 1000).astype(np.int32),
                "m": rng.integers(0, 100, 1000).astype(np.int32)}
        p = str(tmp_path / f"s{i}")
        creator.build(cols, p, f"t_{i}")
        out.append(load_segment(p))
    return out


def test_dispatch_overlaps_across_threads(segs, monkeypatch):
    calls = []

    def slow_compiled_kernel(plan):
        def kernel(cols, params, num_docs, D, G=0):
            calls.append(time.perf_counter())
            time.sleep(KERNEL_S)  # a dispatch in flight
            S = num_docs.shape[0]
            return np.zeros((S, 1 + len(plan.agg_ops)), np.float32)
        return kernel

    monkeypatch.setattr(kernels, "compiled_kernel", slow_compiled_kernel)
    eng = TpuOperatorExecutor()
    ctx = QueryContext.from_sql("SELECT SUM(m) FROM t WHERE d < 5")
    # warm the caches so the measured loop is pure dispatch
    eng.execute(segs, ctx)

    t0 = time.perf_counter()
    n = 8
    with ThreadPoolExecutor(n) as pool:
        res = list(pool.map(lambda _: eng.execute(segs, ctx), range(n)))
    wall = time.perf_counter() - t0
    assert all(not rem for _r, rem in res)
    # serialized behind the lock this would be >= n * KERNEL_S (1.2s);
    # overlapped it is ~KERNEL_S plus scheduling slop
    assert wall < n * KERNEL_S / 2, \
        f"8 concurrent dispatches took {wall:.2f}s — serialized?"
    # and they genuinely overlapped: some dispatch STARTED before the
    # previous one could have finished
    starts = sorted(calls[-n:])
    assert starts[1] - starts[0] < KERNEL_S / 2


def test_results_stay_correct_under_concurrency(segs):
    eng = TpuOperatorExecutor()
    ctx = QueryContext.from_sql("SELECT SUM(m), COUNT(*) FROM t WHERE d < 5")
    from pinot_tpu.query import executor_cpu
    want = [executor_cpu.execute_segment(s, ctx) for s in segs]
    want_sum = sum(float(r.intermediates[0]) for r in want)
    want_cnt = sum(int(r.intermediates[1]) for r in want)

    def one(_):
        results, rem = eng.execute(segs, ctx)
        assert not rem
        got_sum = sum(float(r.intermediates[0]) for r in results)
        got_cnt = sum(int(r.intermediates[1]) for r in results)
        assert got_cnt == want_cnt
        assert abs(got_sum - want_sum) <= 1e-3 * max(1.0, abs(want_sum))
        return True

    with ThreadPoolExecutor(8) as pool:
        assert all(pool.map(one, range(32)))
