"""pandas connector: DataFrame write/read paths.

Ref: pinot-connectors (Spark DataSource write -> segments -> push; read
through the broker) — the dataframe-ecosystem bridge.
"""
import numpy as np
import pytest

pd = pytest.importorskip("pandas")

from pinot_tpu.connectors import pandas_connector as pc
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.segment.loader import load_segment


@pytest.fixture()
def frame():
    rng = np.random.default_rng(0)
    return pd.DataFrame({
        "city": rng.choice(["sf", "nyc", "sea"], size=1000),
        "sales": rng.integers(0, 100, size=1000)})


def _schema():
    return Schema("s", [
        FieldSpec("city", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("sales", DataType.INT, FieldType.METRIC)])


class TestPandasConnector:
    def test_write_and_embedded_read(self, frame, tmp_path):
        cfg = TableConfig(name="s")
        dirs = pc.write_dataframe(frame, cfg, _schema(), str(tmp_path),
                                  rows_per_segment=300)
        assert len(dirs) == 4  # 1000 rows / 300
        segs = [load_segment(d) for d in dirs]
        assert sum(s.num_docs for s in segs) == 1000
        out = pc.from_segments(
            segs, "SELECT city, SUM(sales) FROM s GROUP BY city "
                  "ORDER BY city LIMIT 10")
        want = frame.groupby("city")["sales"].sum()
        got = dict(zip(out["city"], out["sum(sales)"]))
        for city, total in want.items():
            assert got[city] == float(total)

    def test_upload_and_broker_read(self, frame, tmp_path):
        from pinot_tpu.controller.cluster_state import (ClusterState,
                                                        InstanceState)
        from pinot_tpu.controller.coordination import (CoordinationClient,
                                                       CoordinationServer)
        state = ClusterState()
        state.register_instance(InstanceState("s0"))
        coord = CoordinationServer(state)
        coord.start()
        client = CoordinationClient(coord.address)
        try:
            cfg = TableConfig(name="s")
            res = pc.upload_dataframe(frame, cfg, _schema(), client,
                                      str(tmp_path), rows_per_segment=500)
            assert len(res) == 2
            assert all(r["segment"]["instances"] == ["s0"] for r in res)
            assert len(state.segments["s_OFFLINE"]) == 2
        finally:
            client.close()
            coord.stop()
