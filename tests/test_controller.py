"""Controller-lite: state, assignment, retention, rebalance, minion tasks."""
import os
import time

import numpy as np
import pytest

from pinot_tpu.controller import ClusterState, Controller, SegmentState
from pinot_tpu.controller.assignment import (
    assign_balanced, assign_replica_groups, target_assignment)
from pinot_tpu.controller.cluster_state import InstanceState
from pinot_tpu.controller.maintenance import (
    rebalance_table, run_retention, segment_status)
from pinot_tpu.controller.tasks import (
    TaskConfig, TaskContext, generate_merge_rollup_tasks, run_task)
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment


def make_schema():
    return Schema("ct", [
        FieldSpec("d", DataType.STRING),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])


def make_config(**kw):
    tc = TableConfig("ct", TableType.OFFLINE)
    tc.retention.time_column = "ts"
    for k, v in kw.items():
        setattr(tc.retention, k, v)
    return tc


def build_seg(tmp, name, n=100, ts_base=0, seed=0):
    rng = np.random.default_rng(seed)
    cols = {"d": [f"k{v}" for v in rng.integers(0, 5, n)],
            "ts": (ts_base + np.arange(n)).astype(np.int64),
            "m": rng.integers(0, 100, n).astype(np.int64)}
    out = str(tmp / name)
    SegmentCreator(make_config(), make_schema()).build(cols, out, name)
    return out


class TestAssignment:
    def _state(self, n_servers=4):
        st = ClusterState()
        for i in range(n_servers):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(), make_schema())
        return st

    def test_balanced_least_loaded(self):
        st = self._state(3)
        st.upsert_segment(SegmentState("s0", "ct_OFFLINE", ["server_0"]))
        st.upsert_segment(SegmentState("s1", "ct_OFFLINE", ["server_1"]))
        out = assign_balanced(st, "ct_OFFLINE", "s2")
        assert out == ["server_2"]

    def test_replication(self):
        st = self._state(3)
        out = assign_balanced(st, "ct_OFFLINE", "s0", replication=2)
        assert len(out) == 2 and len(set(out)) == 2

    def test_replica_groups(self):
        st = self._state(4)
        out = assign_replica_groups(st, "ct_OFFLINE", "s0",
                                    num_replica_groups=2)
        assert len(out) == 2
        # one from each half
        assert out[0] in ("server_0", "server_1")
        assert out[1] in ("server_2", "server_3")

    def test_partition_aware_groups(self):
        st = self._state(4)
        a = assign_replica_groups(st, "ct_OFFLINE", "s0", 2, partition_id=0)
        b = assign_replica_groups(st, "ct_OFFLINE", "s1", 2, partition_id=1)
        assert a != b


class TestRetention:
    def test_expired_segments_removed(self, tmp_path):
        st = ClusterState()
        cfg = make_config(retention_time_value=1, retention_time_unit="DAYS")
        st.add_table(cfg, make_schema())
        now = int(time.time() * 1000)
        old = SegmentState("old", "ct_OFFLINE", [], end_time=now - 2 * 86_400_000)
        new = SegmentState("new", "ct_OFFLINE", [], end_time=now)
        consuming = SegmentState("c", "ct_OFFLINE", [], status="CONSUMING",
                                 end_time=now - 9 * 86_400_000)
        for s in (old, new, consuming):
            st.upsert_segment(s)
        removed = run_retention(st, now_ms=now)
        assert [s.name for s in removed] == ["old"]
        names = {s.name for s in st.table_segments("ct_OFFLINE")}
        assert names == {"new", "c"}


class TestRebalance:
    def test_rebalance_moves_to_target(self):
        st = ClusterState()
        for i in range(2):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(), make_schema())
        # all segments piled on server_0
        for i in range(4):
            st.upsert_segment(SegmentState(f"s{i}", "ct_OFFLINE", ["server_0"]))
        moves = rebalance_table(st, "ct_OFFLINE", dry_run=True)
        assert moves  # imbalance detected
        rebalance_table(st, "ct_OFFLINE")
        loads = {}
        for s in st.table_segments("ct_OFFLINE"):
            for inst in s.instances:
                loads[inst] = loads.get(inst, 0) + 1
        assert loads == {"server_0": 2, "server_1": 2}

    def test_status_checker(self):
        st = ClusterState()
        st.add_table(make_config(), make_schema())
        st.upsert_segment(SegmentState("a", "ct_OFFLINE", ["server_0"]))
        st.upsert_segment(SegmentState("b", "ct_OFFLINE", []))
        out = segment_status(st, "ct_OFFLINE", expected_replication=1)
        assert out == {"numSegments": 2, "segmentsMissingReplicas": 1,
                       "segmentsOffline": 0}


class TestMinionTasks:
    def _ctx(self, tmp_path):
        st = ClusterState()
        st.add_table(make_config(), make_schema())
        return st, TaskContext(st, str(tmp_path / "task_out"))

    def test_merge_rollup_concat(self, tmp_path):
        st, ctx = self._ctx(tmp_path)
        for i in range(3):
            d = build_seg(tmp_path, f"seg_{i}", n=100, ts_base=i * 1000, seed=i)
            m = load_segment(d).metadata
            st.upsert_segment(SegmentState(
                f"seg_{i}", "ct_OFFLINE", [], dir_path=d, num_docs=100,
                start_time=m.start_time, end_time=m.end_time))
        tasks = generate_merge_rollup_tasks(st, "ct_OFFLINE")
        assert len(tasks) == 1 and len(tasks[0].segments) == 3
        out = run_task(tasks[0], ctx)
        assert out["numDocs"] == 300
        segs = st.table_segments("ct_OFFLINE")
        assert len(segs) == 1 and segs[0].num_docs == 300
        merged = load_segment(segs[0].dir_path)
        assert merged.num_docs == 300

    def test_merge_rollup_rollup(self, tmp_path):
        st, ctx = self._ctx(tmp_path)
        cols = {"d": ["a", "a", "b"], "ts": np.array([1, 1, 2], dtype=np.int64),
                "m": np.array([10, 5, 7], dtype=np.int64)}
        d = str(tmp_path / "r0")
        SegmentCreator(make_config(), make_schema()).build(cols, d, "r0")
        st.upsert_segment(SegmentState("r0", "ct_OFFLINE", [], dir_path=d,
                                       num_docs=3))
        out = run_task(TaskConfig("MergeRollupTask", "ct_OFFLINE", ["r0"],
                                  {"mergeType": "ROLLUP"}), ctx)
        merged = load_segment(st.table_segments("ct_OFFLINE")[0].dir_path)
        assert merged.num_docs == 2  # (a,1) rolled up
        from pinot_tpu.query.executor import QueryExecutor
        r = QueryExecutor([merged], use_tpu=False).execute(
            "SELECT d, SUM(m) FROM ct GROUP BY d ORDER BY d LIMIT 10")
        assert r.rows == [("a", 15.0), ("b", 7.0)]

    def test_realtime_to_offline(self, tmp_path):
        st = ClusterState()
        cfg = TableConfig("ct", TableType.REALTIME)
        cfg.retention.time_column = "ts"
        st.add_table(cfg, make_schema())
        ctx = TaskContext(st, str(tmp_path / "task_out"))
        d = build_seg(tmp_path, "rt0", n=50)
        st.upsert_segment(SegmentState("rt0", "ct_REALTIME", [], dir_path=d,
                                       num_docs=50))
        out = run_task(TaskConfig("RealtimeToOfflineSegmentsTask",
                                  "ct_REALTIME", ["rt0"]), ctx)
        assert out["numDocs"] == 50
        assert not st.table_segments("ct_REALTIME")
        assert len(st.table_segments("ct_OFFLINE")) == 1

    def test_purge(self, tmp_path):
        st, ctx = self._ctx(tmp_path)
        d = build_seg(tmp_path, "p0", n=100)
        st.upsert_segment(SegmentState("p0", "ct_OFFLINE", [], dir_path=d,
                                       num_docs=100))
        out = run_task(TaskConfig("PurgeTask", "ct_OFFLINE", ["p0"],
                                  {"purgePredicate": "ts < 50"}), ctx)
        assert out["purgedSegments"] == ["p0_purged"]
        seg = load_segment(st.table_segments("ct_OFFLINE")[0].dir_path)
        assert seg.num_docs == 50

    def test_purge_no_match_still_converges(self, tmp_path):
        """A segment with NO rows matching the predicate still rewrites
        to its _purged name (same data): the suffix is the generator's
        convergence marker, so skipping it would rescan the segment on
        every cadence tick forever."""
        st, ctx = self._ctx(tmp_path)
        d = build_seg(tmp_path, "pn", n=100)
        st.upsert_segment(SegmentState("pn", "ct_OFFLINE", [], dir_path=d,
                                       num_docs=100))
        out = run_task(TaskConfig("PurgeTask", "ct_OFFLINE", ["pn"],
                                  {"purgePredicate": "ts > 100000"}), ctx)
        assert out["purgedSegments"] == ["pn_purged"]
        (state,) = st.table_segments("ct_OFFLINE")
        assert state.name == "pn_purged"
        assert load_segment(state.dir_path).num_docs == 100  # no row lost


class TestControllerFacade:
    def test_upload_assign_load_delete(self, tmp_path):
        ctrl = Controller(task_output_dir=str(tmp_path / "tasks"))
        loads, unloads = [], []
        for i in range(2):
            ctrl.register_server(
                f"server_{i}",
                lambda t, d, i=i: loads.append((i, t, d)),
                lambda t, n, i=i: unloads.append((i, t, n)))
        ctrl.add_table(make_config(), make_schema())
        d = build_seg(tmp_path, "u0", n=40)
        st = ctrl.upload_segment("ct", d)
        assert st.instances and loads
        ctrl.delete_segment("ct_OFFLINE", st.name)
        assert unloads and unloads[0][2] == st.name

    def test_retention_unloads_servers(self, tmp_path):
        ctrl = Controller()
        unloads = []
        ctrl.register_server("server_0", lambda t, d: None,
                             lambda t, n: unloads.append(n))
        cfg = make_config(retention_time_value=1, retention_time_unit="DAYS")
        ctrl.add_table(cfg, make_schema())
        ctrl.state.upsert_segment(SegmentState(
            "ancient", "ct_OFFLINE", ["server_0"],
            end_time=int(time.time() * 1000) - 10 * 86_400_000))
        out = ctrl.run_maintenance_once()
        assert out["retentionRemoved"] == ["ancient"]
        assert unloads == ["ancient"]

    def test_state_persistence_roundtrip(self, tmp_path):
        st = ClusterState(persist_dir=str(tmp_path / "zk"))
        st.add_table(make_config(), make_schema())
        st.upsert_segment(SegmentState("s0", "ct_OFFLINE", ["server_0"],
                                       num_docs=7))
        st2 = ClusterState(persist_dir=str(tmp_path / "zk"))
        assert "ct" in st2.tables
        segs = st2.table_segments("ct_OFFLINE")
        assert len(segs) == 1 and segs[0].num_docs == 7


class TestConcurrentPersist:
    """Regression: _persist snapshotted under the lock but wrote the
    SHARED state.json.tmp outside it — two concurrent persists (two
    servers registering at once, the multiprocess-cluster boot pattern)
    raced the write + os.replace: the loser raised FileNotFoundError
    after the winner renamed the tmp away, and a write landing between
    the winner's open and rename could ship a torn state.json."""

    def test_concurrent_mutations_persist_cleanly(self, tmp_path):
        import json as _json
        import threading

        st = ClusterState(persist_dir=str(tmp_path / "state"))
        errors = []

        def register(n):
            try:
                for i in range(40):
                    st.register_instance(InstanceState(
                        instance_id=f"server_{n}_{i}", host="h",
                        port=1000 + i))
                    st.upsert_segment(SegmentState(
                        name=f"seg_{n}_{i}", table="t_OFFLINE",
                        instances=[f"server_{n}_{i}"], dir_path=""))
            except Exception as e:  # noqa: BLE001 — the regression
                errors.append(e)

        threads = [threading.Thread(target=register, args=(n,))
                   for n in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors

        # the surviving file is whole, parseable, and reloadable
        blob = _json.loads((tmp_path / "state" / "state.json").read_text())
        assert len(blob["segments"]["t_OFFLINE"]) == 4 * 40
        st2 = ClusterState(persist_dir=str(tmp_path / "state"))
        assert len(st2.table_segments("t_OFFLINE")) == 4 * 40
