"""Deep store (PinotFS), upsert snapshots, and restart recovery.

Ref: pinot-spi filesystem/PinotFS.java, SplitSegmentCommitter's
upload-then-commit, pinot-segment-local upsert/ snapshot logic,
PeerDownloadLLCRealtimeClusterIntegrationTest (deep-store recovery) —
VERDICT r4 missing #2 / next-round task 4.
"""
import os

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.fs import (LocalPinotFS, SegmentDeepStore,
                                  download_segment, get_fs, is_store_uri)
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.segment.upsert import (PartitionUpsertMetadataManager,
                                      load_valid_doc_ids,
                                      persist_valid_doc_ids)


def _build_segment(tmp_path, name="s0", n=1000):
    schema = Schema("t", [
        FieldSpec("id", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig(name="t")
    out = str(tmp_path / name)
    SegmentCreator(tc, schema).build(
        {"id": np.arange(n), "v": np.arange(n) * 2}, out, name)
    return out


class TestPinotFS:
    def test_local_fs_roundtrip(self, tmp_path):
        fs = get_fs("file:///tmp")
        assert isinstance(fs, LocalPinotFS)
        src = tmp_path / "a.txt"
        src.write_bytes(b"hello")
        uri = f"file://{tmp_path}/sub/b.txt"
        fs.copy_from_local(str(src), uri)
        assert fs.exists(uri)
        assert fs.length(uri) == 5
        dst = tmp_path / "c.txt"
        fs.copy_to_local(uri, str(dst))
        assert dst.read_bytes() == b"hello"
        assert fs.delete(uri)
        assert not fs.exists(uri)

    def test_unknown_scheme_rejected(self):
        with pytest.raises(ValueError):
            get_fs("s3://bucket/x")


class TestDeepStore:
    def test_upload_download_roundtrip(self, tmp_path):
        seg_dir = _build_segment(tmp_path)
        store = SegmentDeepStore(str(tmp_path / "store"))
        uri = store.upload(seg_dir, "t_OFFLINE", "s0")
        assert is_store_uri(uri)
        assert store.fs.exists(uri)
        local = download_segment(uri, str(tmp_path / "dl"))
        seg = load_segment(local)
        assert seg.num_docs == 1000
        r = QueryExecutor([seg], use_tpu=False).execute(
            "SELECT SUM(v) FROM t")
        assert r.rows[0][0] == float(sum(range(1000)) * 2)

    def test_snapshot_travels_with_segment(self, tmp_path):
        """validDocIds snapshots ride inside the tar: a downloaded copy
        resumes upsert state."""
        seg_dir = _build_segment(tmp_path)
        seg = load_segment(seg_dir)
        mgr = PartitionUpsertMetadataManager(["id"], "v")
        mgr.add_segment(seg)
        seg.valid_doc_ids.clear(5)
        seg.valid_doc_ids.clear(7)
        assert persist_valid_doc_ids(seg)
        store = SegmentDeepStore(str(tmp_path / "store"))
        uri = store.upload(seg_dir, "t_REALTIME", "s0")
        local = download_segment(uri, str(tmp_path / "dl"))
        seg2 = load_segment(local)
        snap = load_valid_doc_ids(seg2)
        assert snap is not None
        assert not snap.contains(5) and not snap.contains(7) and snap.contains(6)

    def test_add_segment_uses_snapshot(self, tmp_path):
        seg_dir = _build_segment(tmp_path, n=100)
        seg = load_segment(seg_dir)
        mgr = PartitionUpsertMetadataManager(["id"], "v")
        mgr.add_segment(seg)
        seg.valid_doc_ids.clear(3)
        persist_valid_doc_ids(seg)
        # fresh manager + fresh load (the restart): snapshot keeps doc 3
        # invalid and registers only valid docs
        seg2 = load_segment(seg_dir)
        mgr2 = PartitionUpsertMetadataManager(["id"], "v")
        mgr2.add_segment(seg2)
        assert not seg2.valid_doc_ids.contains(3)
        assert mgr2.num_primary_keys == 99


class TestRealtimeDeepStore:
    def test_commit_uploads_and_fsm_advertises_store_uri(self, tmp_path):
        from pinot_tpu.controller.completion import SegmentCompletionManager
        from pinot_tpu.ingest import InMemoryStream, StreamConfig
        from pinot_tpu.ingest.realtime_manager import \
            RealtimeSegmentDataManager
        from pinot_tpu.server.data_manager import TableDataManager

        topic = "ds_topic"
        stream = InMemoryStream(topic, num_partitions=1)
        try:
            for i in range(120):
                stream.publish({"id": i, "v": i}, partition=0)
            schema = Schema("rt", [
                FieldSpec("id", DataType.INT, FieldType.DIMENSION),
                FieldSpec("v", DataType.INT, FieldType.METRIC),
            ])
            tc = TableConfig(name="rt", table_type=TableType.REALTIME)
            sc = StreamConfig(topic=topic, flush_threshold_rows=100,
                              flush_threshold_time_ms=3_600_000)
            store = SegmentDeepStore(str(tmp_path / "store"))
            completion = SegmentCompletionManager(num_replicas=1)
            tdm = TableDataManager("rt_REALTIME")
            mgr = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm, str(tmp_path / "segs"),
                completion_manager=completion, instance_id="server_0",
                deep_store=store)
            mgr.start()
            import time
            deadline = time.time() + 30
            while time.time() < deadline:
                segs = completion._fsms
                if any(f.state == "COMMITTED" for f in segs.values()):
                    break
                time.sleep(0.05)
            mgr.stop()
            committed = [(n, f) for n, f in completion._fsms.items()
                         if f.state == "COMMITTED"]
            assert committed, "no segment committed"
            name, fsm = committed[0]
            assert is_store_uri(fsm.download_path), fsm.download_path
            assert store.fs.exists(fsm.download_path)
            # the stored copy is a loadable, queryable segment
            local = download_segment(fsm.download_path,
                                     str(tmp_path / "recover"))
            seg = load_segment(local)
            assert seg.num_docs >= 100
        finally:
            InMemoryStream.delete(topic)
