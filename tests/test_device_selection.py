"""Device offload for selection / order-by / DISTINCT.

Ref: operator/query/SelectionOrderByOperator.java +
MinMaxValueBasedSelectionOrderByCombineOperator (top-K with only winning
docs materialized) and DistinctOperator (dictionary-based distinct) —
VERDICT r3 item 3.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from tests.queries.harness import assert_responses_equal


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("devsel")
    schema = Schema("t", [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("s", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(21)
    out = []
    for i in range(3):
        n = 5000
        cols = {
            "d": rng.integers(0, 20, n).astype(np.int32),
            "s": np.array([f"v{x}" for x in rng.integers(0, 6, n)], object),
            "m": rng.integers(0, 100000, n).astype(np.int32),
        }
        d = str(tmp / f"seg_{i}")
        creator.build(cols, d, f"t_{i}")
        out.append(load_segment(d))
    return out


def _fresh_pair(segs):
    return (QueryExecutor(segs, use_tpu=False),
            QueryExecutor(segs, use_tpu=True, engine=TpuOperatorExecutor()))


def _check(segs, sql, expect_device=True):
    cpu, tpu = _fresh_pair(segs)
    a = cpu.execute(sql)
    b = tpu.execute(sql)
    assert not a.exceptions and not b.exceptions, (a.exceptions, b.exceptions)
    assert_responses_equal(a, b, sql)
    if expect_device:
        assert len(tpu.tpu_engine._block_cache) > 0, \
            f"device path never engaged for {sql!r}"
    return b


class TestSelectionOffload:
    def test_supports_shapes(self, segs):
        eng = TpuOperatorExecutor()
        yes = [
            "SELECT d, m FROM t WHERE d > 5 LIMIT 20",
            "SELECT d FROM t ORDER BY m LIMIT 10",
            "SELECT s, m FROM t WHERE d BETWEEN 2 AND 9 ORDER BY m DESC LIMIT 5",
            "SELECT DISTINCT d FROM t",
            "SELECT DISTINCT d, s FROM t WHERE d < 10",
        ]
        no = [
            "SELECT d FROM t LIMIT 5",                       # host early-exit
            "SELECT d FROM t ORDER BY m, d LIMIT 5",         # 2 sort keys
            "SELECT d FROM t ORDER BY m LIMIT 100000",       # K over cap
            "SELECT DISTINCT d + 1 FROM t",                  # expr distinct
        ]
        for sql in yes:
            assert eng.supports(QueryContext.from_sql(sql)), sql
        for sql in no:
            assert not eng.supports(QueryContext.from_sql(sql)), sql

    def test_order_by_raw_metric(self, segs):
        _check(segs, "SELECT d, m FROM t ORDER BY m DESC LIMIT 7")

    def test_order_by_asc_with_filter(self, segs):
        _check(segs, "SELECT d, m FROM t WHERE d IN (1, 3, 5) "
                     "ORDER BY m LIMIT 9")

    def test_order_by_dict_string_col(self, segs):
        """Sorted dictionary: ORDER BY a string dict column via dictIds."""
        _check(segs, "SELECT s, d FROM t WHERE m > 50000 "
                     "ORDER BY s LIMIT 11")

    def test_order_by_expression(self, segs):
        _check(segs, "SELECT d, m FROM t ORDER BY m * 2 DESC LIMIT 5")

    def test_selection_with_filter_no_order(self, segs):
        cpu, tpu = _fresh_pair(segs)
        sql = "SELECT d FROM t WHERE d = 7 LIMIT 2000"
        a, b = cpu.execute(sql), tpu.execute(sql)
        # unordered selection: compare as multisets
        assert sorted(a.result_table.rows) == sorted(b.result_table.rows)
        assert len(tpu.tpu_engine._block_cache) > 0

    def test_offset(self, segs):
        _check(segs, "SELECT m FROM t ORDER BY m LIMIT 5 OFFSET 3")

    def test_select_star_order_by(self, segs):
        _check(segs, "SELECT * FROM t ORDER BY m DESC LIMIT 4")

    def test_limit_larger_than_matches(self, segs):
        _check(segs, "SELECT d, m FROM t WHERE d = 3 AND m < 2000 "
                     "ORDER BY m LIMIT 500")


class TestTopnSentinel:
    def test_matched_rows_never_lose_to_sentinel(self, tmp_path):
        """Matched docs whose score clamps to -inf territory (huge values
        under ASC negation) must still outrank unmatched docs."""
        schema = Schema("t", [
            FieldSpec("d", DataType.INT, FieldType.DIMENSION),
            FieldSpec("x", DataType.DOUBLE, FieldType.METRIC)])
        tc = TableConfig("t", TableType.OFFLINE)
        tc.indexing.no_dictionary_columns = ["x"]
        creator = SegmentCreator(tc, schema)
        x = np.full(1000, 1.0)
        dd = np.zeros(1000, np.int32)
        x[::100] = 1e300  # f32-staging overflows; ASC score becomes -inf
        dd[::100] = 1     # filter selects exactly the overflow rows
        cols = {"d": dd, "x": x}
        d = str(tmp_path / "seg")
        creator.build(cols, d, "t_0")
        seg = load_segment(d)
        cpu = QueryExecutor([seg], use_tpu=False)
        tpu = QueryExecutor([seg], use_tpu=True,
                            engine=TpuOperatorExecutor())
        sql = "SELECT d FROM t WHERE d = 1 ORDER BY x LIMIT 20"
        a, b = cpu.execute(sql), tpu.execute(sql)
        assert len(b.result_table.rows) == len(a.result_table.rows) == 10
        assert len(tpu.tpu_engine._block_cache) > 0


class TestDistinctOffload:
    def test_distinct_single(self, segs):
        _check(segs, "SELECT DISTINCT d FROM t ORDER BY d LIMIT 100")

    def test_distinct_multi(self, segs):
        _check(segs, "SELECT DISTINCT d, s FROM t ORDER BY d, s LIMIT 500")

    def test_distinct_filtered(self, segs):
        _check(segs, "SELECT DISTINCT s FROM t WHERE d BETWEEN 5 AND 8 "
                     "ORDER BY s LIMIT 100")

    def test_distinct_empty(self, segs):
        # min/max pruning drops every segment before the engine sees them
        _check(segs, "SELECT DISTINCT d FROM t WHERE d > 1000",
               expect_device=False)

    def test_distinct_empty_match_on_device(self, segs):
        # unprunable empty result (IN set within min/max range)
        _check(segs, "SELECT DISTINCT s FROM t WHERE d IN (0, 19) "
                     "AND m < 0 ORDER BY s LIMIT 10", expect_device=False)
