"""Device-path sketch aggregations: HLL registers + histogram TDigest.

Ref: pinot-core query/aggregation/function/DistinctCountHLLAggregationFunction,
PercentileTDigestAggregationFunction — VERDICT r4 item 1 (BASELINE config #4
ran host-side python at 55k rows/s). The device kernel hashes i32 split
planes into HLL register max-scatters and scatter-adds histogram partials;
HLL registers must be BIT-IDENTICAL to the host sketch so partials merge.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.query.aggregation.sketches import HyperLogLog, TDigest
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment


@pytest.fixture(scope="module")
def segments(tmp_path_factory):
    rng = np.random.default_rng(7)
    tmp = tmp_path_factory.mktemp("sketch_segs")
    schema = Schema("taxi", [
        FieldSpec("trip_id", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("vendor", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("fare", DataType.FLOAT, FieldType.METRIC),
    ])
    tc = TableConfig(name="taxi")
    segs = []
    for i in range(3):
        n = 50_000
        cols = {
            "trip_id": rng.integers(0, 1 << 40, size=n),
            "vendor": rng.choice(["a", "b", "c"], size=n),
            "fare": rng.gamma(2.0, 10.0, size=n).astype(np.float32),
        }
        out = str(tmp / f"s{i}")
        SegmentCreator(tc, schema).build(cols, out, f"s{i}")
        segs.append(load_segment(out))
    return segs


@pytest.fixture(scope="module")
def executors(segments):
    return (QueryExecutor(segments, use_tpu=False),
            QueryExecutor(segments, use_tpu=True))


class TestDeviceHll:
    def test_registers_bit_identical(self, executors):
        host, dev = executors
        sql = "SELECT DISTINCTCOUNTHLL(trip_id) FROM taxi"
        rh = host.execute(sql)
        rd = dev.execute(sql)
        assert rh.rows == rd.rows  # same registers -> same estimate
        assert len(dev._tpu_engine._block_cache) > 0, "device not engaged"

    def test_estimate_accuracy(self, executors, segments):
        _host, dev = executors
        true = len(np.unique(np.concatenate(
            [s.data_source("trip_id").values() for s in segments])))
        est = dev.execute(
            "SELECT DISTINCTCOUNTHLL(trip_id) FROM taxi").rows[0][0]
        assert abs(est - true) / true < 0.05

    def test_with_filter(self, executors):
        host, dev = executors
        sql = "SELECT DISTINCTCOUNTHLL(trip_id) FROM taxi WHERE fare > 25"
        assert host.execute(sql).rows == dev.execute(sql).rows

    def test_grouped_hll_falls_back_to_host(self, executors, segments):
        host, dev = executors
        sql = ("SELECT vendor, DISTINCTCOUNTHLL(trip_id) FROM taxi "
               "GROUP BY vendor")
        assert not dev.tpu_engine.supports(_ctx(sql))
        # and the full path still answers correctly via host fallback
        assert sorted(host.execute(sql).rows) == sorted(dev.execute(sql).rows)

    def test_hll_plus_scalar_aggs_one_kernel(self, executors):
        host, dev = executors
        sql = ("SELECT DISTINCTCOUNTHLL(trip_id), COUNT(*), SUM(fare) "
               "FROM taxi")
        rh, rd = host.execute(sql), dev.execute(sql)
        assert rh.rows[0][0] == rd.rows[0][0]
        assert rh.rows[0][1] == rd.rows[0][1]
        assert rd.rows[0][2] == pytest.approx(rh.rows[0][2], rel=2e-3)


class TestDeviceTDigest:
    def test_close_to_exact(self, executors, segments):
        _host, dev = executors
        fares = np.concatenate(
            [s.data_source("fare").values() for s in segments])
        exact = np.quantile(fares, 0.95)
        est = dev.execute(
            "SELECT PERCENTILETDIGEST95(fare) FROM taxi").rows[0][0]
        # error bound: digest error + one histogram bucket width
        width = (fares.max() - fares.min()) / 8192
        assert abs(est - exact) < max(0.02 * exact, 5 * width)

    def test_with_filter(self, executors, segments):
        _host, dev = executors
        fares = np.concatenate(
            [s.data_source("fare").values() for s in segments])
        exact = np.quantile(fares[fares > 10], 0.5)
        est = dev.execute(
            "SELECT PERCENTILETDIGEST(fare, 50) FROM taxi "
            "WHERE fare > 10").rows[0][0]
        assert abs(est - exact) < max(0.03 * exact, 1.0)


class TestHashParity:
    def test_device_and_host_hash_agree(self):
        """The jnp uint32 hash must match sketches.hash32_pair exactly."""
        import jax.numpy as jnp
        from pinot_tpu.ops.kernels import _fmix32 as jfmix
        from pinot_tpu.query.aggregation.sketches import (_split_planes,
                                                          hash32_pair)
        rng = np.random.default_rng(3)
        vals = rng.integers(-(1 << 50), 1 << 50, size=10_000)
        hi, lo = _split_planes(vals)
        h1, h2 = hash32_pair(hi, lo)
        jhi = jnp.asarray(hi.astype(np.int32)).astype(jnp.uint32)
        jlo = jnp.asarray(lo.astype(np.int32)).astype(jnp.uint32)
        jh1 = jfmix(jfmix(jlo ^ jnp.uint32(0x9E3779B9)) ^ jhi)
        jh2 = jfmix(jfmix(jhi ^ jnp.uint32(0x85EBCA77)) ^ jlo)
        np.testing.assert_array_equal(np.asarray(jh1), h1)
        np.testing.assert_array_equal(np.asarray(jh2), h2)


def _ctx(sql: str):
    from pinot_tpu.query.context import QueryContext
    return QueryContext.from_sql(sql)


class TestExactIntSums:
    """Bit-exact device SUM for int columns (VERDICT r4 weak #2): the
    'isum' slot accumulates 6-bit planes in i32 (ops/kernels.py
    _isum_slot; ref SumAggregationFunction's exact doubles)."""

    @pytest.fixture(scope="class")
    def int_segments(self, tmp_path_factory):
        rng = np.random.default_rng(11)
        tmp = tmp_path_factory.mktemp("isum_segs")
        schema = Schema("it", [
            FieldSpec("v", DataType.INT, FieldType.METRIC),
            FieldSpec("neg", DataType.INT, FieldType.METRIC),
        ])
        tc = TableConfig(name="it")
        tc.indexing.no_dictionary_columns = ["v", "neg"]
        segs, arrays = [], []
        for i in range(2):
            n = 300_000
            cols = {
                "v": rng.integers(0, 1 << 24, size=n, dtype=np.int64),
                "neg": rng.integers(-(1 << 24), 1 << 24, size=n,
                                    dtype=np.int64),
            }
            out = str(tmp / f"s{i}")
            SegmentCreator(tc, schema).build(cols, out, f"s{i}")
            segs.append(load_segment(out))
            arrays.append(cols)
        return segs, arrays

    def test_sum_bit_exact(self, int_segments):
        segs, arrays = int_segments
        host = QueryExecutor(segs, use_tpu=False)
        dev = QueryExecutor(segs, use_tpu=True)
        exact = sum(int(a["v"].sum()) for a in arrays)
        rh = host.execute("SELECT SUM(v) FROM it").rows[0][0]
        rd = dev.execute("SELECT SUM(v) FROM it").rows[0][0]
        assert float(rd) == float(rh) == float(exact)
        assert len(dev.tpu_engine._block_cache) > 0

    def test_negative_and_filtered(self, int_segments):
        segs, arrays = int_segments
        host = QueryExecutor(segs, use_tpu=False)
        dev = QueryExecutor(segs, use_tpu=True)
        sql = "SELECT SUM(neg), AVG(v) FROM it WHERE v > 1000"
        rh = host.execute(sql).rows[0]
        rd = dev.execute(sql).rows[0]
        assert float(rd[0]) == float(rh[0])
        assert float(rd[1]) == pytest.approx(float(rh[1]), rel=1e-12)


class TestHllFilterOnSameColumn:
    """Review finding: HLL forces its no-dict int column into split-plane
    staging, so a filter on the SAME column must use vrange64 (not the
    'val:' block that won't exist)."""

    def test_hll_with_filter_on_hll_column(self, tmp_path):
        rng = np.random.default_rng(13)
        schema = Schema("h", [
            FieldSpec("x", DataType.LONG, FieldType.DIMENSION),
        ])
        tc = TableConfig(name="h")
        tc.indexing.no_dictionary_columns = ["x"]
        n = 50_000
        xs = rng.integers(0, 1 << 20, size=n, dtype=np.int64)
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build({"x": xs}, out, "s0")
        seg = load_segment(out)
        host = QueryExecutor([seg], use_tpu=False)
        dev = QueryExecutor([seg], use_tpu=True)
        sql = "SELECT DISTINCTCOUNTHLL(x) FROM h WHERE x > 5000"
        assert host.execute(sql).rows == dev.execute(sql).rows
        assert len(dev.tpu_engine._block_cache) > 0

    def test_huge_longs_fall_back_and_stay_distinct(self, tmp_path):
        # |v| >= 2^55: device path must decline, and the HOST fold must
        # keep values differing only in the top byte distinct
        schema = Schema("h2", [
            FieldSpec("x", DataType.LONG, FieldType.DIMENSION),
        ])
        tc = TableConfig(name="h2")
        tc.indexing.no_dictionary_columns = ["x"]
        xs = np.array([k << 55 for k in range(1, 100)], dtype=np.int64)
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build({"x": xs}, out, "s0")
        seg = load_segment(out)
        dev = QueryExecutor([seg], use_tpu=True)
        est = dev.execute("SELECT DISTINCTCOUNTHLL(x) FROM h2").rows[0][0]
        assert abs(est - 99) / 99 < 0.1
