"""Concurrent-query dispatch pipeline (ops/dispatch.py).

Pins the tentpole properties deterministically:
  * shared-plan micro-batching — fingerprint-equal concurrent queries
    coalesce into ONE vmapped launch and split back per caller,
    BIT-IDENTICAL to per-query execution (property-tested over random
    literal sets)
  * cancel/deadline discipline — a cancelled query leaves its batch
    before launch; a deadline that expires while queued surfaces as
    BrokerTimeoutError without executing
  * retrace guard — steady-state traffic over warmed (plan, batch-size
    bucket) shapes compiles NOTHING new (kernels.trace_count is the
    compile odometer; a regression here re-compiles the hot path per
    query and tanks serving latency)
  * seeded chaos — the server.dispatch.before failpoint replays exactly

Determinism trick: a one-shot delay failpoint on server.dispatch.before
holds the ring on the FIRST pop while the remaining threads enqueue, so
the batch composition is exact rather than a scheduling race.
"""
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import dispatch, kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.accounting import (BrokerTimeoutError,
                                        QueryCancelledError,
                                        ResourceAccountant)
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import FailpointError, failpoints

HOLD_S = 0.25  # ring-hold long enough for peers to stage + enqueue


@pytest.fixture()
def segs(tmp_path):
    schema = Schema("t", [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC)])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(11)
    out = []
    for i in range(3):
        cols = {"d": rng.integers(0, 10, 4000).astype(np.int32),
                "m": rng.integers(0, 100, 4000).astype(np.int32)}
        p = str(tmp_path / f"s{i}")
        creator.build(cols, p, f"t_{i}")
        out.append(load_segment(p))
    return out


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def make_engine(**overrides):
    return TpuOperatorExecutor(config=PinotConfiguration(overrides=overrides))


def agg_values(results):
    """Comparable value tuple per segment result (exact: int sums/counts
    stay integral in f64, so equality is bit-meaningful)."""
    out = []
    for r in results:
        if hasattr(r, "groups"):
            out.append(tuple(sorted(
                (k, tuple(float(v) for v in inters))
                for k, inters in r.groups.items())))
        else:
            out.append(tuple(float(v) for v in r.intermediates))
    return tuple(out)


def run_concurrent(eng, segs, ctxs, hold=HOLD_S):
    """Run ctxs concurrently with the ring held on the first pop, so all
    of them are enqueued before coalescing — deterministic batching.
    times=2: the first delay may be consumed by a racing thread's
    lone-query fast path (inline dispatch); the second then holds the
    ring leader while the rest enqueue."""
    failpoints.arm("server.dispatch.before", delay=hold, times=2)
    try:
        with ThreadPoolExecutor(len(ctxs)) as pool:
            futs = [pool.submit(eng.execute, segs, c) for c in ctxs]
            return [f.result() for f in futs]
    finally:
        failpoints.disarm("server.dispatch.before")


class TestMicroBatching:
    def test_coalesce_and_split_matches_per_query(self, segs):
        eng = make_engine()
        ctxs = [QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*), MIN(m) FROM t WHERE d < {k}")
            for k in range(1, 7)]
        singles = [agg_values(eng.execute(segs, c)[0]) for c in ctxs]
        reg = eng._dispatcher._metrics
        max0 = reg.timer("dispatch_batch_size").max_ms
        got = run_concurrent(eng, segs, ctxs)
        assert all(not rem for _r, rem in got)
        assert [agg_values(r) for r, _rem in got] == singles
        # batching actually happened (not six serialized singles)
        assert reg.timer("dispatch_batch_size").max_ms >= max(max0, 2)

    def test_group_by_batched_matches_per_query(self, segs):
        eng = make_engine()
        ctxs = [QueryContext.from_sql(
            f"SELECT d, SUM(m) FROM t WHERE m BETWEEN {a} AND {a + 40} "
            f"GROUP BY d") for a in (0, 10, 20, 30)]
        singles = [agg_values(eng.execute(segs, c)[0]) for c in ctxs]
        got = run_concurrent(eng, segs, ctxs)
        assert [agg_values(r) for r, _rem in got] == singles

    def test_bit_identical_property_over_random_literal_sets(self, segs):
        """Property: for ANY plan-fingerprint-equal query set, batched
        execution is bit-identical to per-query execution."""
        eng = make_engine()
        rng = np.random.default_rng(23)
        for _trial in range(4):
            k = int(rng.integers(2, 9))
            bounds = rng.integers(0, 100, size=(k, 2))
            ctxs = [QueryContext.from_sql(
                "SELECT SUM(m), COUNT(*), MAX(m) FROM t "
                f"WHERE m BETWEEN {min(a, b)} AND {max(a, b)} AND d < 8")
                for a, b in bounds]
            singles = [agg_values(eng.execute(segs, c)[0]) for c in ctxs]
            got = run_concurrent(eng, segs, ctxs)
            assert [agg_values(r) for r, _rem in got] == singles

    def test_serialized_mode_matches_pipelined(self, segs):
        """The A/B baseline mode (pre-ring inline dispatch) must stay
        result-identical — it's both the bench baseline and the escape
        hatch."""
        pipe = make_engine()
        ser = make_engine(**{"pinot.server.dispatch.mode": "serialized"})
        for sql in ("SELECT SUM(m), COUNT(*) FROM t WHERE d < 5",
                    "SELECT d, COUNT(*) FROM t GROUP BY d"):
            ctx = QueryContext.from_sql(sql)
            a, _ = pipe.execute(segs, ctx)
            b, _ = ser.execute(segs, ctx)
            assert agg_values(a) == agg_values(b)


class TestCancelAndDeadline:
    def test_cancelled_query_leaves_batch_before_launch(self, segs):
        eng = make_engine()
        ctxs = [QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM t WHERE d < {k}")
            for k in range(1, 5)]
        singles = [agg_values(eng.execute(segs, c)[0]) for c in ctxs]

        def cancelled():
            raise QueryCancelledError("cancelled by test")

        failpoints.arm("server.dispatch.before", delay=HOLD_S, times=2)
        try:
            with ThreadPoolExecutor(4) as pool:
                futs = [pool.submit(eng.execute, segs, c,
                                    cancelled if i == 1 else None)
                        for i, c in enumerate(ctxs)]
                with pytest.raises(QueryCancelledError):
                    futs[1].result()
                # survivors split correctly without the cancelled member
                for i in (0, 2, 3):
                    res, rem = futs[i].result()
                    assert not rem
                    assert agg_values(res) == singles[i]
        finally:
            failpoints.disarm("server.dispatch.before")

    def test_deadline_honored_while_queued(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql("SELECT SUM(m) FROM t WHERE d < 5")
        eng.execute(segs, ctx)  # warm (staging off the timed path)
        acc = ResourceAccountant()
        acc.begin_query("q-dl", timeout_s=0.02)
        # hold the ring so the query sits QUEUED past its whole budget
        failpoints.arm("server.dispatch.before", delay=0.2, times=1)
        try:
            with ThreadPoolExecutor(2) as pool:
                blocker = pool.submit(eng.execute, segs, ctx)
                time.sleep(0.05)  # ring now busy; budget now expired
                with pytest.raises(BrokerTimeoutError):
                    eng.execute(segs, ctx, acc.checker("q-dl"))
                blocker.result()
        finally:
            failpoints.disarm("server.dispatch.before")
            acc.finish_query("q-dl")


class TestRetraceGuard:
    def test_steady_state_zero_retraces_and_zero_column_bytes(self, segs):
        """CI guard (ISSUE 6): a repeated-query steady state — singles
        AND coalesced batches over warmed shapes — must neither compile
        (compile odometer) nor ship ONE column byte host->device
        (transfer odometer): columns are resident, blocks are assembled
        and cached, params are plan-keyed. Either regression silently
        re-pays the ~100ms link or a recompile per query in production."""
        from pinot_tpu.ops import residency
        eng = make_engine()
        ctxs = [QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*), MIN(m) FROM t WHERE d < {k}")
            for k in range(1, 9)]
        for c in ctxs:
            eng.execute(segs, c)      # warm singles (stage + compile)
        run_concurrent(eng, segs, ctxs)   # warm the batched bucket
        t0 = kernels.trace_count()
        b0 = residency.transfer_bytes()
        for c in ctxs:
            eng.execute(segs, c)
        run_concurrent(eng, segs, ctxs)
        assert kernels.trace_count() == t0, \
            "steady-state traffic re-compiled a kernel"
        assert residency.transfer_bytes() == b0, \
            "steady-state traffic uploaded host->device bytes"

    def test_steady_state_zero_retrace(self, segs):
        """CI guard: warmed (plan, shape, batch-size bucket) traffic must
        not compile ANYTHING — a compile-cache miss here re-traces the
        hot path per query in production."""
        eng = make_engine()

        def round_of(base):
            ctxs = [QueryContext.from_sql(
                f"SELECT SUM(m), COUNT(*) FROM t WHERE d < {base + k}")
                for k in range(8)]
            got = run_concurrent(eng, segs, ctxs)
            assert all(not rem for _r, rem in got)

        ctx0 = QueryContext.from_sql("SELECT SUM(m), COUNT(*) FROM t "
                                     "WHERE d < 1")
        eng.execute(segs, ctx0)      # warm the single-kernel shape
        round_of(0)                  # warm the bucket-8 batched shape
        before = kernels.trace_count()
        meter0 = eng._dispatcher._metrics.meter("kernel_retrace")
        round_of(1)                  # same shapes, fresh literals
        round_of(2)
        eng.execute(segs, ctx0)
        assert kernels.trace_count() == before, \
            "steady-state traffic re-compiled a kernel"
        assert eng._dispatcher._metrics.meter("kernel_retrace") == meter0


class TestDispatchChaos:
    def test_seeded_chaos_replays_exactly(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql("SELECT SUM(m), COUNT(*) FROM t "
                                    "WHERE d < 4")
        eng.execute(segs, ctx)  # warm: compiles happen outside the chaos

        def run_round():
            fp = failpoints.arm("server.dispatch.before",
                                error=FailpointError("dispatch chaos"),
                                probability=0.5, seed=1234)
            outcomes = []
            try:
                for _ in range(10):
                    try:
                        res, rem = eng.execute(segs, ctx)
                        assert not rem
                        outcomes.append("ok")
                    except FailpointError:
                        outcomes.append("chaos")
            finally:
                failpoints.disarm("server.dispatch.before")
            return outcomes, list(fp.decisions)

        o1, d1 = run_round()
        o2, d2 = run_round()
        assert o1 == o2 and d1 == d2  # same seed -> exact replay
        assert "chaos" in o1 and "ok" in o1  # both paths exercised

    def test_dispatch_error_fails_only_that_query(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql("SELECT COUNT(*) FROM t WHERE d < 3")
        eng.execute(segs, ctx)
        failpoints.arm("server.dispatch.before",
                       error=FailpointError("one-shot"), times=1)
        try:
            with pytest.raises(FailpointError):
                eng.execute(segs, ctx)
        finally:
            failpoints.disarm("server.dispatch.before")
        res, rem = eng.execute(segs, ctx)  # ring fully recovered
        assert not rem and res


class TestZeroCopySplit:
    def test_split_packed_returns_views(self):
        """ROADMAP item: per-member splits of a batched fetch are VIEWS
        into the one packed array, never host-side copies."""
        from pinot_tpu.ops import dispatch
        arr = np.arange(24.0).reshape(4, 6)
        members = dispatch.split_packed(arr, 3)
        assert len(members) == 3
        for i, m in enumerate(members):
            assert m.base is not None and np.shares_memory(m, arr)
            assert np.array_equal(m, arr[i])

    def test_batched_fetch_split_is_zero_copy_end_to_end(self, segs):
        """Through the REAL coalesced path: spy on split_packed and
        assert every member handed to a caller future shares memory with
        the packed fetch (and results stay correct)."""
        from pinot_tpu.ops import dispatch
        eng = make_engine()
        ctxs = [QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM t WHERE d < {k}")
            for k in range(1, 6)]
        singles = [agg_values(eng.execute(segs, c)[0]) for c in ctxs]
        calls = []
        orig = dispatch.split_packed

        def spy(arr, n):
            members = orig(arr, n)
            calls.append((arr, members))
            return members

        dispatch.split_packed = spy
        try:
            got = run_concurrent(eng, segs, ctxs)
        finally:
            dispatch.split_packed = orig
        assert [agg_values(r) for r, _rem in got] == singles
        assert calls, "no batch formed — the spy never fired"
        for arr, members in calls:
            for m in members:
                assert m.base is not None and np.shares_memory(m, arr)


class TestPipelineMetrics:
    def test_dispatch_metrics_populated(self, segs):
        eng = make_engine()
        reg = eng._dispatcher._metrics
        c0 = reg.timer("dispatch_batch_size").count
        ctxs = [QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM t WHERE d < {k}")
            for k in range(1, 5)]
        for c in ctxs:
            eng.execute(segs, c)
        run_concurrent(eng, segs, ctxs)
        t = reg.timer("dispatch_batch_size")
        assert t.count > c0
        assert t.max_ms >= 2  # a real batch formed
        assert reg.gauge("dispatch_queue_depth") is not None
        assert reg.meter("kernel_retrace") > 0  # compiles were metered

    def test_execute_async_overlaps_caller(self, segs):
        """execute_async returns before the device result lands, so the
        caller can run host-path work in parallel."""
        eng = make_engine()
        ctx = QueryContext.from_sql("SELECT SUM(m), COUNT(*) FROM t "
                                    "WHERE d < 6")
        want = agg_values(eng.execute(segs, ctx)[0])
        failpoints.arm("server.dispatch.before", delay=0.2, times=1)
        try:
            t0 = time.perf_counter()
            fut = eng.execute_async(segs, ctx)
            submitted_in = time.perf_counter() - t0
            res, rem = fut.result(timeout=10)
        finally:
            failpoints.disarm("server.dispatch.before")
        assert submitted_in < 0.15, "execute_async blocked the caller"
        assert not rem and agg_values(res) == want


class TestWaitResult:
    """Deadline-bounded future waits (dispatch.wait_result) — the fix
    idiom the hang-risk lint demands at every dispatcher wait."""

    def test_returns_value(self):
        from concurrent.futures import Future
        f = Future()
        f.set_result(41)
        assert dispatch.wait_result(f) == 41

    def test_completion_in_poll_expiry_race_window_returns_value(self):
        """Regression: a future that completes AFTER the 0.25s poll's
        result() raised but BEFORE the done() check must yield its
        value, not a spurious TimeoutError. The original code re-raised
        the poll's own timeout whenever done() was True — under
        sustained load (4 polls/sec per in-flight launch) that window
        failed healthy queries with 'timeout' while the packed result
        sat in the future."""
        from concurrent.futures import Future

        class RacyFuture(Future):
            """Simulates the race: the first result(timeout=) call
            raises the poll timeout, then the value lands."""
            def __init__(self):
                super().__init__()
                self._polled = False

            def result(self, timeout=None):
                if not self._polled:
                    self._polled = True
                    self.set_result(17)     # lands DURING the poll
                    raise TimeoutError()    # ...which already expired
                return super().result(timeout)

        assert dispatch.wait_result(RacyFuture(), poll_s=0.01) == 17

    def test_work_raised_timeout_propagates(self):
        """A TimeoutError raised BY the work is the query's own deadline
        tripping — it must propagate as-is, not spin the poll loop."""
        from concurrent.futures import Future
        f = Future()
        f.set_exception(TimeoutError("work deadline"))
        with pytest.raises(TimeoutError, match="work deadline"):
            dispatch.wait_result(f, poll_s=0.01)

    def test_cancel_check_runs_each_poll(self):
        from concurrent.futures import Future
        calls = []

        def checker():
            calls.append(1)
            if len(calls) >= 3:
                raise RuntimeError("query cancelled")

        with pytest.raises(RuntimeError, match="query cancelled"):
            dispatch.wait_result(Future(), cancel_check=checker, poll_s=0.005)
        assert len(calls) == 3

    def test_hard_cap_bounds_budgetless_wait(self):
        from concurrent.futures import Future
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError, match="dispatcher wedged"):
            dispatch.wait_result(Future(), max_wait_s=0.05, poll_s=0.01)
        assert time.perf_counter() - t0 < 2.0
