"""f32-staging parity: strict raw-value comparisons with x64 DISABLED.

The production TPU default is jax_enable_x64=False, where raw columns stage
as float32. ADVICE r1 (high): _vrange_bounds computed the open-interval
bound with float64 nextafter, which collapses back to the literal when cast
to float32 — 'x > 5' executed as 'x >= 5'. These tests pin the fix by
running the device path under jax.enable_x64(False).
"""
import numpy as np
import pytest

import jax

if not hasattr(jax, "enable_x64"):
    # older jax: the context manager lives in jax.experimental
    from jax.experimental import enable_x64 as _enable_x64
    jax.enable_x64 = _enable_x64

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor

from tests.queries.harness import assert_responses_equal, build_segments


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("f32seg")
    schema = Schema("testTable", [
        FieldSpec("rawInt", DataType.INT, FieldType.METRIC),
        FieldSpec("rawFloat", DataType.FLOAT, FieldType.METRIC),
        FieldSpec("dimCol", DataType.INT, FieldType.DIMENSION),
    ])
    tc = TableConfig("testTable", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["rawInt", "rawFloat"]
    rng = np.random.default_rng(7)
    n = 4096
    cols = {
        # plant many exact boundary hits so strict-vs-nonstrict differs
        "rawInt": np.where(rng.random(n) < 0.3, 5,
                           rng.integers(-50, 50, n)).astype(np.int32),
        "rawFloat": np.where(rng.random(n) < 0.3, np.float32(2.5),
                             rng.random(n).astype(np.float32) * 10),
        "dimCol": rng.integers(0, 100, n).astype(np.int32),
    }
    return build_segments(tmp, schema, tc, [cols])


STRICT_QUERIES = [
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawInt > 5",
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawInt < 5",
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawInt >= 5",
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawInt <= 5",
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawFloat > 2.5",
    "SELECT COUNT(*), SUM(dimCol) FROM testTable WHERE rawFloat < 2.5",
    "SELECT COUNT(*) FROM testTable WHERE rawFloat > 2.5 AND rawInt > 5",
]


@pytest.mark.parametrize("sql", STRICT_QUERIES)
def test_strict_bounds_f32(segs, sql):
    with jax.enable_x64(False):
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True)
        a, b = cpu.execute(sql), tpu.execute(sql)
        # the device path must actually have run (not fallen back) for this
        # to pin the f32 bound computation; parity alone suffices either way
        assert_responses_equal(a, b, sql)


def test_strict_gt_excludes_boundary(segs):
    """x > 5 must exclude the planted exact-5 rows under f32 staging."""
    with jax.enable_x64(False):
        tpu = QueryExecutor(segs, use_tpu=True)
        gt = tpu.execute("SELECT COUNT(*) FROM testTable WHERE rawInt > 5")
        ge = tpu.execute("SELECT COUNT(*) FROM testTable WHERE rawInt >= 5")
        n_gt = gt.result_table.rows[0][0]
        n_ge = ge.result_table.rows[0][0]
        # ~30% of 4096 rows are exactly 5
        assert n_ge - n_gt > 1000, (n_gt, n_ge)
