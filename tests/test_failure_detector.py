"""Broker failure detection + replica failover.

Ref: pinot-broker failuredetector/ConnectionFailureDetector.java and the
adaptive retry in core/transport/QueryRouter — VERDICT r3 item 9: kill a
server, queries keep answering from the surviving replica.
"""
import time

import numpy as np
import pytest

from pinot_tpu.broker.failure_detector import ConnectionFailureDetector
from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.models.schema import Schema
from pinot_tpu.models.table_config import TableConfig
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment


class TestDetectorUnit:
    def test_backoff_doubles(self):
        d = ConnectionFailureDetector(base_backoff_s=1.0, max_backoff_s=8.0)
        t0 = time.time()
        d.mark_failure("s1")
        assert not d.is_healthy("s1", now=t0 + 0.5)
        assert d.is_healthy("s1", now=t0 + 1.1)  # backoff expired: probe
        d.mark_failure("s1")
        assert not d.is_healthy("s1", now=time.time() + 1.5)
        assert d.is_healthy("s1", now=time.time() + 2.1)
        for _ in range(10):
            d.mark_failure("s1")
        # capped at max_backoff
        assert d.is_healthy("s1", now=time.time() + 8.1)

    def test_success_clears(self):
        d = ConnectionFailureDetector()
        d.mark_failure("s1")
        d.mark_failure("s1")
        d.mark_success("s1")
        assert d.is_healthy("s1")
        assert d.failure_count("s1") == 0
        assert d.unhealthy_servers() == set()

    def test_unhealthy_set(self):
        d = ConnectionFailureDetector(base_backoff_s=30.0)
        d.mark_failure("a")
        d.mark_failure("b")
        assert d.unhealthy_servers() == {"a", "b"}


@pytest.fixture()
def replicated_cluster(tmp_path):
    schema = Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})
    tc = TableConfig.from_dict({"tableName": "t", "tableType": "OFFLINE"})
    creator = SegmentCreator(tc, schema)
    c = MiniCluster(num_servers=2)
    c.start()
    c.add_table("t")
    rng = np.random.default_rng(3)
    total = 0
    for i in range(4):
        n = 1000
        cols = {"d": rng.integers(0, 10, n).astype(np.int64),
                "m": rng.integers(0, 100, n).astype(np.int64)}
        total += int(cols["m"].sum())
        d = str(tmp_path / f"seg_{i}")
        creator.build(cols, d, f"t_{i}")
        # every segment on BOTH servers (replica group of 2)
        c.add_segment("t", load_segment(d), server_idx=i % 2,
                      replicas=[(i + 1) % 2])
    yield c, total
    c.stop()


class TestFailover:
    def test_kill_server_keeps_answering(self, replicated_cluster):
        c, total = replicated_cluster
        r = c.query("SELECT COUNT(*), SUM(m) FROM t")
        assert not r.exceptions
        assert r.result_table.rows[0] == (4000, total)

        # kill server_1 (transport down, broker connection now refused)
        c.servers[1].transport.stop()
        c._connections["server_1"].close()

        # the SAME query keeps answering, complete, via the replica
        # (first query pays the failure + one retry round)
        r = c.query("SELECT COUNT(*), SUM(m) FROM t")
        assert not r.exceptions, r.exceptions
        assert r.result_table.rows[0] == (4000, total)
        fd = c.broker.failure_detector
        assert "server_1" in fd.unhealthy_servers()

        # subsequent queries route around the dead server: no retries, no
        # failure-count growth
        before = fd.failure_count("server_1")
        for _ in range(3):
            r = c.query("SELECT COUNT(*), SUM(m) FROM t WHERE d < 5")
            assert not r.exceptions, r.exceptions
        assert fd.failure_count("server_1") == before

    def test_unreplicated_segment_surfaces_error(self, replicated_cluster,
                                                 tmp_path):
        c, total = replicated_cluster
        # one extra segment ONLY on server_1
        schema = Schema.from_dict({
            "schemaName": "t",
            "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
            "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})
        tc = TableConfig.from_dict({"tableName": "t",
                                    "tableType": "OFFLINE"})
        d = str(tmp_path / "solo")
        SegmentCreator(tc, schema).build(
            {"d": np.array([1], np.int64), "m": np.array([7], np.int64)},
            d, "t_solo")
        c.add_segment("t", load_segment(d), server_idx=1)
        c.servers[1].transport.stop()
        c._connections["server_1"].close()
        r = c.query("SELECT COUNT(*) FROM t")
        # replicated segments answer; the lost one raises a server error
        # instead of silently returning a partial-looking clean result
        assert r.exceptions, "lost unreplicated segment must be surfaced"
