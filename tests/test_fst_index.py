"""FST-style regex/prefix index over sorted dictionaries.

Ref: pinot-segment-local readers/LuceneFSTIndexReader.java,
utils/nativefst/ImmutableFST.java — VERDICT r4 missing #6 / weak #8:
LIKE 'pre%' / regexp_like must not regex-scan whole dictionaries, and
text-index prefix queries must not scan the vocabulary.
"""
import re

import numpy as np
import pytest

from pinot_tpu.segment.fst_index import (FstIndex, literal_prefix,
                                         prefix_range)
from pinot_tpu.segment.text_index import TextIndex


class TestLiteralPrefix:
    def test_shapes(self):
        assert literal_prefix("^abc.*") == ("abc", True)
        assert literal_prefix("^abc.*$") == ("abc", True)
        assert literal_prefix("^abc$") == ("abc", False)  # exact, verify
        assert literal_prefix("^abc[0-9]+") == ("abc", False)
        assert literal_prefix("abc") == (None, False)  # unanchored
        assert literal_prefix("^\\.hidden.*") == (".hidden", True)
        assert literal_prefix("^[ab]c") == (None, False)

    def test_prefix_range(self):
        terms = np.array(sorted(["apple", "apply", "banana", "appzz",
                                 "app", "aqua"]), object)
        lo, hi = prefix_range(terms, "app")
        assert list(terms[lo:hi]) == ["app", "apple", "apply", "appzz"]


class TestFstIndex:
    TERMS = np.array(sorted(
        [f"user_{i:04d}" for i in range(500)]
        + [f"admin_{i:03d}" for i in range(100)]
        + ["root", "guest"]), object)

    def _naive(self, pattern):
        rx = re.compile(pattern)
        return [i for i, t in enumerate(self.TERMS) if rx.search(t)]

    @pytest.mark.parametrize("pattern", [
        "^user_.*", "^admin_0[0-4].*", "^user_00(1|2)\\d$", "^root$",
        "^zzz.*", "0_9", "user_0001",
    ])
    def test_matches_naive(self, pattern):
        ix = FstIndex(self.TERMS)
        assert ix.matching_dict_ids(pattern).tolist() == self._naive(pattern)

    def test_cache_hit_returns_same(self):
        ix = FstIndex(self.TERMS)
        a = ix.matching_dict_ids("^user_.*")
        b = ix.matching_dict_ids("^user_.*")
        assert a is b

    def test_numeric_terms_fall_back(self):
        ix = FstIndex(np.arange(100))
        assert ix.matching_dict_ids("^1.*").tolist() == \
            [i for i, v in enumerate(range(100))
             if re.search("^1.*", str(v))]


class TestSqlLikePath:
    def test_like_prefix_and_regexp(self, tmp_path):
        from pinot_tpu.models import (DataType, FieldSpec, FieldType,
                                      Schema, TableConfig)
        from pinot_tpu.query.executor import QueryExecutor
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment
        rng = np.random.default_rng(9)
        n = 20_000
        names = np.array([f"{p}{i % 997}" for i, p in enumerate(
            rng.choice(["alpha_", "beta_", "gamma_"], size=n))], object)
        schema = Schema("t", [
            FieldSpec("name", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="t")
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build({"name": names}, out, "s0")
        seg = load_segment(out)
        host = QueryExecutor([seg], use_tpu=False)
        dev = QueryExecutor([seg], use_tpu=True)
        for sql, want in [
            ("SELECT COUNT(*) FROM t WHERE name LIKE 'beta_%'",
             int(np.sum([s.startswith("beta_") for s in names]))),
            ("SELECT COUNT(*) FROM t WHERE REGEXP_LIKE(name, '^alpha_1.*')",
             int(np.sum([bool(re.search('^alpha_1.*', s)) for s in names]))),
        ]:
            assert host.execute(sql).rows[0][0] == want
            assert dev.execute(sql).rows[0][0] == want


class TestSoundnessEdges:
    """Review findings: unsound prefixes must not drop matching rows."""

    def test_toplevel_alternation_scans(self):
        terms = np.array(sorted(["abx", "xcd", "zz"]), object)
        ix = FstIndex(terms)
        got = ix.matching_dict_ids("^ab|cd").tolist()
        want = [i for i, t in enumerate(terms) if re.search("^ab|cd", t)]
        assert got == want and terms[got[1]] == "xcd"

    def test_grouped_alternation_still_uses_prefix(self):
        assert literal_prefix("^ab(c|d)e")[0] == "ab"

    def test_zero_quantifier_drops_last_literal(self):
        assert literal_prefix("^abc*") == ("ab", False)
        assert literal_prefix("^abc?x") == ("ab", False)
        assert literal_prefix("^abc{0,2}") == ("ab", False)
        assert literal_prefix("^abc+")[0] == "abc"  # + needs >= 1: sound
        terms = np.array(sorted(["ab", "abc", "abcc", "abd"]), object)
        ix = FstIndex(terms)
        got = ix.matching_dict_ids("^abc*$").tolist()
        want = [i for i, t in enumerate(terms)
                if re.search("^abc*$", t)]
        assert got == want  # 'ab' included

    def test_bytes_terms_fall_back(self):
        terms = np.array(sorted([b"aa", b"ab", b"zz"]), object)
        ix = FstIndex(terms)
        got = ix.matching_dict_ids("^a.*").tolist()
        want = [i for i, t in enumerate(terms)
                if re.search("^a.*", str(t))]
        assert got == want
