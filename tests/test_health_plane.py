"""Fleet health plane (ISSUE 14): metrics history, cluster rollup,
per-query cost attribution, SLO burn-rate watchdog.

Layers under test:

  * MetricsRegistry.sample() + # HELP exposition + remove_gauge (the
    stale labeled-series fix) + concurrent scrape safety;
  * MetricsHistory ring / MetricsSampler cadence + hook isolation;
  * SloWatchdog multi-window burn math, A/A silence, and the
    end-to-end breach under a seeded failpoint latency regression;
  * WorkloadRegistry rollup + the coalesced-launch cost split
    (property-tested: member charges sum to the launch total);
  * ClusterHealthMonitor sweep: live/degraded verdicts, scrape-failure
    degradation without a throw, fleet counter rollup;
  * /debug endpoints (history/sample/health/workload, /debug/queries
    tenant + remainingDeadlineMs) over DebugHttpServer;
  * selfmetrics: the time-series engine answering simpleql over the
    role's own history (the engine's first real consumer);
  * the bench --health smoke leg (tier-1 overhead gate).
"""
import json
import logging
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.health.history import (MetricsHistory, MetricsSampler,
                                      get_history, start_sampling,
                                      stop_sampling)
from pinot_tpu.health.rollup import (ClusterHealthMonitor, ScrapeTarget,
                                     role_health_summary)
from pinot_tpu.health.slo import SloWatchdog
from pinot_tpu.health.workload import WorkloadRegistry, get_workload
from pinot_tpu.utils import metrics as metrics_mod
from pinot_tpu.utils.accounting import ResourceAccountant
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import failpoints
from pinot_tpu.utils.metrics import MetricsRegistry, get_registry


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture()
def fresh_server_registry():
    """Swap the process-global 'server' registry for a fresh one so
    cumulative timer reservoirs from other tests can't leak into
    latency-quantile assertions."""
    with metrics_mod._reg_lock:
        old = metrics_mod._registries.get("server")
        fresh = MetricsRegistry("server")
        metrics_mod._registries["server"] = fresh
    try:
        yield fresh
    finally:
        with metrics_mod._reg_lock:
            if old is not None:
                metrics_mod._registries["server"] = old
            else:
                metrics_mod._registries.pop("server", None)


def _build_segment(tmp_path, name="s0", docs=500):
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC)])
    rng = np.random.default_rng(7)
    d = str(tmp_path / name)
    SegmentCreator(TableConfig(name="t"), schema).build(
        {"k": rng.integers(0, 100, docs).astype(np.int32),
         "v": rng.integers(0, 10, docs).astype(np.int32)}, d, name)
    return load_segment(d)


# ---------------------------------------------------------------------------
# registry: sample / HELP / remove_gauge / concurrent scrape
# ---------------------------------------------------------------------------

class TestRegistrySurface:
    def test_sample_is_flat_and_timestamped(self):
        reg = MetricsRegistry("r1")
        reg.add_meter("queries", 3)
        reg.add_meter("queries", 2, labels={"table": "t"})
        reg.set_gauge("task_queue_depth", 7.0)
        with reg.time("query_execution"):
            pass
        s = reg.sample()
        assert s["role"] == "r1" and s["ts"] <= time.time()
        assert s["counters"]["queries"] == 3
        assert s["counters"]['queries{table="t"}'] == 2
        assert s["gauges"]["task_queue_depth"] == 7.0
        t = s["timers"]["query_execution"]
        assert t["count"] == 1 and t["p99"] >= 0

    def test_help_lines_from_catalog(self):
        reg = MetricsRegistry("r2")
        reg.add_meter("queries")          # cataloged
        reg.add_meter("totally_uncataloged_thing")
        text = reg.prometheus_text()
        lines = text.splitlines()
        i = lines.index("# TYPE pinot_tpu_r2_queries counter")
        assert lines[i - 1].startswith("# HELP pinot_tpu_r2_queries "), \
            lines[i - 1]
        # uncataloged names emit TYPE only — no fabricated HELP
        assert "# TYPE pinot_tpu_r2_totally_uncataloged_thing counter" \
            in lines
        assert not any(
            ln.startswith("# HELP pinot_tpu_r2_totally_uncataloged")
            for ln in lines)
        # one HELP per family, even with several label sets
        reg.add_meter("queries", labels={"table": "x"})
        text = reg.prometheus_text()
        assert text.count("# HELP pinot_tpu_r2_queries ") == 1

    def test_remove_gauge_drops_series(self):
        reg = MetricsRegistry("r3")
        reg.set_gauge("ingestion_delay_ms", 120.0,
                      labels={"partition": "0"})
        reg.set_gauge("ingestion_delay_ms", 80.0,
                      labels={"partition": "1"})
        assert reg.remove_gauge("ingestion_delay_ms",
                                labels={"partition": "0"})
        text = reg.prometheus_text()
        assert 'partition="0"' not in text
        assert 'partition="1"' in text
        assert 'ingestion_delay_ms{partition="0"}' \
            not in reg.sample()["gauges"]
        # removing a series that never existed reports False
        assert not reg.remove_gauge("ingestion_delay_ms",
                                    labels={"partition": "9"})

    def test_delay_tracker_remove_partition_regression(self):
        """The satellite fix: a removed partition's labeled gauge must
        LEAVE the exposition — the old zeroing behavior kept the stale
        series on /metrics forever."""
        from pinot_tpu.ingest.realtime_manager import IngestionDelayTracker
        reg = MetricsRegistry("r4")
        tr = IngestionDelayTracker(metrics=reg, labels={"table": "t"})
        tr.record(0, int(time.time() * 1000) - 500)
        tr.record(1, int(time.time() * 1000) - 100)
        assert 'partition="0"' in reg.prometheus_text()
        tr.remove_partition(0)
        text = reg.prometheus_text()
        assert 'partition="0"' not in text, \
            "removed partition's gauge lingers on /metrics"
        assert 'partition="1"' in text
        assert tr.delay_ms(0) is None

    def test_concurrent_scrape_safety(self):
        """Hammer prometheus_text()/sample() against concurrent
        writers: every page parses, counters are monotonic."""
        reg = MetricsRegistry("r5")
        stop = threading.Event()
        errors = []

        def writer(i):
            n = 0
            while not stop.is_set():
                n += 1
                reg.add_meter("queries", labels={"w": str(i)})
                reg.set_gauge("task_queue_depth", n % 50,
                              labels={"w": str(i)})
                reg.add_timing("query_execution", n % 7,
                               labels={"w": str(i)})

        line_rx = re.compile(
            r'^(# (TYPE|HELP) .+|[a-zA-Z_:][\w:]*(\{[^}]*\})? '
            r'[-+0-9.eE]+(nan|inf)?)$')

        def reader():
            last: dict = {}
            try:
                for _ in range(30):
                    text = reg.prometheus_text()
                    for ln in text.splitlines():
                        assert line_rx.match(ln), f"unparseable: {ln!r}"
                    s = reg.sample()
                    for k, v in s["counters"].items():
                        assert v >= last.get(k, 0.0), \
                            f"counter {k} went backwards"
                        last[k] = v
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        writers = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        readers = [threading.Thread(target=reader) for _ in range(3)]
        for t in writers + readers:
            t.start()
        for t in readers:
            t.join(20)
        stop.set()
        for t in writers:
            t.join(5)
        assert not errors, errors


# ---------------------------------------------------------------------------
# history ring + sampler
# ---------------------------------------------------------------------------

class TestHistory:
    def test_ring_bound_and_window(self):
        h = MetricsHistory(capacity=4)
        for i in range(10):
            h.append({"ts": 1000.0 + i, "counters": {"c": float(i)}})
        assert len(h) == 4
        assert [s["ts"] for s in h.samples()] == [1006.0, 1007.0,
                                                  1008.0, 1009.0]
        win = h.samples(window_s=2.0, now=1009.0)
        assert [s["ts"] for s in win] == [1007.0, 1008.0, 1009.0]
        assert h.latest()["ts"] == 1009.0

    def test_counter_delta_and_reset_clamp(self):
        h = MetricsHistory()
        h.append({"ts": 0.0, "counters": {"c": 10.0}})
        h.append({"ts": 10.0, "counters": {"c": 25.0}})
        delta, secs = h.counter_delta("c", 60.0, now=10.0)
        assert (delta, secs) == (15.0, 10.0)
        # restart between samples: the registry reset must not read as
        # negative traffic — clamp to the newest absolute value
        h.append({"ts": 20.0, "counters": {"c": 3.0}})
        delta, _ = h.counter_delta("c", 60.0, now=20.0)
        assert delta == 3.0

    def test_family_sum_and_timer_series(self):
        h = MetricsHistory()
        h.append({"ts": 0.0,
                  "counters": {'e{t="a"}': 1.0, 'e{t="b"}': 2.0},
                  "timers": {'q{t="a"}': {"p99": 5.0},
                             'q{t="b"}': {"p99": 9.0}}})
        h.append({"ts": 5.0,
                  "counters": {'e{t="a"}': 4.0, 'e{t="b"}': 2.0},
                  "timers": {'q{t="a"}': {"p99": 7.0}}})
        assert h.counter_sum_delta("e", 60.0, now=5.0)[0] == 3.0
        series = h.timer_series("q", "p99", 60.0, now=5.0)
        assert series == [(0.0, 9.0), (5.0, 7.0)]  # worst across labels
        # prefix matching must not cross families ("e" vs "extra")
        h.append({"ts": 6.0, "counters": {'e{t="a"}': 4.0, 'e{t="b"}': 2.0,
                                          "extra": 100.0}})
        assert h.counter_sum_delta("e", 60.0, now=6.0)[0] == 3.0
        assert h.counter_sum_delta("extra", 60.0, now=6.0)[0] == 100.0

    def test_sampler_appends_and_hook_isolation(self):
        reg = MetricsRegistry("hsamp")
        h = MetricsHistory()
        s = MetricsSampler("hsamp", history=h, registry=reg)
        calls = []
        s.add_hook(lambda: calls.append(1))
        s.add_hook(lambda: 1 / 0)  # a hook bug must not stop sampling
        s.sample_once()
        s.sample_once()
        assert len(h) == 2 and calls == [1, 1]
        assert reg.sample()["counters"]["metrics_history_samples"] == 2.0

    def test_sampler_thread_lifecycle(self):
        reg = MetricsRegistry("hthread")
        h = MetricsHistory()
        s = MetricsSampler("hthread", interval_s=0.02, history=h,
                           registry=reg)
        s.start()
        deadline = time.time() + 5.0
        while len(h) < 3 and time.time() < deadline:
            time.sleep(0.02)
        s.stop()
        n = len(h)
        assert n >= 3
        time.sleep(0.1)
        assert len(h) == n, "sampler kept appending after stop"

    def test_start_sampling_knobs(self):
        cfg_off = PinotConfiguration(
            overrides={"pinot.metrics.history.enabled": False})
        assert start_sampling("knobrole", cfg_off) is None
        cfg = PinotConfiguration(overrides={
            "pinot.metrics.history.interval.ms": 10.0,
            "pinot.metrics.history.window.seconds": 1.0})
        try:
            s1 = start_sampling("knobrole", cfg)
            assert s1 is not None
            assert start_sampling("knobrole", cfg) is s1  # idempotent
            # capacity sized from window/interval
            assert get_history("knobrole").capacity >= 8
        finally:
            stop_sampling("knobrole")


# ---------------------------------------------------------------------------
# SLO watchdog
# ---------------------------------------------------------------------------

def _slo_cfg(**over):
    base = {"pinot.slo.query.p99.ms": 50.0,
            "pinot.slo.window.short.seconds": 30.0,
            "pinot.slo.window.long.seconds": 60.0,
            "pinot.slo.burn.threshold": 1.0,
            "pinot.slo.latency.budget": 0.1}
    base.update(over)
    return PinotConfiguration(overrides=base)


class TestSloWatchdog:
    def test_disabled_without_targets(self):
        dog = SloWatchdog("sd", MetricsHistory(),
                          config=PinotConfiguration())
        assert not dog.enabled
        assert dog.evaluate() == {}

    def test_latency_burn_multi_window(self):
        reg = MetricsRegistry("slo1")
        h = MetricsHistory()
        now = 1000.0
        # cumulative counters, 10 queries per 6s tick; from i=8 every
        # query runs over target (slo_latency_bad tracks queries 1:1).
        # The burn is a WINDOWED bad/total ratio — deliberately not the
        # registry timer p99s, whose lifetime reservoir would make
        # every sample carry the same sticky cumulative quantile.
        for i in range(10):
            h.append({"ts": now - 60 + i * 6,
                      "counters": {
                          "queries": 10.0 * (i + 1),
                          "slo_latency_bad":
                              0.0 if i < 8 else 10.0 * (i - 7)}})
        dog = SloWatchdog("slo1", h, config=_slo_cfg(), metrics=reg)
        v = dog.evaluate(now=now)["query.p99.ms"]
        # short window (30s, ts>=970): samples i=5..9 -> 20 bad of 40
        # queries -> frac .5 / budget .1 = burn 5; long (60s): 20 bad
        # of 90 -> burn 20/90/.1
        assert v["burnShort"] == pytest.approx(5.0)
        assert v["burnLong"] == pytest.approx((20.0 / 90.0) / 0.1,
                                              abs=1e-3)
        assert v["breached"]
        assert reg.sample()["gauges"]['slo_burn_rate{slo="query.p99.ms"}'] \
            == pytest.approx(5.0)

    def test_short_blip_does_not_breach(self):
        h = MetricsHistory()
        now = 1000.0
        # 10 queries per 5s tick; a blip at i>=18 makes 8 of them bad
        for i in range(20):
            h.append({"ts": now - 95 + i * 5,
                      "counters": {
                          "queries": 10.0 * (i + 1),
                          "slo_latency_bad":
                              0.0 if i < 18 else 8.0 * (i - 17)}})
        dog = SloWatchdog(
            "slo2", h, config=_slo_cfg(
                **{"pinot.slo.window.short.seconds": 10.0,
                   "pinot.slo.window.long.seconds": 90.0,
                   "pinot.slo.latency.budget": 0.5}),
            metrics=MetricsRegistry("slo2"))
        v = dog.evaluate(now=now)["query.p99.ms"]
        assert v["burnShort"] > 1.0      # the blip fills the short window
        assert v["burnLong"] < 1.0       # but not the long one
        assert not v["breached"]         # -> no page

    def test_error_rate_burn(self):
        h = MetricsHistory()
        h.append({"ts": 0.0, "counters": {"broker_queries": 100.0,
                                          "broker_query_errors": 0.0}})
        h.append({"ts": 30.0, "counters": {"broker_queries": 200.0,
                                           "broker_query_errors": 5.0}})
        cfg = _slo_cfg(**{"pinot.slo.query.p99.ms": 0.0,
                          "pinot.slo.error.rate": 0.01})
        dog = SloWatchdog("slo3", h, config=cfg,
                          metrics=MetricsRegistry("slo3"))
        v = dog.evaluate(now=30.0)["error.rate"]
        # 5 errors / 100 queries = .05 over a .01 target -> burn 5
        assert v["burnShort"] == pytest.approx(5.0)
        assert v["breached"]

    def test_freshness_burn(self):
        h = MetricsHistory()
        for i in range(4):
            h.append({"ts": float(i * 10),
                      "gauges": {'ingestion_delay_ms{partition="0"}':
                                 50_000.0 if i >= 2 else 100.0}})
        cfg = _slo_cfg(**{"pinot.slo.query.p99.ms": 0.0,
                          "pinot.slo.freshness.ms": 1000.0,
                          "pinot.slo.latency.budget": 0.25})
        dog = SloWatchdog("slo4", h, config=cfg,
                          metrics=MetricsRegistry("slo4"))
        v = dog.evaluate(now=30.0)["freshness.ms"]
        assert v["burnShort"] == pytest.approx(2.0)  # 2/4 bad / .25

    def test_e2e_breach_under_failpoint_delay(
            self, tmp_path, fresh_server_registry, caplog):
        """The acceptance leg: an injected latency regression (seeded
        failpoint delay on the server execute path) fires SLO_BREACH +
        the burn gauge; the A/A baseline stays silent; a sustained
        breach logs its onset ONCE."""
        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.query_server import ServerQueryExecutor
        seg = _build_segment(tmp_path)
        dm = InstanceDataManager("slo-e2e")
        dm.table("t").add_segment(seg)
        cfg = _slo_cfg(**{"pinot.slo.query.p99.ms": 100.0,
                          "pinot.slo.window.short.seconds": 600.0,
                          "pinot.slo.window.long.seconds": 600.0})
        # the executor reads the same target: queries over it bump the
        # slo_latency_bad counter the watchdog's latency burn reads
        ex = ServerQueryExecutor(dm, use_tpu=False, config=cfg)
        reg = fresh_server_registry
        h = MetricsHistory()
        sampler = MetricsSampler("server", history=h, registry=reg)
        dog = SloWatchdog("server", h, config=cfg, metrics=reg)
        sampler.add_hook(dog.evaluate)

        def run(n):
            for i in range(n):
                ex.execute("t", "SELECT COUNT(*) FROM t",
                           query_id=f"q{time.time_ns()}")
                sampler.sample_once()

        # A/A baseline: fast queries, no breach, no gauge over threshold
        with caplog.at_level(logging.WARNING, logger="pinot_tpu.slo"):
            run(4)
            assert not dog.breached()
            assert "SLO_BREACH" not in caplog.text
            # the regression: every execute now pays a seeded 250ms
            failpoints.arm("server.execute.before", delay=0.25, seed=14)
            run(4)
        assert dog.breached()
        v = dog.verdicts()["query.p99.ms"]
        assert v["burnShort"] > 1.0
        breach_lines = [r for r in caplog.records
                        if "SLO_BREACH" in r.getMessage()]
        assert len(breach_lines) == 1, "sustained breach must log onset once"
        payload = json.loads(
            breach_lines[0].getMessage().split("SLO_BREACH ", 1)[1])
        assert payload["slo"] == "query.p99.ms"
        assert reg.sample()["counters"]['slo_breaches{slo="query.p99.ms"}'] \
            == 1.0


# ---------------------------------------------------------------------------
# workload accounting + the coalesced cost split
# ---------------------------------------------------------------------------

class TestWorkload:
    def test_rollup_and_tenant_gauge(self):
        reg = MetricsRegistry("wl1")
        wl = WorkloadRegistry("wl1", metrics=reg)
        wl.record(tenant="acme", table="t1", fingerprint="fp1",
                  cpu_ms=10.0, device_kernel_ms=5.0, rows_scanned=100)
        wl.record(tenant="acme", table="t1", fingerprint="fp1",
                  cpu_ms=2.0, rows_scanned=50, error=True)
        wl.record(tenant="beta", table="t2", fingerprint="fp2",
                  cpu_ms=100.0)
        top = wl.top(10)
        assert top[0]["tenant"] == "beta"
        acme = next(e for e in top if e["tenant"] == "acme")
        assert acme["queries"] == 2 and acme["errors"] == 1
        assert acme["rowsScanned"] == 150
        assert acme["costMs"] == pytest.approx(17.0)
        assert wl.tenants()["acme"] == pytest.approx(17.0)
        g = reg.sample()["gauges"]
        assert g['workload_tenant_cost_ms{tenant="beta"}'] == 100.0
        payload = wl.payload(k=1)
        assert len(payload["topK"]) == 1
        assert payload["tenantCostMs"]["acme"] == pytest.approx(17.0)

    def test_eviction_keeps_expensive(self):
        wl = WorkloadRegistry("wl2", metrics=MetricsRegistry("wl2"),
                              max_entries=3)
        for i in range(3):
            wl.record(tenant="t", table=f"tab{i}", fingerprint="f",
                      cpu_ms=(i + 1) * 100.0)
        wl.record(tenant="t", table="fresh", fingerprint="f", cpu_ms=1.0)
        tables = {e["table"] for e in wl.top(10)}
        assert "tab0" not in tables          # cheapest evicted
        assert {"tab1", "tab2", "fresh"} == tables

    def test_unattributed_keys_do_not_collide_with_blank(self):
        wl = WorkloadRegistry("wl3", metrics=MetricsRegistry("wl3"))
        wl.record(tenant="", table="", fingerprint="", cpu_ms=1.0)
        e = wl.top(1)[0]
        assert e["tenant"] == "-" and e["table"] == "-"

    def test_split_charge_property(self):
        """The acceptance invariant, property-tested: across random doc
        distributions (incl. zero-doc members), the per-member kernel-ms
        charges sum EXACTLY to the launch total, proportional to doc
        share."""
        from pinot_tpu.ops.dispatch import Launch, split_charge
        rng = np.random.default_rng(1234)
        for trial in range(50):
            n = int(rng.integers(1, 12))
            docs = rng.integers(0, 100_000, n)
            if trial % 7 == 0:
                docs[:] = 0          # degenerate: even split
            kernel_ms = float(rng.uniform(0.1, 500.0))
            acct = ResourceAccountant()
            launches = []
            for i in range(n):
                qid = f"q{trial}-{i}"
                acct.begin_query(qid, None)
                launches.append(Launch(
                    call=lambda: None, slip=acct.slip(qid),
                    docs=int(docs[i])))
            split_charge(launches, kernel_ms)
            charges = [acct.usage(f"q{trial}-{i}").device_kernel_ms
                       for i in range(n)]
            assert sum(charges) == pytest.approx(kernel_ms, rel=1e-9), \
                (trial, docs, kernel_ms, charges)
            total = docs.sum()
            for i in range(n):
                want = (kernel_ms * docs[i] / total if total
                        else kernel_ms / n)
                assert charges[i] == pytest.approx(want, rel=1e-9)

    def test_split_charge_skips_detached_without_redistributing(self):
        from pinot_tpu.ops.dispatch import Launch, split_charge
        acct = ResourceAccountant()
        acct.begin_query("q0", None)
        live = [Launch(call=lambda: None, slip=acct.slip("q0"), docs=250),
                Launch(call=lambda: None, slip=None, docs=750)]
        split_charge(live, 100.0)
        # the attributed member pays ITS share only — the slip-less
        # peer's share is unrecorded, never redistributed
        assert acct.usage("q0").device_kernel_ms == pytest.approx(25.0)

    def test_eight_coalesced_queries_split_one_launch(self):
        """Eight concurrent fingerprint-equal launches coalesce into ONE
        batched launch; each member's kernel charge is its doc share of
        the one launch's measured total, and the charges sum to it."""
        from pinot_tpu.ops import dispatch as dispatch_mod
        from pinot_tpu.ops.dispatch import KernelDispatcher, Launch

        cfg = PinotConfiguration(overrides={
            "pinot.server.dispatch.batch.window.ms": 250.0,
            "pinot.server.dispatch.batch.max": 8})
        disp = KernelDispatcher(config=cfg,
                                metrics=MetricsRegistry("wl4"))
        kernel_calls = []

        def factory(B, stacked):
            def kern(cols, plist, num_docs, D=0, G=0):
                kernel_calls.append(B)
                time.sleep(0.01)
                return np.zeros((B, 4), np.float64)
            return kern

        observed = {}
        real_split = dispatch_mod.split_charge

        def spy_split(live, kernel_ms):
            observed["kernel_ms"] = kernel_ms
            observed["n"] = len(live)
            real_split(live, kernel_ms)

        acct = ResourceAccountant()
        docs = [100, 200, 300, 400, 500, 600, 700, 800]
        launches = []
        for i, d in enumerate(docs):
            acct.begin_query(f"c{i}", None)
            launches.append(Launch(
                call=lambda: np.zeros(4), plan="fp", cols=(), params=(i,),
                num_docs=None, D=8, G=0, batch_key=("fp", 8, 8, 0),
                cols_key=("same",), factory=factory,
                slip=acct.slip(f"c{i}"), docs=d))
        barrier = threading.Barrier(9)

        def submit(launch):
            # enter BEFORE the barrier: the ring must observe 8 active
            # callers when the first launch arrives, or the lone-query
            # inline fast path serves them serially with nothing to
            # coalesce
            disp.enter_active()
            try:
                barrier.wait(5)
                return dispatch_mod.wait_result(disp.submit(launch),
                                                max_wait_s=30.0)
            finally:
                disp.exit_active()

        dispatch_mod.split_charge = spy_split
        try:
            threads = [threading.Thread(target=submit, args=(ln,))
                       for ln in launches]
            for t in threads:
                t.start()
            barrier.wait(5)
            for t in threads:
                t.join(30)
        finally:
            dispatch_mod.split_charge = real_split
            disp.close()
        assert kernel_calls == [8], \
            f"expected one batched launch of 8, got {kernel_calls}"
        assert observed["n"] == 8
        charges = [acct.usage(f"c{i}").device_kernel_ms
                   for i in range(8)]
        assert all(c > 0 for c in charges)
        assert sum(charges) == pytest.approx(observed["kernel_ms"],
                                             rel=1e-9)
        total = sum(docs)
        for c, d in zip(charges, docs):
            assert c == pytest.approx(
                observed["kernel_ms"] * d / total, rel=1e-9)

    def test_executor_charges_rows_and_records_workload(
            self, tmp_path, fresh_server_registry):
        """End-to-end server path: a finished query's usage (rows/bytes
        scanned, attribution dimensions) lands in the server workload
        rollup keyed by (tenant, table, fingerprint)."""
        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.query_server import ServerQueryExecutor
        seg = _build_segment(tmp_path, docs=400)
        dm = InstanceDataManager("wl-e2e")
        dm.table("t").add_segment(seg)
        ex = ServerQueryExecutor(dm, use_tpu=False)
        wl = get_workload("server")
        wl.clear()
        ex.execute("t", "SELECT COUNT(*) FROM t WHERE k < 50",
                   query_id="wlq1", tenant="acme")
        top = wl.top(5)
        assert top, "no workload recorded"
        e = top[0]
        assert e["tenant"] == "acme" and e["table"] == "t"
        assert e["planFingerprint"] not in ("", "-")
        assert e["queries"] == 1
        assert e["rowsScanned"] > 0
        assert e["bytesScanned"] > 0
        wl.clear()


# ---------------------------------------------------------------------------
# cluster rollup
# ---------------------------------------------------------------------------

def _fake_target(iid, role="server", counters=None, degraded=False,
                 boom=False):
    def fetch():
        if boom:
            raise ConnectionError("connection refused")
        return {"health": {"verdict": "degraded" if degraded else "live",
                           "degraded": ["slo"] if degraded else [],
                           "subsystems": {}},
                "sample": {"ts": time.time(), "role": role,
                           "counters": dict(counters or {}),
                           "gauges": {"g": 1.0}, "timers": {}}}
    return ScrapeTarget(instance_id=iid, fetch=fetch, role=role)


class TestClusterRollup:
    def test_sweep_verdicts_and_metrics(self):
        reg = MetricsRegistry("roll1")
        targets = [
            _fake_target("s1", counters={"queries": 10.0}),
            _fake_target("s2", counters={"queries": 5.0,
                                         'q{t="a"}': 2.0}),
            _fake_target("s3", boom=True),
            _fake_target("s4", degraded=True),
        ]
        ages = {"s1": 1.0, "s2": 999.0, "s3": 2.0}
        mon = ClusterHealthMonitor(lambda: targets,
                                   liveness_fn=lambda: ages,
                                   liveness_ttl_s=15.0, metrics=reg)
        payload = mon.sweep()
        inst = payload["instances"]
        assert inst["s1"]["verdict"] == "live"
        assert inst["s1"]["liveness"] == "live"
        # a reachable instance with a stale heartbeat is degraded
        assert inst["s2"]["liveness"] == "stale"
        assert inst["s2"]["verdict"] == "degraded"
        # a scrape failure degrades with the reason, never throws
        assert inst["s3"]["verdict"] == "degraded"
        assert not inst["s3"]["reachable"]
        assert "ConnectionError" in inst["s3"]["reason"]
        # an instance reporting its own degradation passes through
        assert inst["s4"]["verdict"] == "degraded"
        assert inst["s4"]["degraded"] == ["slo"]
        # no heartbeat signal at all reads "unknown", not a lie
        assert inst["s4"]["liveness"] == "unknown"
        assert payload["instancesLive"] == 1
        assert payload["instancesDegraded"] == 3
        g = reg.sample()["gauges"]
        assert g["cluster_instances_live"] == 1.0
        assert g["cluster_instances_degraded"] == 3.0
        assert reg.sample()["counters"]["cluster_scrape_failures"] == 1.0
        # cluster metrics: counters summed across instances, gauges kept
        # per instance
        cm = mon.cluster_metrics()
        assert cm["counters"]["queries"] == 15.0
        assert cm["counters"]['q{t="a"}'] == 2.0
        assert cm["gaugesByInstance"]["s1"]["g"] == 1.0

    def test_sweep_survives_broken_targets_fn(self):
        mon = ClusterHealthMonitor(
            lambda: 1 / 0, metrics=MetricsRegistry("roll2"))
        payload = mon.sweep()   # must not raise
        assert payload["instances"] == {}

    def test_first_get_answers_without_prior_sweep(self):
        mon = ClusterHealthMonitor(
            lambda: [_fake_target("x", counters={"c": 1.0})],
            metrics=MetricsRegistry("roll3"))
        assert mon.cluster_health()["instances"]["x"]["verdict"] == "live"
        mon2 = ClusterHealthMonitor(
            lambda: [_fake_target("x", counters={"c": 1.0})],
            metrics=MetricsRegistry("roll3"))
        assert mon2.cluster_metrics()["counters"]["c"] == 1.0

    def test_role_health_summary_subsystems(self):
        reg = MetricsRegistry("roll4")
        s = role_health_summary("roll4", registry=reg)
        assert s["verdict"] == "live" and s["degraded"] == []
        # an open remote-tier breaker degrades the data path
        reg.set_gauge("remote_cache_breaker_state", 1.0,
                      labels={"node": "n1"})
        s = role_health_summary("roll4", registry=reg)
        assert s["verdict"] == "degraded"
        assert "breakers" in s["degraded"]
        reg.set_gauge("remote_cache_breaker_state", 0.0,
                      labels={"node": "n1"})
        # a paused ingestion partition degrades ingestion
        reg.set_gauge("ingest_consumer_paused", 1.0,
                      labels={"partition": "0"})
        reg.set_gauge("ingestion_delay_ms", 1234.0,
                      labels={"partition": "0"})
        s = role_health_summary("roll4", registry=reg)
        assert "ingestion" in s["degraded"]
        assert s["subsystems"]["ingestion"]["maxDelayMs"] == 1234.0
        assert s["subsystems"]["ingestion"]["pausedPartitions"] == 1


# ---------------------------------------------------------------------------
# /debug endpoints
# ---------------------------------------------------------------------------

class TestDebugEndpoints:
    def test_debug_http_health_plane_routes(self):
        from pinot_tpu.utils.trace_store import DebugHttpServer
        role = "dbgrole"
        reg = get_registry(role)
        reg.add_meter("queries", 3)
        hist = get_history(role)
        hist.clear()
        hist.append(reg.sample())
        wl = get_workload(role)
        wl.clear()
        wl.record(tenant="acme", table="t", fingerprint="f", cpu_ms=2.0)
        srv = DebugHttpServer([role])
        srv.start()
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://{srv.host}:{srv.port}{path}",
                        timeout=5) as r:
                    return json.loads(r.read())
            s = get("/debug/metrics/sample")
            assert s["counters"]["queries"] == 3.0
            hy = get("/debug/metrics/history")
            assert hy["role"] == role and len(hy["samples"]) == 1
            hl = get("/debug/health")
            assert hl["verdict"] == "live"
            assert hl["historySamples"] == 1
            w = get("/debug/workload")
            assert w["topK"][0]["tenant"] == "acme"
        finally:
            srv.stop()

    def test_inflight_tenant_and_remaining_deadline(self):
        from pinot_tpu.utils.trace_store import InflightRegistry
        reg = InflightRegistry()
        reg.begin("q1", sql="SELECT 1", tenant="acme",
                  deadline=time.time() + 30.0)
        reg.begin("q2", sql="SELECT 2")
        reg.annotate("q2", tenant="beta", deadline=time.time() + 5.0)
        snap = {e["queryId"]: e for e in reg.snapshot()}
        assert snap["q1"]["tenant"] == "acme"
        assert 0 < snap["q1"]["remainingDeadlineMs"] <= 30_000
        assert snap["q2"]["tenant"] == "beta"
        assert 0 < snap["q2"]["remainingDeadlineMs"] <= 5_000
        # a query with no budget reports None, not a fake number
        reg.begin("q3", sql="SELECT 3")
        snap = {e["queryId"]: e for e in reg.snapshot()}
        assert snap["q3"]["remainingDeadlineMs"] is None
        assert snap["q3"]["tenant"] is None


# ---------------------------------------------------------------------------
# selfmetrics: the time-series engine's first real consumer
# ---------------------------------------------------------------------------

class TestSelfMetrics:
    def test_simpleql_over_own_history(self):
        from pinot_tpu.health.selfmetrics import query_history
        role = "selfm"
        reg = MetricsRegistry(role)
        hist = MetricsHistory(64)
        sampler = MetricsSampler(role, history=hist, registry=reg)
        base = int(time.time())
        for i in range(10):
            reg.add_meter("queries", 5)
            reg.set_gauge("task_queue_depth", float(i))
            with reg.time("query_execution"):
                pass
            s = sampler.sample_once()
            s["ts"] = base + i   # pin whole-second timestamps
        start, end = base, base + 10
        # gauge series straight through the engine
        block = query_history(
            f"fetch(selfmetrics, value, ts, {start}, {end}, 1) "
            f"| where(family = 'task_queue_depth') | sum()",
            role=role, history=hist)
        assert len(block.series) == 1
        assert block.series[0].values.tolist() == [float(i)
                                                   for i in range(10)]
        # cumulative counter piped through rate(): 5/step after warmup
        block = query_history(
            f"fetch(selfmetrics, value, ts, {start}, {end}, 1) "
            f"| where(family = 'queries') | sum() | rate()",
            role=role, history=hist)
        vals = block.series[0].values
        assert np.allclose(vals[1:], 5.0)
        # timer fields ride the name suffix (count is cumulative; step 1
        # keeps the leaf's in-bucket SUM an identity)
        block = query_history(
            f"fetch(selfmetrics, value, ts, {start}, {end}, 1) "
            f"| where(name = 'query_execution:count') | max()",
            role=role, history=hist)
        assert block.series[0].values[-1] == 10.0

    def test_empty_history_fails_loud(self):
        from pinot_tpu.health.selfmetrics import query_history
        with pytest.raises(ValueError, match="no metrics-history"):
            query_history(
                "fetch(selfmetrics, value, ts, 0, 10, 1) | sum()",
                role="selfm-empty", history=MetricsHistory())


# ---------------------------------------------------------------------------
# tier-1 smoke of the acceptance driver
# ---------------------------------------------------------------------------

class TestHealthBenchSmoke:
    def test_health_bench_smoke(self, tmp_path):
        """The --health acceptance scenario at smoke scale: the paired
        accounting A/B + block-paired sampling legs run end to end and
        the qualitative overhead contract holds (the strict <2% bar
        belongs to the full run in BENCH_health.json)."""
        import bench
        out = str(tmp_path / "BENCH_health_smoke.json")
        bench.health_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["history_samples"] > 0
        assert data["smoke"] is True
