"""Production ingestion pipeline (ISSUE 11): zero-gap seal, columnar
transforms, backpressure, ordered checkpoints, chaos sites.

The seal is never query-visible: the seal-lock is held only for the
snapshot, the immutable builds on a build executor while the consumer
keeps consuming into the next CONSUMING segment, and the sealed mutable
serves until its warmed replacement swaps in. Checkpoints fire strictly
in seal order; a torn checkpoint write degrades to re-consume, never to
a corrupt offset. A SimulatedCrash vanishes the consumer mid-batch and
recovery converges exactly-once via committed offsets + validDocIds
snapshot replay.
"""
import os
import time

import numpy as np
import pytest

from pinot_tpu.controller.completion import SegmentCompletionManager
from pinot_tpu.ingest import InMemoryStream, LongMsgOffset, StreamConfig
from pinot_tpu.ingest.realtime_manager import (
    IngestionDelayTracker, RealtimeSegmentDataManager)
from pinot_tpu.ingest.transforms import TransformPipeline
from pinot_tpu.models import (DataType, FieldSpec, FieldType, IngestionConfig,
                              Schema, TableConfig, TableType, UpsertConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.loader import ImmutableSegment, load_segment
from pinot_tpu.server.data_manager import TableDataManager
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (
    FailpointError, SimulatedCrash, failpoints)


def make_schema():
    return Schema("rt", [
        FieldSpec("id", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("name", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("score", DataType.DOUBLE, FieldType.METRIC),
    ])


def upsert_schema():
    return Schema("u", [
        FieldSpec("pk", DataType.LONG),
        FieldSpec("ver", DataType.LONG),
        FieldSpec("val", DataType.DOUBLE, FieldType.METRIC),
    ], primary_key_columns=["pk"])


def upsert_config():
    tc = TableConfig("u", TableType.REALTIME)
    tc.upsert = UpsertConfig(mode="FULL", comparison_column="ver")
    return tc


def _wait(pred, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _count_rows(tdm, table="rt"):
    sdms = tdm.acquire_segments()
    try:
        ex = QueryExecutor([s.segment for s in sdms], use_tpu=False)
        return ex.execute(f"SELECT COUNT(*) FROM {table} LIMIT 5").rows[0][0]
    finally:
        TableDataManager.release_all(sdms)


class TestTransformBatchParity:
    def test_batch_equals_per_row(self):
        """transform_batch(rs)[i] == transform(rs[i]) for every row —
        poison isolated per row, nulls/MV through the exact slow path."""
        tc = TableConfig("rt", TableType.REALTIME)
        tc.ingestion = IngestionConfig(
            transform_configs=[
                {"columnName": "score", "transformFunction": "id * 2"}],
            filter_function="id >= 100")
        p = TransformPipeline(tc, make_schema())
        rng = np.random.default_rng(17)
        records = []
        for i in range(400):
            r = {"id": int(rng.integers(0, 150)), "name": f"n{i % 7}"}
            roll = rng.random()
            if roll < 0.1:
                r["id"] = None
            elif roll < 0.15:
                r["id"] = "not-a-number"
            elif roll < 0.2:
                r["id"] = str(r["id"])
            elif roll < 0.25:
                r["score"] = 5.0
            elif roll < 0.28:
                r["id"] = [1, 2]
            records.append(r)
        batch = p.transform_batch([dict(r) for r in records])
        for i, r in enumerate(records):
            try:
                want = p.transform(dict(r))
            except Exception:
                assert isinstance(batch[i], Exception), (i, r)
                continue
            assert not isinstance(batch[i], Exception), (i, r, batch[i])
            assert batch[i] == want, (i, r)

    def test_mixed_type_batch_keeps_per_row_equality_semantics(self):
        """One stray string in a numeric batch must NOT stringify the
        whole column (np.array([5, 'x']) unifies to '<U21' and '5' == 5
        is silently elementwise-False): mixed batches evaluate as object
        arrays with per-element Python semantics, so equality filters
        match exactly what the per-row path matches."""
        tc = TableConfig("rt", TableType.REALTIME)
        # drop rows whose name equals the sentinel (STRING field stays
        # un-coerced, so a numeric value in it makes the batch mixed)
        tc.ingestion = IngestionConfig(filter_function="name = 'drop'")
        p = TransformPipeline(tc, make_schema())
        rows = [{"id": 1, "name": "drop"}, {"id": 2, "name": 7},
                {"id": 3, "name": "keep"}, {"id": 4, "name": "drop"}]
        out = p.transform_batch([dict(r) for r in rows])
        want = [p.transform(dict(r)) for r in rows]
        assert out == want
        assert out[0] is None and out[3] is None  # dropped
        assert isinstance(out[1], dict) and isinstance(out[2], dict)

    def test_poison_rows_do_not_lose_the_batch(self):
        tc = TableConfig("rt", TableType.REALTIME)
        tc.ingestion = IngestionConfig(filter_function="id >= 100")
        p = TransformPipeline(tc, make_schema())
        rows = [{"id": i, "name": "x"} for i in range(10)]
        rows[4]["id"] = object()  # unhashable/uncomparable poison
        out = p.transform_batch(rows)
        good = [o for o in out if isinstance(o, dict)]
        assert len(good) == 9
        assert isinstance(out[4], Exception)


class TestZeroGapSeal:
    def test_seal_never_query_visible_and_consumer_keeps_consuming(
            self, tmp_path):
        """The tentpole property: while the immutable build runs (armed
        slow), the sealed mutable keeps serving — observed row counts
        never regress — AND the consumer keeps indexing into the next
        CONSUMING segment."""
        topic = InMemoryStream("zg_topic", 1)
        failpoints.arm("ingest.seal.build", delay=0.6, times=1)
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="zg_topic",
                              flush_threshold_rows=100)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
                tdm, str(tmp_path),
                on_commit=lambda n, o: commits.append((n, o)))
            for i in range(150):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            # rows 100..149 must land in the NEXT consuming segment
            # while the first segment's build is still in flight
            assert _wait(lambda: mgr.rows_indexed >= 150, timeout=10)
            saw_overlap = len(mgr._pending_sealed) > 0 and not commits
            counts = []
            deadline = time.time() + 5
            while time.time() < deadline and not commits:
                counts.append(_count_rows(tdm))
                time.sleep(0.02)
            counts.append(_count_rows(tdm))
            assert saw_overlap, "build finished before overlap observable"
            # no seal-gap: counts monotonic (no drop when the swap lands)
            assert all(b >= a for a, b in zip(counts, counts[1:])), counts
            assert _wait(lambda: len(commits) == 1, timeout=10)
            assert commits[0][1] == LongMsgOffset(100)
            assert _count_rows(tdm) == 150
            mgr.stop()
            # sealed segment swapped to immutable; consuming still mutable
            sdms = tdm.acquire_segments()
            kinds = {s.segment.name: isinstance(s.segment, ImmutableSegment)
                     for s in sdms}
            TableDataManager.release_all(sdms)
            assert sum(kinds.values()) == 1, kinds
        finally:
            failpoints.disarm("ingest.seal.build")
            InMemoryStream.delete("zg_topic")

    def test_build_failure_retries_without_row_loss(self, tmp_path):
        topic = InMemoryStream("bf_topic", 1)
        failpoints.arm("ingest.seal.build",
                       error=FailpointError("disk hiccup"), times=2)
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="bf_topic",
                              flush_threshold_rows=50)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
                tdm, str(tmp_path),
                on_commit=lambda n, o: commits.append((n, o)))
            for i in range(60):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            assert _wait(lambda: len(commits) == 1, timeout=15), \
                "build retry never converged"
            assert commits[0][1] == LongMsgOffset(50)
            assert _count_rows(tdm) == 60  # rows served throughout
            assert failpoints.count("ingest.seal.build") == 2
            mgr.stop()
        finally:
            failpoints.disarm("ingest.seal.build")
            InMemoryStream.delete("bf_topic")

    def test_torn_checkpoint_retries_in_order(self, tmp_path):
        """A torn checkpoint write persists NOTHING; the ordered-commit
        gate holds later checkpoints behind it and the retry lands both
        in seal order."""
        topic = InMemoryStream("tc_topic", 1)
        failpoints.arm("ingest.checkpoint", torn=True, times=1)
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="tc_topic",
                              flush_threshold_rows=50)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
                tdm, str(tmp_path),
                on_commit=lambda n, o: commits.append((n, o)))
            for i in range(100):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            assert _wait(lambda: len(commits) == 2, timeout=15)
            assert [c[1] for c in commits] == [LongMsgOffset(50),
                                               LongMsgOffset(100)]
            mgr.stop()
        finally:
            failpoints.disarm("ingest.checkpoint")
            InMemoryStream.delete("tc_topic")

    def test_persistent_torn_checkpoint_degrades_to_reconsume(
            self, tmp_path):
        """Checkpoint writes torn FOREVER: segments still seal and serve,
        but no offset persists — a restarted consumer re-consumes from 0
        and (dedup) converges to exactly the published rows. Degrade =
        re-consume, never corrupt."""
        from pinot_tpu.models import DedupConfig
        topic = InMemoryStream("pt_topic", 1)
        failpoints.arm("ingest.checkpoint", torn=True)
        schema = upsert_schema()
        tc = TableConfig("u", TableType.REALTIME)
        tc.dedup = DedupConfig()
        try:
            tdm = TableDataManager("u_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="pt_topic",
                              flush_threshold_rows=50)
            mgr = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm, str(tmp_path),
                on_commit=lambda n, o: commits.append((n, o)))
            for pk in range(60):
                topic.publish({"pk": pk, "ver": 1, "val": 1.0})
            mgr.start()
            assert _wait(lambda: mgr.rows_indexed >= 60, timeout=10)
            assert _wait(lambda: not mgr._pending_sealed, timeout=10)
            mgr.stop()  # NOT drained: the un-sealed tail dies with us
            assert commits == []  # checkpoint never persisted
            failpoints.disarm("ingest.checkpoint")

            # "restart": fresh tdm rebuilt from the on-disk segments, a
            # new manager resuming from offset 0 (nothing committed)
            tdm2 = TableDataManager("u_REALTIME")
            recovered = []
            for name in sorted(os.listdir(str(tmp_path))):
                path = os.path.join(str(tmp_path), name)
                if os.path.isdir(path) and not name.startswith("_"):
                    seg = load_segment(path)
                    tdm2.add_segment(seg)
                    recovered.append(seg)
            mgr2 = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm2, str(tmp_path),
                start_offset=LongMsgOffset(0), start_seq=len(recovered),
                recover_segments=recovered)
            mgr2.start()
            assert _wait(
                lambda: _count_rows(tdm2, "u") == 60, timeout=15), \
                _count_rows(tdm2, "u")
            time.sleep(0.2)
            assert _count_rows(tdm2, "u") == 60  # no dupes, no losses
            mgr2.stop()
        finally:
            failpoints.disarm("ingest.checkpoint")
            InMemoryStream.delete("pt_topic")


class TestForceCommitAndDrain:
    def test_force_commit_routes_through_fsm(self, tmp_path):
        """Satellite: force_commit on an FSM-managed table must go
        through the completion protocol (the old code called _commit()
        directly, splitting replicas). The FSM records the commit."""
        topic = InMemoryStream("fc_topic", 1)
        try:
            completion = SegmentCompletionManager(num_replicas=1)
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="fc_topic",
                              flush_threshold_rows=100_000)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
                tdm, str(tmp_path), completion_manager=completion,
                instance_id="s0",
                on_commit=lambda n, o: commits.append((n, o)))
            name = mgr.mutable.segment_name
            for i in range(30):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            assert _wait(lambda: mgr.mutable.num_docs == 30
                         or mgr.rows_indexed >= 30, timeout=10)
            assert mgr.force_commit(wait_s=10.0)
            # the seal went THROUGH the FSM: the controller-side state
            # machine saw and accepted this segment's commit
            assert completion.state_of(name) == "COMMITTED"
            assert len(commits) == 1 and commits[0][1] == LongMsgOffset(30)
            mgr.stop()
        finally:
            InMemoryStream.delete("fc_topic")

    def test_stop_drain_loses_zero_rows(self, tmp_path):
        """Satellite: stop(drain=True) force-commits the non-empty
        mutable and persists the final checkpoint — a rolling restart
        resumes with zero loss and zero replay."""
        topic = InMemoryStream("dr_topic", 1)
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="dr_topic",
                              flush_threshold_rows=100_000)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
                tdm, str(tmp_path),
                on_commit=lambda n, o: commits.append((n, o)))
            for i in range(40):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            assert _wait(lambda: mgr.rows_indexed >= 40, timeout=10)
            mgr.stop(drain=True)
            assert len(commits) == 1 and commits[0][1] == LongMsgOffset(40)
            # all rows live in a durable immutable segment now
            sdms = tdm.acquire_segments()
            imm = [s.segment for s in sdms
                   if isinstance(s.segment, ImmutableSegment)]
            total = sum(s.num_docs for s in imm)
            TableDataManager.release_all(sdms)
            assert total == 40
        finally:
            InMemoryStream.delete("dr_topic")


class TestBackpressure:
    def _mgr(self, tmp_path, topic, budget, flush_rows=100_000,
             lag_pause_ms=0.0, tracker=None, commits=None):
        cfg = PinotConfiguration(overrides={
            "pinot.server.ingest.memory.bytes": budget,
            "pinot.server.ingest.lag.pause.ms": lag_pause_ms,
            "pinot.server.ingest.fetch.max.rows": 200,
        })
        sc = StreamConfig(stream_type="inmemory", topic=topic,
                          flush_threshold_rows=flush_rows)
        tdm = TableDataManager("rt_REALTIME")
        return RealtimeSegmentDataManager(
            TableConfig("rt", TableType.REALTIME), make_schema(), sc, 0,
            tdm, str(tmp_path), config=cfg, ingestion_delay_tracker=tracker,
            on_commit=(lambda n, o: commits.append((n, o)))
            if commits is not None else None), tdm

    def test_overdriven_producer_bounded_bytes_then_resume(self, tmp_path):
        """The budget pauses the consumer instead of OOMing; releasing
        the pressure resumes it (pause -> resume surfaced)."""
        topic = InMemoryStream("bp_topic", 1)
        try:
            mgr, _tdm = self._mgr(tmp_path, "bp_topic", budget=20_000)
            for i in range(5000):
                topic.publish({"id": i, "name": "n" * 10, "score": 1.0})
            mgr.start()
            assert _wait(lambda: mgr.paused, timeout=10), "never paused"
            peak = mgr.ingest_bytes()
            # bounded: one fetch past the budget at most (adaptive fetch
            # shrank to 1 row approaching the wall)
            assert peak <= 20_000 * 1.5, peak
            assert 0 < mgr.rows_indexed < 5000
            # release the pressure: consumption resumes to completion
            mgr.memory_budget_bytes = 0
            assert _wait(lambda: mgr.rows_indexed == 5000, timeout=15)
            assert not mgr.paused
            mgr.stop()
        finally:
            InMemoryStream.delete("bp_topic")

    def test_lag_ceiling_sheds_via_early_seal(self, tmp_path):
        """Over budget AND past the lag ceiling: the manager force-seals
        into the build pipeline instead of pausing indefinitely — rows
        keep flowing, bytes stay bounded."""
        topic = InMemoryStream("lg_topic", 1)
        try:
            tracker = IngestionDelayTracker()
            commits = []
            mgr, tdm = self._mgr(tmp_path, "lg_topic", budget=20_000,
                                 lag_pause_ms=1.0, tracker=tracker,
                                 commits=commits)
            old_ts = int(time.time() * 1000) - 60_000  # 60s behind
            for i in range(4000):
                topic.publish({"id": i, "name": "n" * 10, "score": 1.0},
                              ts_ms=old_ts)
            mgr.start()
            assert _wait(lambda: mgr.rows_indexed == 4000, timeout=30), \
                mgr.rows_indexed
            assert len(commits) >= 1, "lag ceiling never shed a seal"
            mgr.stop(drain=True)
            assert _count_rows(tdm) == 4000
        finally:
            InMemoryStream.delete("lg_topic")

    def test_manual_pause_resume(self, tmp_path):
        topic = InMemoryStream("mp_topic", 1)
        try:
            mgr, _tdm = self._mgr(tmp_path, "mp_topic", budget=0)
            mgr.pause()
            for i in range(50):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            time.sleep(0.3)
            assert mgr.rows_indexed == 0 and mgr.paused
            mgr.resume()
            assert _wait(lambda: mgr.rows_indexed == 50, timeout=10)
            mgr.stop()
        finally:
            InMemoryStream.delete("mp_topic")


class TestDelayTracker:
    def test_remove_partition_and_clock_skew_clamp(self):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("test")
        t = IngestionDelayTracker(metrics=m, labels={"instance": "s0"})
        now = int(time.time() * 1000)
        t.record(0, now - 5000)
        assert t.delay_ms(0) == pytest.approx(5000, abs=2000)
        # clock skew: an event stamped in the future clamps to zero lag,
        # never negative
        t.record(1, now + 60_000)
        assert 0.0 <= t.delay_ms(1) < 1000
        assert t.partitions() == [0, 1]
        assert t.max_delay_ms() >= 3000
        # a stopped/reassigned partition stops reporting, and its
        # labeled gauge series leaves /metrics entirely (ISSUE 14) —
        # a zeroed ghost series would still render forever
        t.remove_partition(0)
        assert t.delay_ms(0) is None
        assert t.partitions() == [1]
        assert m.gauge("ingestion_delay_ms",
                       {"instance": "s0", "partition": "0"}) is None
        assert 'partition="0"' not in m.prometheus_text()


@pytest.mark.chaos
class TestIngestSiteReplay:
    """Same-seed decision journals replay byte-identical across the NEW
    ingest failpoint sites (ingest.seal.build / ingest.seal.swap /
    ingest.checkpoint) — the chaos-marker suite entry that keeps the
    PR-3 determinism bar CI-enforced as ingestion grew."""

    def _run(self, tmp_path, tag, seed):
        topic_name = f"sr_topic_{tag}"
        topic = InMemoryStream(topic_name, 1)
        fps = [
            failpoints.arm("ingest.seal.build", delay=0.02,
                           probability=0.5, seed=seed),
            failpoints.arm("ingest.seal.swap",
                           error=FailpointError("swap chaos"),
                           probability=0.3, times=2, seed=seed + 1),
            failpoints.arm("ingest.checkpoint", torn=True,
                           probability=0.4, times=2, seed=seed + 2),
        ]
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic=topic_name,
                              flush_threshold_rows=40)
            mgr = RealtimeSegmentDataManager(
                TableConfig("rt", TableType.REALTIME), make_schema(), sc,
                0, tdm, str(tmp_path / tag),
                on_commit=lambda n, o: commits.append(str(o)))
            for i in range(200):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr.start()
            assert _wait(lambda: len(commits) == 5, timeout=30), commits
            mgr.stop(drain=True)
            assert _count_rows(tdm) == 200  # chaos cost retries, no rows
            return commits, [list(fp.decisions) for fp in fps]
        finally:
            for site in ("ingest.seal.build", "ingest.seal.swap",
                         "ingest.checkpoint"):
                failpoints.disarm(site)
            InMemoryStream.delete(topic_name)

    def test_same_seed_replays_byte_identical(self, tmp_path):
        c1, d1 = self._run(tmp_path, "a", seed=99)
        c2, d2 = self._run(tmp_path, "b", seed=99)
        assert d1 == d2, "same-seed ingest chaos journal diverged"
        assert c1 == c2  # and the observable outcome matches too


class TestIngestBenchSmoke:
    def test_ingest_bench_smoke(self, tmp_path):
        """The --ingest acceptance scenario at smoke scale (BENCH_groups
        pattern): mixed read/write load, freshness probe, seal windows,
        backpressure bound, seeded consumer kill + exactly-once
        convergence + journal replay — wired into tier-1. Writes to a
        temp path so the committed BENCH_ingest.json is never clobbered
        by CI."""
        import importlib
        import json
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_ingest_smoke.json")
        bench.ingest_main(smoke=True, out_path=out)
        with open(out) as f:
            report = json.load(f)
        assert report["failed_queries"] == 0
        assert report["chaos"]["failed_queries"] == 0
        assert report["chaos"]["converged"] is True
        assert report["chaos_replay_identical"] is True
        assert report["exact_count"][0] == report["exact_count"][1]


@pytest.mark.chaos
class TestIngestChaosKill:
    """SimulatedCrash mid-batch -> consumer vanishes -> restart from the
    committed offset + validDocIds snapshots -> exactly-once convergence.
    Seeded decisions replay byte-identical (the PR-3 chaos bar)."""

    N_PKS = 40
    N_EVENTS = 160

    def _run_leg(self, tmp_path, topic_name, seed):
        topic = InMemoryStream(topic_name, 1)
        fp = failpoints.arm("ingest.upsert.apply",
                            error=SimulatedCrash("kill"), times=1,
                            probability=0.35, seed=seed)
        schema = upsert_schema()
        tc = upsert_config()
        rng = np.random.default_rng(seed)
        events = []
        for ver in range(1, 1 + self.N_EVENTS // self.N_PKS):
            for pk in range(self.N_PKS):
                events.append({"pk": pk, "ver": ver,
                               "val": float(rng.integers(1, 100))})
        try:
            store = str(tmp_path / f"store_{seed}_{topic_name}")
            tdm = TableDataManager("u_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic=topic_name,
                              flush_threshold_rows=50)
            mgr = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm, store,
                on_commit=lambda n, o: commits.append((n, o)))
            for e in events:
                topic.publish(dict(e))
            mgr.start()
            # the seeded coin kills the consumer mid-batch
            assert _wait(lambda: mgr._crashed, timeout=20), \
                "chaos kill never fired"
            assert not mgr._thread.is_alive()
            killed_at = mgr.rows_indexed
            mgr.stop()  # joins the dead thread + flushes builds

            # restart exactly as a new server process would: fresh tdm
            # from the on-disk committed segments, resume from the MAX
            # committed offset, upsert state from persisted snapshots
            resume = max((int(str(o)) for _n, o in commits), default=0)
            tdm2 = TableDataManager("u_REALTIME")
            recovered = []
            if os.path.isdir(store):
                for name in sorted(os.listdir(store)):
                    path = os.path.join(store, name)
                    if os.path.isdir(path) and not name.startswith("_"):
                        seg = load_segment(path)
                        tdm2.add_segment(seg)
                        recovered.append(seg)
            mgr2 = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm2, store,
                start_offset=LongMsgOffset(resume),
                start_seq=len(recovered), recover_segments=recovered)
            mgr2.start()

            def converged():
                sdms = tdm2.acquire_segments()
                try:
                    ex = QueryExecutor([s.segment for s in sdms],
                                       use_tpu=False)
                    r = ex.execute(
                        "SELECT COUNT(*), SUM(val) FROM u LIMIT 5")
                    return r.rows[0]
                finally:
                    TableDataManager.release_all(sdms)

            # exactly-once: one visible row per pk, values = LAST version
            last = {}
            for e in events:
                last[e["pk"]] = e["val"]
            want = (self.N_PKS, pytest.approx(sum(last.values())))
            assert _wait(lambda: converged()[0] == want[0], timeout=20), \
                converged()
            time.sleep(0.3)  # no late duplicates
            got = converged()
            assert got[0] == want[0] and got[1] == want[1], (got, want)
            mgr2.stop()
            return killed_at, list(fp.decisions)
        finally:
            failpoints.disarm("ingest.upsert.apply")
            InMemoryStream.delete(topic_name)

    def test_kill_midbatch_exactly_once_and_seeded_replay(self, tmp_path):
        k1, d1 = self._run_leg(tmp_path, "ck_topic_a", seed=1234)
        k2, d2 = self._run_leg(tmp_path, "ck_topic_b", seed=1234)
        # the PR-3 bar: same seed -> byte-identical decision journal
        assert d1 == d2
        assert k1 == k2
