"""JSON index + text index: build, serde, json_match / text_match /
json_extract_scalar semantics.

Ref: pinot-segment-local readers/json/ImmutableJsonIndexReader.java,
readers/text/NativeTextIndexReader.java — VERDICT r3 item 6.
"""
import json

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.json_index import JsonIndex, extract_path, flatten
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.segment.text_index import TextIndex


class TestFlatten:
    def test_scalars_and_objects(self):
        recs = flatten({"a": 1, "b": {"c": "x"}})
        assert recs == [{"a": "1", "b.c": "x"}]

    def test_array_spawns_records(self):
        recs = flatten({"tags": ["x", "y"]})
        assert {r["tags[*]"] for r in recs} == {"x", "y"}
        assert any(r.get("tags[0]") == "x" for r in recs)

    def test_array_of_objects_and_semantics(self):
        # the reference's flattened-record AND semantics: x=1 AND y=2 must
        # hold within ONE array element
        doc = {"arr": [{"x": 1, "y": 2}, {"x": 3, "y": 4}]}
        recs = flatten(doc)
        both = [r for r in recs
                if r.get("arr[*].x") == "1" and r.get("arr[*].y") == "2"]
        assert both
        cross = [r for r in recs
                 if r.get("arr[*].x") == "1" and r.get("arr[*].y") == "4"]
        assert not cross


class TestJsonIndex:
    DOCS = [
        {"name": "adam", "age": 30, "addr": {"city": "ny"}},
        {"name": "bob", "age": 25, "tags": ["a", "b"]},
        {"name": "carl", "age": 30, "addr": {"city": "sf"}},
        {"name": "dave", "arr": [{"x": 1, "y": 2}, {"x": 3, "y": 4}]},
        {"name": "eve", "arr": [{"x": 1, "y": 4}]},
    ]

    def _index(self):
        vals = [json.dumps(d) for d in self.DOCS]
        return JsonIndex.build(vals, len(vals))

    def _match(self, idx, s):
        from pinot_tpu.query.filter import parse_filter_string
        return sorted(idx.matching_docs(parse_filter_string(s)).tolist())

    def test_equals(self):
        idx = self._index()
        assert self._match(idx, "\"$.name\" = 'bob'") == [1]
        assert self._match(idx, "\"$.addr.city\" = 'sf'") == [2]
        assert self._match(idx, "\"$.age\" = 30") == [0, 2]

    def test_array_contains(self):
        idx = self._index()
        assert self._match(idx, "\"$.tags[*]\" = 'a'") == [1]
        assert self._match(idx, "\"$.tags[0]\" = 'a'") == [1]
        assert self._match(idx, "\"$.tags[1]\" = 'a'") == []

    def test_and_within_flat_record(self):
        idx = self._index()
        # x=1 AND y=2 holds inside one element only for doc 3
        assert self._match(
            idx, "\"$.arr[*].x\" = 1 AND \"$.arr[*].y\" = 2") == [3]
        # x=1 AND y=4 holds within one element only for doc 4 (doc 3 has
        # them in DIFFERENT elements)
        assert self._match(
            idx, "\"$.arr[*].x\" = 1 AND \"$.arr[*].y\" = 4") == [4]

    def test_or_not_in_range(self):
        idx = self._index()
        assert self._match(
            idx, "\"$.name\" = 'bob' OR \"$.name\" = 'eve'") == [1, 4]
        assert self._match(idx, "\"$.age\" IN (25, 30)") == [0, 1, 2]
        assert self._match(idx, "\"$.age\" > 25") == [0, 2]
        assert self._match(idx, "\"$.age\" BETWEEN 20 AND 27") == [1]
        assert self._match(idx, "\"$.addr.city\" IS NOT NULL") == [0, 2]

    def test_serde_roundtrip(self):
        idx = self._index()
        rt = JsonIndex.from_bytes(idx.to_bytes())
        assert self._match(rt, "\"$.age\" = 30") == [0, 2]
        assert rt.num_docs == idx.num_docs

    def test_extract_path(self):
        d = {"a": {"b": [{"c": 5}]}}
        assert extract_path(d, "$.a.b[0].c") == 5
        assert extract_path(d, "$.a.b[1].c") is None
        assert extract_path(d, "$.missing") is None


class TestTextIndex:
    VALUES = [
        "Java is a distributed OLAP datastore",
        "realtime ingestion from kafka streams",
        "Apache Pinot supports JSON indexes",
        "distributed systems need consensus",
        None,
    ]

    def _index(self):
        return TextIndex.build(self.VALUES, len(self.VALUES))

    def test_terms_and_ops(self):
        idx = self._index()
        assert idx.matching_docs("distributed").tolist() == [0, 3]
        assert idx.matching_docs("distributed AND olap").tolist() == [0]
        assert idx.matching_docs("kafka OR consensus").tolist() == [1, 3]
        assert idx.matching_docs("distributed AND NOT olap").tolist() == [3]

    def test_case_insensitive(self):
        idx = self._index()
        assert idx.matching_docs("APACHE").tolist() == [2]

    def test_prefix(self):
        idx = self._index()
        assert idx.matching_docs("dist*").tolist() == [0, 3]
        assert idx.matching_docs("ind*").tolist() == [2]

    def test_phrase(self):
        idx = self._index()
        got = idx.matching_docs('"distributed olap"',
                                raw_values=self.VALUES)
        assert got.tolist() == [0]
        # same words, wrong order -> no match
        got = idx.matching_docs('"olap distributed"',
                                raw_values=self.VALUES)
        assert got.tolist() == []

    def test_serde(self):
        rt = TextIndex.from_bytes(self._index().to_bytes())
        assert rt.matching_docs("pinot").tolist() == [2]


# ---------------------------------------------------------------------------
# end-to-end: SQL through segments with the indexes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def seg_ex(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("jsontext")
    n = 200
    rng = np.random.default_rng(5)
    cities = ["ny", "sf", "la", "chi"]
    docs, logs = [], []
    for i in range(n):
        docs.append(json.dumps({
            "id": i, "city": cities[i % 4],
            "skills": [f"s{i % 5}", f"s{(i + 1) % 5}"],
            "score": int(rng.integers(0, 100))}))
        logs.append(f"request {i} served from node{i % 3} "
                    f"{'ERROR timeout' if i % 10 == 0 else 'OK fast'}")
    schema = Schema("t", [
        FieldSpec("j", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("log", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.json_index_columns = ["j"]
    tc.indexing.text_index_columns = ["log"]
    tc.indexing.no_dictionary_columns = ["v"]
    creator = SegmentCreator(tc, schema)
    d = str(tmp / "seg")
    creator.build({"j": np.array(docs, object),
                   "log": np.array(logs, object),
                   "v": np.arange(n, dtype=np.int32)}, d, "t_0")
    seg = load_segment(d)
    return QueryExecutor([seg], use_tpu=False), docs, logs, seg


class TestSqlIntegration:
    def test_indexes_on_disk(self, seg_ex):
        _ex, _docs, _logs, seg = seg_ex
        assert seg.data_source("j").json_index is not None
        assert seg.data_source("log").text_index is not None

    def test_json_match_sql(self, seg_ex):
        ex, docs, _logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT COUNT(*) FROM t WHERE "
            "JSON_MATCH(j, '\"$.city\" = ''sf''')")
        assert not resp.exceptions, resp.exceptions
        want = sum(1 for d in docs if json.loads(d)["city"] == "sf")
        assert resp.result_table.rows[0][0] == want

    def test_json_match_array_sql(self, seg_ex):
        ex, docs, _logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT COUNT(*) FROM t WHERE "
            "JSON_MATCH(j, '\"$.skills[*]\" = ''s2''')")
        assert not resp.exceptions, resp.exceptions
        want = sum(1 for d in docs if "s2" in json.loads(d)["skills"])
        assert resp.result_table.rows[0][0] == want

    def test_text_match_sql(self, seg_ex):
        ex, _docs, logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT COUNT(*) FROM t WHERE TEXT_MATCH(log, 'error')")
        assert not resp.exceptions, resp.exceptions
        want = sum(1 for line in logs if "ERROR" in line)
        assert resp.result_table.rows[0][0] == want

    def test_text_match_and_sql(self, seg_ex):
        ex, _docs, logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT COUNT(*) FROM t WHERE "
            "TEXT_MATCH(log, 'node1 AND error')")
        assert not resp.exceptions, resp.exceptions
        want = sum(1 for line in logs
                   if "node1" in line and "ERROR" in line)
        assert resp.result_table.rows[0][0] == want

    def test_json_extract_scalar_sql(self, seg_ex):
        ex, docs, _logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT SUM(JSON_EXTRACT_SCALAR(j, '$.score', 'INT')) FROM t")
        assert not resp.exceptions, resp.exceptions
        want = sum(json.loads(d)["score"] for d in docs)
        assert resp.result_table.rows[0][0] == want

    def test_json_extract_scalar_group_by(self, seg_ex):
        ex, docs, _logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT JSON_EXTRACT_SCALAR(j, '$.city', 'STRING') AS c, "
            "COUNT(*) FROM t "
            "GROUP BY JSON_EXTRACT_SCALAR(j, '$.city', 'STRING') "
            "ORDER BY c LIMIT 10")
        assert not resp.exceptions, resp.exceptions
        want = {}
        for d in docs:
            c = json.loads(d)["city"]
            want[c] = want.get(c, 0) + 1
        got = {r[0]: r[1] for r in resp.result_table.rows}
        assert got == want

    def test_combined_with_regular_filter(self, seg_ex):
        ex, docs, _logs, _seg = seg_ex
        resp = ex.execute(
            "SELECT COUNT(*) FROM t WHERE v < 100 AND "
            "JSON_MATCH(j, '\"$.city\" = ''ny''')")
        assert not resp.exceptions, resp.exceptions
        want = sum(1 for i, d in enumerate(docs)
                   if i < 100 and json.loads(d)["city"] == "ny")
        assert resp.result_table.rows[0][0] == want


class TestAdviceR4Fixes:
    """Regression tests for advisor round-4 findings."""

    def test_nested_array_flatten_is_linear_not_cartesian(self):
        # ADVICE r4: two chained traversals per array element squared the
        # record count and mixed values from different elements
        recs = flatten({"a": [{"b": [1, 2]}]})
        assert len(recs) == 2
        # every record is internally consistent: [*] value == indexed value
        for r in recs:
            star = r["a[*].b[*]"]
            indexed = [v for k, v in r.items()
                       if "[0]" in k or "[1]" in k]
            assert all(v == star for v in indexed), r

    def test_nested_array_conjunction_no_false_positive(self):
        docs = [json.dumps({"a": [{"b": [1]}, {"b": [2]}]})]
        idx = JsonIndex.build(docs, 1)
        from pinot_tpu.query.filter import parse_filter_string
        # 1 and 2 live in different elements of a: a conjunction over
        # [*].b[*] must NOT match within one flat record
        expr = parse_filter_string('"a[*].b[*]" = 1 AND "a[*].b[*]" = 2')
        assert idx.matching_docs(expr).tolist() == []

    def test_text_not_is_prohibited_clause(self):
        vals = ["apple pie", "apple tart", "cherry pie", "banana split"]
        ix = TextIndex.build(vals, 4)
        assert ix.matching_docs("apple NOT pie", vals).tolist() == [1]
        assert ix.matching_docs("apple AND NOT pie", vals).tolist() == [1]
        assert ix.matching_docs("NOT pie", vals).tolist() == [1, 3]
        assert ix.matching_docs("NOT apple AND pie", vals).tolist() == [2]
        # positive-only behavior is unchanged (implicit OR)
        assert ix.matching_docs("apple pie", vals).tolist() == [0, 1, 2]
