"""Unified kernel factory: cross-table shape-bucketed batching +
vmapped sharded kernels (ISSUE 9).

Pins the tentpole properties deterministically:

  * cross-table coalescing — fingerprint-equal queries over DIFFERENT
    tables whose segment/doc counts pad into the same (S, D) bucket
    share ONE launch (column blocks stacked along a leading batch axis),
    BIT-IDENTICAL to per-query execution (property-tested over random
    literal sets and random member->table assignments)
  * doc-sharded mesh batching — multi-device engines no longer fall off
    the batching path: the factory vmaps INSIDE shard_map (batch axis
    innermost, mesh axes outermost, one set of psum collectives per
    batch), same bit-identity bar, same-table AND cross-table
  * batch-member fault isolation — the `server.dispatch.batch`
    failpoint fires per member inside the coalesced path; an erroring
    member fails only its own future while peers complete, and the
    seeded decision journal replays byte-identical
  * compile observability — `kernels.trace_log()` attributes every
    compile to (kind, plan fingerprint, shape bucket) and the
    `kernel_retrace` meter carries a per-plan label
  * steady state — warmed cross-table traffic compiles NOTHING

Determinism trick (same as test_dispatch.py): a one-shot delay
failpoint on server.dispatch.before holds the ring on the first pop
while the remaining threads enqueue, so batch composition is exact.
"""
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import jax

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import FailpointError, failpoints

HOLD_S = 0.3


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def build_table(tmp_path, name, num_segments, docs, seed):
    """One table's segment batch: same schema SHAPE as every other
    table here (so plans fingerprint-equal), its own data and doc
    count (so buckets must do the matching)."""
    schema = Schema(name, [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC)])
    tc = TableConfig(name, TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(seed)
    out = []
    for i in range(num_segments):
        cols = {"d": rng.integers(0, 10, docs).astype(np.int32),
                "m": rng.integers(0, 100, docs).astype(np.int32)}
        p = str(tmp_path / f"{name}_{i}")
        creator.build(cols, p, f"{name}_{i}")
        out.append(load_segment(p))
    return out


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    """Three tables, mixed doc counts in the SAME pow2 doc bucket
    (4096) and segment counts that pad into one S bucket — the
    mixed-table dashboard fleet."""
    tmp = tmp_path_factory.mktemp("xtab")
    return {
        "t1": build_table(tmp, "t1", 3, 3000, 1),
        "t2": build_table(tmp, "t2", 4, 2500, 2),
        "t3": build_table(tmp, "t3", 3, 3900, 3),
    }


def make_engine(**overrides):
    return TpuOperatorExecutor(config=PinotConfiguration(overrides=overrides))


def agg_values(results):
    out = []
    for r in results:
        if hasattr(r, "groups"):
            out.append(tuple(sorted(
                (k, tuple(float(v) for v in inters))
                for k, inters in r.groups.items())))
        else:
            out.append(tuple(float(v) for v in r.intermediates))
    return tuple(out)


def run_concurrent(eng, jobs, hold=HOLD_S):
    """jobs: [(segments, ctx), ...] executed concurrently with the ring
    held on the first pop so batch composition is deterministic."""
    failpoints.arm("server.dispatch.before", delay=hold, times=2)
    try:
        with ThreadPoolExecutor(len(jobs)) as pool:
            futs = [pool.submit(eng.execute, s, c) for s, c in jobs]
            return [f.result() for f in futs]
    finally:
        failpoints.disarm("server.dispatch.before")


class TestCrossTableBatching:
    def test_cross_table_coalesce_bit_identical(self, tables):
        eng = make_engine()
        jobs = []
        for i, tn in enumerate(["t1", "t2", "t3", "t1", "t2", "t3"]):
            jobs.append((tables[tn], QueryContext.from_sql(
                f"SELECT SUM(m), COUNT(*), MIN(m) FROM {tn} "
                f"WHERE d < {i + 2}")))
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        got = run_concurrent(eng, jobs)
        assert all(not rem for _r, rem in got)
        assert [agg_values(r) for r, _rem in got] == singles
        # a STACKED (cross-table) batch actually formed — not six
        # serialized singles, not a same-batch broadcast
        reg = eng._dispatcher._metrics
        assert reg.meter("dispatch_batch_cross_table") > 0

    def test_bit_identical_property_random_tables_and_literals(self, tables):
        """Property: ANY member->table assignment with ANY literal set,
        coalesced in ANY composition, equals per-query execution."""
        eng = make_engine()
        rng = np.random.default_rng(31)
        names = list(tables)
        for _trial in range(3):
            k = int(rng.integers(3, 8))
            picks = [names[j] for j in rng.integers(0, len(names), k)]
            bounds = rng.integers(0, 100, size=(k, 2))
            jobs = [(tables[tn], QueryContext.from_sql(
                "SELECT SUM(m), COUNT(*), MAX(m) FROM x "
                f"WHERE m BETWEEN {min(a, b)} AND {max(a, b)} AND d < 8"))
                for tn, (a, b) in zip(picks, bounds)]
            singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
            got = run_concurrent(eng, jobs)
            assert [agg_values(r) for r, _rem in got] == singles

    def test_group_by_cross_table_bit_identical(self, tables):
        eng = make_engine()
        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT d, SUM(m) FROM x WHERE m BETWEEN {a} AND {a + 40} "
            "GROUP BY d"))
            for tn, a in (("t1", 0), ("t3", 10), ("t1", 20), ("t3", 30))]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        got = run_concurrent(eng, jobs)
        assert [agg_values(r) for r, _rem in got] == singles

    def test_cross_table_disabled_keeps_same_batch_key(self, tables):
        """The escape hatch: cross.table=false restores PR-4 semantics —
        different tables never share a launch (no stacked batches), but
        results are still correct."""
        eng = make_engine(**{
            "pinot.server.dispatch.batch.cross.table": False})
        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM x WHERE d < {i + 2}"))
            for i, tn in enumerate(["t1", "t2", "t1", "t2"])]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        # the registry is process-global: assert the DELTA stays zero
        m0 = eng._dispatcher._metrics.meter("dispatch_batch_cross_table")
        got = run_concurrent(eng, jobs)
        assert [agg_values(r) for r, _rem in got] == singles
        assert eng._dispatcher._metrics.meter(
            "dispatch_batch_cross_table") == m0

    def test_steady_state_cross_table_zero_retrace(self, tables):
        """Warmed mixed-table traffic (singles + stacked batches over
        warmed shape buckets) compiles NOTHING — the acceptance bar the
        bench asserts under load, pinned here deterministically."""
        eng = make_engine()

        def round_of(base):
            jobs = [(tables[tn], QueryContext.from_sql(
                "SELECT SUM(m), COUNT(*) FROM x "
                f"WHERE d < {base + i}"))
                for i, tn in enumerate(
                    ["t1", "t2", "t3", "t1", "t2", "t3", "t1", "t2"])]
            got = run_concurrent(eng, jobs)
            assert all(not rem for _r, rem in got)

        for tn in tables:  # warm singles (stage + compile per table)
            eng.execute(tables[tn], QueryContext.from_sql(
                "SELECT SUM(m), COUNT(*) FROM x WHERE d < 1"))
        round_of(0)   # warm the batched bucket shapes
        round_of(1)   # a second composition (partial-pad variants)
        before = kernels.trace_count()
        round_of(2)
        round_of(3)
        for tn in tables:
            eng.execute(tables[tn], QueryContext.from_sql(
                "SELECT SUM(m), COUNT(*) FROM x WHERE d < 5"))
        assert kernels.trace_count() == before, \
            "steady-state cross-table traffic re-compiled a kernel"


@pytest.fixture(scope="module")
def mesh_engine():
    """A (segments x docs) mesh over 2+2 devices: the doc-sharded path
    that PR 4 excluded from batching entirely."""
    mesh = make_mesh(jax.devices()[:4], doc_axis=2)
    return TpuOperatorExecutor(mesh=mesh, config=PinotConfiguration())


class TestMeshBatching:
    def test_doc_sharded_same_table_batches_bit_identical(
            self, tables, mesh_engine):
        eng = mesh_engine
        jobs = [(tables["t1"], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*), MIN(m) FROM t1 WHERE d < {k}"))
            for k in range(1, 7)]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        reg = eng._dispatcher._metrics
        max0 = reg.timer("dispatch_batch_size").max_ms
        got = run_concurrent(eng, jobs)
        assert all(not rem for _r, rem in got)
        assert [agg_values(r) for r, _rem in got] == singles
        # the sharded path actually batched (vmap inside shard_map)
        assert reg.timer("dispatch_batch_size").max_ms >= max(max0, 2)

    def test_doc_sharded_cross_table_batches_bit_identical(
            self, tables, mesh_engine):
        eng = mesh_engine
        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM x WHERE d < {i + 2}"))
            for i, tn in enumerate(["t1", "t3", "t1", "t3"])]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        got = run_concurrent(eng, jobs)
        assert [agg_values(r) for r, _rem in got] == singles

    def test_doc_sharded_steady_state_zero_retrace(self, tables,
                                                   mesh_engine):
        eng = mesh_engine

        def round_of(base):
            jobs = [(tables["t1"], QueryContext.from_sql(
                f"SELECT SUM(m), COUNT(*) FROM t1 WHERE d < {base + k}"))
                for k in range(6)]
            got = run_concurrent(eng, jobs)
            assert all(not rem for _r, rem in got)

        eng.execute(tables["t1"], QueryContext.from_sql(
            "SELECT SUM(m), COUNT(*) FROM t1 WHERE d < 1"))
        round_of(0)
        round_of(1)
        before = kernels.trace_count()
        round_of(2)
        round_of(3)
        assert kernels.trace_count() == before, \
            "steady-state mesh traffic re-compiled a kernel"


class TestBatchChaos:
    def test_one_erroring_member_fails_only_its_future(self, tables):
        """server.dispatch.batch fires per member inside the coalesced
        path: with a one-shot error armed, exactly one of four batched
        queries fails and the three peers complete bit-identically."""
        eng = make_engine()
        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM x WHERE d < {i + 2}"))
            for i, tn in enumerate(["t1", "t2", "t1", "t2"])]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        failpoints.arm("server.dispatch.before", delay=HOLD_S, times=2)
        failpoints.arm("server.dispatch.batch",
                       error=FailpointError("member chaos"), times=1)
        try:
            with ThreadPoolExecutor(len(jobs)) as pool:
                futs = [pool.submit(eng.execute, s, c) for s, c in jobs]
                outcomes = []
                for i, f in enumerate(futs):
                    try:
                        res, rem = f.result()
                        assert not rem
                        assert agg_values(res) == singles[i]
                        outcomes.append("ok")
                    except FailpointError:
                        outcomes.append("chaos")
        finally:
            failpoints.disarm("server.dispatch.before")
            failpoints.disarm("server.dispatch.batch")
        assert outcomes.count("chaos") == 1, outcomes
        assert outcomes.count("ok") == len(jobs) - 1
        # the ring is fully recovered: peers re-execute cleanly
        for (s, c), want in zip(jobs, singles):
            assert agg_values(eng.execute(s, c)[0]) == want

    #: ring hold for THIS test: journal identity across rounds requires
    #: identical batch formations, so stragglers must make the window
    #: even on a loaded CI box (0.3s proved marginal under full-suite
    #: contention — a late 4th member changes the per-member fire count)
    CHAOS_HOLD_S = 0.75

    def test_seeded_batch_chaos_replays_exactly(self, tables):
        """Same seed -> byte-identical decision journal across rounds,
        with surviving members always bit-identical to per-query."""
        eng = make_engine()
        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*) FROM x WHERE d < {i + 2}"))
            for i, tn in enumerate(["t1", "t2", "t3", "t1"])]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]

        # pre-warm the BATCHED kernels for this formation (chaos off):
        # round 1 otherwise pays the jit trace mid-window while round 2
        # runs cached — asymmetric timing that can split formations
        failpoints.arm("server.dispatch.before",
                       delay=self.CHAOS_HOLD_S, times=2)
        try:
            with ThreadPoolExecutor(len(jobs)) as pool:
                for f in [pool.submit(eng.execute, s, c)
                          for s, c in jobs]:
                    f.result()
        finally:
            failpoints.disarm("server.dispatch.before")

        def run_round():
            fp = failpoints.arm("server.dispatch.batch",
                                error=FailpointError("batch chaos"),
                                probability=0.5, seed=4242)
            failed = 0
            try:
                for _ in range(3):
                    failpoints.arm("server.dispatch.before",
                                   delay=self.CHAOS_HOLD_S, times=2)
                    try:
                        with ThreadPoolExecutor(len(jobs)) as pool:
                            futs = [pool.submit(eng.execute, s, c)
                                    for s, c in jobs]
                            for i, f in enumerate(futs):
                                try:
                                    res, _rem = f.result()
                                    assert agg_values(res) == singles[i]
                                except FailpointError:
                                    failed += 1
                    finally:
                        failpoints.disarm("server.dispatch.before")
            finally:
                failpoints.disarm("server.dispatch.batch")
            return failed, list(fp.decisions)

        f1, d1 = run_round()
        f2, d2 = run_round()
        assert d1 == d2, "same-seed batch chaos journals diverged"
        assert f1 == f2
        assert f1 > 0, "chaos never fired"


class TestCompileObservability:
    def test_trace_log_attributes_compiles(self, tables):
        eng = make_engine()
        ctx = QueryContext.from_sql(
            "SELECT SUM(m), COUNT(*), MAX(m) FROM t2 WHERE d < 3 AND m < 7")
        seq0 = kernels.trace_count()
        eng.execute(tables["t2"], ctx)
        entries = [e for e in kernels.trace_log() if e["seq"] > seq0]
        assert entries, "compile left no trace-log entry"
        prep = eng._prepare_agg(tables["t2"], ctx)
        fp = kernels.plan_fingerprint(prep[0])
        mine = [e for e in entries if e["plan"] == fp]
        assert mine, f"no entry for plan {fp}: {entries}"
        # bucket carries the shape key: (..., S, D, G)
        assert mine[-1]["bucket"][-2:] == (4096, 0)
        assert mine[-1]["kind"] in (
            "agg", "sharded", "batched", "batched_stacked")

    def test_kernel_retrace_meter_has_plan_label(self, tables):
        eng = make_engine()
        ctx = QueryContext.from_sql(
            "SELECT SUM(m), MIN(m), MAX(m) FROM t3 WHERE m < 42 AND d < 9")
        eng.execute(tables["t3"], ctx)
        prep = eng._prepare_agg(tables["t3"], ctx)
        fp = kernels.plan_fingerprint(prep[0])
        reg = eng._dispatcher._metrics
        # attribution is a SEPARATE series so the aggregate stays summable
        assert reg.meter("kernel_retrace_by_plan", labels={"plan": fp}) > 0
        assert reg.meter("kernel_retrace") > 0  # unlabelled total intact
        assert kernels.trace_count_by_plan().get(fp, 0) > 0


# tier-1 smoke of the acceptance driver
class TestBatchingBenchSmoke:
    def test_batching_bench_smoke(self, tmp_path):
        """The --batching acceptance scenario at smoke scale: mixed
        tables + a doc-sharded mesh engine, unified factory vs
        serialized mode, zero steady-state retraces asserted inside."""
        import importlib
        import json
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_batching_smoke.json")
        bench.batching_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["mixed_table"]["unified"]["retraces_steady"] == 0
        assert data["doc_sharded"]["unified"]["retraces_steady"] == 0
