"""Multi-chip scale-out acceptance suite (ISSUE 19).

The conftest forces an 8-device virtual CPU platform; engines here get
explicit (segments x docs) meshes so the collective broker merge
(ops/collective.py) is the path under test: per-segment partials fold
ON DEVICE — one psum/pmin/pmax over the whole mesh — instead of being
shipped to the host IndexedTable fold. Covered:

  * real-SQL parity vs the host executor on 1x1 / 2x2 / 4x2 meshes;
  * property test: merged rows are BIT-IDENTICAL to the escape hatch
    (`pinot.server.mesh.collective.merge=false`, the host fold) across
    randomized agg/group-by/filter shapes — integer columns under the
    test suite's x64 staging make exact equality legitimate;
  * zero steady-state retraces across repeated merged launches;
  * per-chip residency observability: `hbm_cache_bytes{device=}` /
    `hbm_resident_bytes{device=}` gauges and the /debug/health rollup;
  * per-chip admission: a skewed mesh rejects on the MOST-LOADED chip
    while the pooled number still looks healthy;
  * the `server.mesh.collective` failpoint: armed errors fall back to
    the host fold (mesh_merge_fallback{reason=chaos}) with correct
    rows, and same-seed decision journals replay byte-identical;
  * `bench.py --mesh --smoke` end to end (BENCH_mesh.json contract).
"""
import json

import numpy as np
import pytest

import jax

from pinot_tpu.ops import kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.ops.residency import ResidencyManager
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.server.admission import AdmissionController
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import FailpointError, failpoints
from tests.queries.harness import (
    build_segments, synthetic_columns, synthetic_schema,
    synthetic_table_config)

NUM_DOCS = 700  # not a power of two: padding must mask right
#: (total devices, doc axis) -> 1x1, 2x2, 4x2 (segments x docs)
MESH_SHAPES = [(1, 1), (4, 2), (8, 2)]


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    tmp = tmp_path_factory.mktemp("mesh_scaling")
    data = [synthetic_columns(NUM_DOCS, seed=131 + i) for i in range(6)]
    return build_segments(tmp, synthetic_schema(), synthetic_table_config(),
                          data)


@pytest.fixture(scope="module")
def host(segs):
    return QueryExecutor(segs, use_tpu=False)


def _mesh_engine(n, doc_axis, labels=None, **overrides):
    cfg = PinotConfiguration(overrides=overrides) if overrides else None
    mesh = make_mesh(jax.devices()[:n], doc_axis=doc_axis)
    return TpuOperatorExecutor(mesh=mesh, config=cfg,
                               metrics_labels=labels)


def _assert_parity(dr, hr, exact=False):
    assert not dr.exceptions and not hr.exceptions, (
        dr.exceptions, hr.exceptions)
    assert len(dr.rows) == len(hr.rows), (dr.rows, hr.rows)
    for a, b in zip(dr.rows, hr.rows):
        for x, y in zip(a, b):
            if exact or not (isinstance(x, float) or isinstance(y, float)):
                assert x == y, (dr.rows, hr.rows)
            else:
                assert abs(float(x) - float(y)) <= \
                    1e-5 * max(1.0, abs(float(y))), (dr.rows, hr.rows)


PARITY_SQLS = [
    "SELECT SUM(intCol), COUNT(*), MIN(intCol), MAX(intCol) "
    "FROM testTable WHERE intCol > 250",
    "SELECT SUM(intCol * rawIntCol), AVG(intCol) FROM testTable "
    "WHERE stringCol IN ('s1', 's4', 's8') AND intCol < 800",
    "SELECT groupCol, COUNT(*), SUM(intCol), MIN(rawIntCol) "
    "FROM testTable GROUP BY groupCol ORDER BY groupCol LIMIT 50",
    "SELECT stringCol, groupCol, COUNT(*), MAX(intCol) FROM testTable "
    "GROUP BY stringCol, groupCol ORDER BY COUNT(*) DESC, stringCol, "
    "groupCol LIMIT 25",
]


class TestMeshParity:
    """Real SQL, every mesh geometry, parity vs the host executor."""

    @pytest.mark.parametrize("n,doc_axis", MESH_SHAPES)
    def test_sql_parity(self, segs, host, n, doc_axis):
        engine = _mesh_engine(n, doc_axis)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        for sql in PARITY_SQLS:
            _assert_parity(device.execute(sql), host.execute(sql))
        if n > 1:
            reg = engine._dispatcher._metrics
            assert reg.meter("mesh_merge_served") > 0, \
                "multi-chip parity queries never took the merged path"


def _random_sql(rng):
    """A random agg/group-by/filter shape over the integer columns —
    integer data + x64 staging keep every aggregate exactly
    representable, so merged-vs-host-fold comparison is == not ~=."""
    aggs = list(rng.choice(
        ["SUM(intCol)", "COUNT(*)", "MIN(intCol)", "MAX(rawIntCol)",
         "SUM(rawIntCol)", "AVG(intCol)", "SUM(intCol * rawIntCol)",
         "MIN(rawIntCol)", "MAX(intCol)"],
        size=rng.integers(1, 4), replace=False))
    filters = ["", " WHERE intCol > %d" % rng.integers(0, 900),
               " WHERE rawIntCol BETWEEN %d AND %d" % (
                   rng.integers(0, 40), rng.integers(50, 120)),
               " WHERE stringCol IN ('s1', 's5') AND intCol < %d"
               % rng.integers(200, 1000)]
    where = filters[rng.integers(0, len(filters))]
    group = ["", "groupCol", "stringCol", "stringCol, groupCol"][
        rng.integers(0, 4)]
    if group:
        sql = (f"SELECT {group}, {', '.join(aggs)} FROM testTable"
               f"{where} GROUP BY {group} ORDER BY {group} LIMIT 200")
    else:
        sql = f"SELECT {', '.join(aggs)} FROM testTable{where}"
    return sql


class TestCollectiveBitParity:
    """The merged collective vs the host-fold escape hatch: same rows,
    BIT-identical, across randomized query shapes."""

    def test_property_merged_equals_host_fold(self, segs):
        eng_on = _mesh_engine(8, 2, labels={"leg": "bp_on"})
        eng_off = _mesh_engine(
            8, 2, labels={"leg": "bp_off"},
            **{"pinot.server.mesh.collective.merge": False})
        ex_on = QueryExecutor(segs, use_tpu=True, engine=eng_on)
        ex_off = QueryExecutor(segs, use_tpu=True, engine=eng_off)
        rng = np.random.default_rng(20260807)
        for _ in range(12):
            sql = _random_sql(rng)
            r_on = ex_on.execute(sql)
            r_off = ex_off.execute(sql)
            assert not r_on.exceptions and not r_off.exceptions, (
                sql, r_on.exceptions, r_off.exceptions)
            assert r_on.rows == r_off.rows, (
                f"merged path diverged from host fold: {sql}: "
                f"{r_on.rows} vs {r_off.rows}")
        # the registry is process-global: scope reads by each engine's
        # label so the two engines' counters stay distinguishable
        reg = eng_on._dispatcher._metrics
        assert reg.meter("mesh_merge_served",
                         labels={"leg": "bp_on"}) > 0
        # the escape hatch is a REAL knob: the off engine metered every
        # eligible query as a disabled-reason fallback
        assert reg.meter("mesh_merge_fallback",
                         labels={"leg": "bp_off",
                                 "reason": "disabled"}) > 0
        assert reg.meter("mesh_merge_served",
                         labels={"leg": "bp_off"}) == 0


class TestZeroRetrace:
    def test_steady_state_merged_launches_never_retrace(self, segs):
        engine = _mesh_engine(8, 2)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        warm = [
            "SELECT SUM(intCol), COUNT(*) FROM testTable WHERE intCol > 100",
            "SELECT groupCol, COUNT(*), SUM(intCol) FROM testTable "
            "WHERE intCol > 100 GROUP BY groupCol "
            "ORDER BY groupCol LIMIT 50",
        ]
        for sql in warm:
            device.execute(sql)
        traces0 = kernels.trace_count()
        # same plan shapes, fresh filter constants: params change,
        # the compiled merged kernel must not
        for lo in (150, 300, 450, 600):
            device.execute(
                f"SELECT SUM(intCol), COUNT(*) FROM testTable "
                f"WHERE intCol > {lo}")
            device.execute(
                f"SELECT groupCol, COUNT(*), SUM(intCol) FROM testTable "
                f"WHERE intCol > {lo} GROUP BY groupCol "
                f"ORDER BY groupCol LIMIT 50")
        assert kernels.trace_count() == traces0, \
            "steady-state retrace on the merged path"


class TestPerChipObservability:
    def test_per_device_gauges_emitted(self, segs):
        engine = _mesh_engine(8, 2)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        device.execute("SELECT SUM(intCol), COUNT(*) FROM testTable "
                       "WHERE intCol > 100")
        reg = engine._dispatcher._metrics
        # pooled gauge stays (dashboards keyed on it keep working) ...
        assert reg.gauge("hbm_cache_bytes") is not None
        # ... and every chip gets its own split under a device= label
        labels = [f"{d.platform}:{d.id}" for d in engine.devices]
        assert len(labels) == 8
        for lab in labels:
            assert reg.gauge("hbm_cache_bytes",
                             labels={"device": lab}) is not None, lab
            assert reg.gauge("hbm_resident_bytes",
                             labels={"device": lab}) is not None, lab
        # resident rows were committed to specific chips — the split is
        # real attribution, not an even smear
        by_dev = engine._residency.bytes_by_device()
        assert sum(by_dev.values()) == engine._residency.bytes
        assert sum(reg.gauge("hbm_resident_bytes", labels={"device": lab})
                   for lab in labels) == engine._residency.bytes

    def test_health_rollup_reports_max_device(self, segs):
        from pinot_tpu.health.rollup import role_health_summary
        engine = _mesh_engine(8, 2)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        device.execute("SELECT SUM(intCol), COUNT(*) FROM testTable "
                       "WHERE intCol > 100")
        out = role_health_summary(
            "server", registry=engine._dispatcher._metrics)
        hbm = out["subsystems"]["hbm"]
        assert hbm["ok"] and hbm["totalBytes"] > 0
        assert hbm["maxDevice"] in {f"{d.platform}:{d.id}"
                                    for d in engine.devices}
        assert hbm["maxDeviceBytes"] == \
            max(hbm["perDeviceBytes"].values())
        assert len(hbm["perDeviceBytes"]) == 8


class _FakeDev:
    def __init__(self, i):
        self.platform = "cpu"
        self.id = i


class _FakeSeg:
    def __init__(self, name):
        self.name = name


class TestSkewedMeshAdmission:
    """Per-chip budgeting: one hot chip trips admission long before the
    POOLED number looks full — the pooled view hides exactly the skew
    that OOMs a single chip."""

    def test_pressure_tracks_most_loaded_chip(self):
        rm = ResidencyManager(1000, admission=False,
                              devices=[_FakeDev(i) for i in range(4)])
        assert rm.device_budget_bytes == 250
        segs = [_FakeSeg(f"seg{i}") for i in range(4)]
        # skew: chip cpu:0 nearly full, others nearly empty
        assert rm.admit(segs[0], "fwd", "a", "i64", "row", 240,
                        device="cpu:0")
        assert rm.admit(segs[1], "fwd", "a", "i64", "row", 10,
                        device="cpu:1")
        # pooled fill is 25% — healthy; the max chip is at 96%
        assert rm.bytes == 250
        assert rm.max_device_bytes() == 240
        assert rm.pressure() == pytest.approx(240 / 250)

    def test_admission_rejects_on_skewed_chip(self):
        rm = ResidencyManager(1000, admission=False,
                              devices=[_FakeDev(i) for i in range(4)])
        rm.admit(_FakeSeg("s"), "fwd", "a", "i64", "row", 245,
                 device="cpu:0")
        ac = AdmissionController(num_threads=2, memory_threshold=0.95,
                                 memory_pressure_fn=rm.pressure)
        rej = ac.admit(table="t")
        assert rej is not None and "memory pressure" in str(rej)
        # drain the hot chip -> admission recovers
        rm.drop_all()
        ac._pressure_at = 0.0  # expire the memo
        assert ac.admit(table="t") is None

    def test_per_chip_share_evicts_only_that_chip(self):
        rm = ResidencyManager(1000, admission=False,
                              devices=[_FakeDev(i) for i in range(4)])
        keep = _FakeSeg("keep")
        rm.admit(keep, "fwd", "cold", "i64", "row", 200, device="cpu:1")
        victims = [_FakeSeg(f"v{i}") for i in range(3)]
        for i, s in enumerate(victims):
            rm.admit(s, "fwd", f"c{i}", "i64", "row", 100, device="cpu:0")
        # chip0 at 300/250 after this admit: ITS oldest rows evict;
        # chip1's resident row must survive untouched
        assert rm.admit(_FakeSeg("hot"), "fwd", "hot", "i64", "row", 100,
                        device="cpu:0")
        by_dev = rm.bytes_by_device()
        assert by_dev["cpu:1"] == 200
        assert by_dev["cpu:0"] <= rm.device_budget_bytes

    def test_oversized_row_declined_against_chip_share(self):
        rm = ResidencyManager(1000, admission=False,
                              devices=[_FakeDev(i) for i in range(4)])
        # fits the pooled budget, can never fit one chip's share
        assert not rm.admit(_FakeSeg("big"), "fwd", "big", "i64", "row",
                            400, device="cpu:0")
        assert rm.bytes == 0


class TestMeshCollectiveFailpoint:
    def test_armed_error_falls_back_to_host_fold(self, segs, host):
        engine = _mesh_engine(8, 2)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        sql = ("SELECT groupCol, COUNT(*), SUM(intCol) FROM testTable "
               "GROUP BY groupCol ORDER BY groupCol LIMIT 50")
        with failpoints.armed("server.mesh.collective",
                              error=FailpointError("mesh chaos")):
            _assert_parity(device.execute(sql), host.execute(sql))
        reg = engine._dispatcher._metrics
        assert reg.meter("mesh_merge_fallback",
                         labels={"reason": "chaos"}) > 0
        # disarmed: the merged path resumes on the SAME engine
        served0 = reg.meter("mesh_merge_served")
        _assert_parity(device.execute(sql), host.execute(sql))
        assert reg.meter("mesh_merge_served") > served0

    def test_same_seed_journals_replay_byte_identical(self, segs):
        engine = _mesh_engine(8, 2)
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        sqls = [f"SELECT SUM(intCol), COUNT(*) FROM testTable "
                f"WHERE intCol > {lo}" for lo in (100, 300, 500, 700)]

        def run():
            with failpoints.armed("server.mesh.collective",
                                  error=FailpointError("mesh chaos"),
                                  probability=0.5, seed=7) as fp:
                for sql in sqls:
                    r = device.execute(sql)
                    assert not r.exceptions, r.exceptions
                return json.dumps(fp.decisions).encode()

        j1, j2 = run(), run()
        assert j1 == j2, "same-seed chaos journals diverged"
        assert b"true" in j1, "the 0.5 coin never fired in 4 queries"


# tier-1 smoke of the acceptance driver
class TestMeshBenchSmoke:
    def test_mesh_bench_smoke(self, tmp_path):
        """The --mesh acceptance scenario at smoke scale: weak-scaling
        segments-axis leg + one-huge-segment doc-axis leg, merged
        collective A/B'd against the host fold, bit-parity and zero
        steady-state retraces asserted inside."""
        import importlib
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_mesh_smoke.json")
        bench.mesh_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["metric"] == "mesh_weak_scaling_efficiency"
        assert [p["devices"] for p in data["segments_axis"]] == [1, 2]
        for p in data["segments_axis"]:
            assert p["retraces_steady"] == 0
            assert p["rows_per_sec"] > 0
        assert data["segments_axis"][-1]["merge_served"] > 0
        assert data["doc_axis"]["segments"] == 1
        assert data["doc_axis"]["retraces_steady"] == 0


class TestMergeKnobAndContext:
    def test_single_device_mesh_never_merges(self, segs, host):
        """A 1-device engine has nothing to fold across — the merged
        branch must not engage (and must not meter a fallback: there
        was no mesh decision to make)."""
        engine = _mesh_engine(1, 1, labels={"leg": "one"})
        device = QueryExecutor(segs, use_tpu=True, engine=engine)
        _assert_parity(device.execute(PARITY_SQLS[0]),
                       host.execute(PARITY_SQLS[0]))
        reg = engine._dispatcher._metrics
        assert reg.meter("mesh_merge_served",
                         labels={"leg": "one"}) == 0
        assert reg.meter("mesh_merge_fallback",
                         labels={"leg": "one", "reason": "disabled"}) == 0
