"""Distributed minion task fabric (ISSUE 5).

Covers the lease-based scheduler and the fault-tolerant segment
lifecycle end to end:

  * queue mechanics — lease/renew/complete, lease expiry requeues
    EXACTLY once, capped exponential retry backoff, cancel semantics,
    journal reload resuming PENDING/LEASED tasks after a controller
    restart
  * MiniCluster(minions=N) integration — a purge task end to end on the
    tier-1 smoke path; merge-rollup swaps with cache coherence (broker
    whole-result + server partial caches miss on the new epoch, negative
    entries dropped, warmup replays logged plans before the swapped
    segment serves)
  * chaos — a minion killed mid-task (minion.task.execute failpoint)
    re-leases to a second worker and completes with the EXACT segment
    set a no-chaos run produces; same seed replays identically; a crash
    between upload and swap resumes from the commit manifest without
    re-executing
"""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.controller.task_manager import (
    CANCELLED, COMPLETED, FAILED, LEASED, PENDING, RUNNING,
    TaskManager, TaskQueue)
from pinot_tpu.controller.tasks import TaskConfig, task_token
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (FailpointError, FaultSchedule,
                                        SimulatedCrash, failpoints)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def make_schema():
    return Schema("ct", [
        FieldSpec("d", DataType.STRING),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])


def make_config():
    tc = TableConfig("ct")
    tc.retention.time_column = "ts"
    return tc


def build_seg(tmp, name, n=60, ts_base=0, seed=0):
    rng = np.random.default_rng(seed)
    cols = {"d": [f"k{v}" for v in rng.integers(0, 4, n)],
            "ts": (ts_base + np.arange(n)).astype(np.int64),
            "m": rng.integers(0, 100, n).astype(np.int64)}
    out = str(tmp / name)
    SegmentCreator(make_config(), make_schema()).build(cols, out, name)
    return out


# ---------------------------------------------------------------------------
# TaskQueue unit mechanics
# ---------------------------------------------------------------------------

class TestTaskQueue:
    def test_lease_lifecycle(self):
        q = TaskQueue(lease_ttl_s=5.0)
        e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["s0"]))
        assert e.state == PENDING
        got = q.lease("w0", ["PurgeTask"])
        assert got is not None and got.task_id == e.task_id
        assert got.state == LEASED and got.worker == "w0"
        r = q.renew(e.task_id, "w0", progress="executing")
        assert r == {"ok": True, "cancelled": False}
        assert q.get(e.task_id).state == RUNNING
        assert q.get(e.task_id).progress == "executing"
        assert q.complete(e.task_id, "w0", {"ok": 1})
        assert q.get(e.task_id).state == COMPLETED

    def test_lease_filters_task_types(self):
        q = TaskQueue()
        q.submit(TaskConfig("MergeRollupTask", "ct_OFFLINE", ["s0"]))
        assert q.lease("w0", ["PurgeTask"]) is None
        assert q.lease("w0", ["MergeRollupTask"]) is not None

    def test_foreign_worker_cannot_renew_or_complete(self):
        q = TaskQueue()
        e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["s0"]))
        q.lease("w0")
        assert q.renew(e.task_id, "w1") == {"ok": False, "cancelled": False}
        assert not q.complete(e.task_id, "w1")
        assert q.get(e.task_id).state == LEASED

    def test_lease_expiry_requeues_exactly_once(self):
        q = TaskQueue(lease_ttl_s=0.01, backoff_s=0.0, max_attempts=5)
        e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["s0"]))
        q.lease("w0")
        time.sleep(0.02)
        assert q.expire_leases() == [e.task_id]
        cur = q.get(e.task_id)
        assert cur.state == PENDING and cur.attempts == 1
        # a second sweep must NOT touch the already-requeued task
        assert q.expire_leases() == []
        assert q.get(e.task_id).attempts == 1

    def test_retry_backoff_exponential_and_capped(self):
        q = TaskQueue(lease_ttl_s=60.0, backoff_s=1.0, backoff_cap_s=3.0,
                      max_attempts=10)
        e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["s0"]))
        gaps = []
        for _ in range(4):
            cur = q.get(e.task_id)
            cur.not_before = 0.0  # make leasable immediately
            q.lease("w0")
            t0 = time.time()
            q.fail(e.task_id, "w0", "boom")
            gaps.append(q.get(e.task_id).not_before - t0)
        # 1, 2, 3 (capped), 3 (capped) within timing slack
        assert 0.9 <= gaps[0] <= 1.1
        assert 1.9 <= gaps[1] <= 2.1
        assert 2.9 <= gaps[2] <= 3.1
        assert 2.9 <= gaps[3] <= 3.1

    def test_attempts_exhausted_fails_terminally(self):
        q = TaskQueue(backoff_s=0.0, max_attempts=2)
        e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["s0"]))
        for _ in range(2):
            q.get(e.task_id).not_before = 0.0
            assert q.lease("w0") is not None
            q.fail(e.task_id, "w0", "boom")
        assert q.get(e.task_id).state == FAILED
        assert q.lease("w0") is None

    def test_cancel_pending_and_running(self):
        q = TaskQueue()
        a = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["a"]))
        assert q.cancel(a.task_id) == CANCELLED
        b = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["b"]))
        q.lease("w0")
        assert q.cancel(b.task_id) in (LEASED, RUNNING)
        r = q.renew(b.task_id, "w0")
        assert r["ok"] and r["cancelled"]  # worker told to abort
        q.fail(b.task_id, "w0", "aborted", cancelled=True)
        assert q.get(b.task_id).state == CANCELLED

    def test_journal_reload_resumes_pending_and_leased(self, tmp_path):
        path = str(tmp_path / "tasks.journal")
        q = TaskQueue(journal_path=path, lease_ttl_s=0.05, backoff_s=0.0)
        a = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", ["a"]))
        b = q.submit(TaskConfig("MergeRollupTask", "ct_OFFLINE",
                                ["b1", "b2"]))
        q.lease("w0", ["MergeRollupTask"])  # b now LEASED
        q.close()
        # "restart": a fresh queue over the same journal
        q2 = TaskQueue(journal_path=path, lease_ttl_s=0.05, backoff_s=0.0)
        assert q2.get(a.task_id).state == PENDING
        assert q2.get(b.task_id).state == LEASED
        # the reloaded lease is still wall-clock honored: expiry requeues
        time.sleep(0.06)
        q2.expire_leases()
        assert q2.get(b.task_id).state == PENDING
        got = {q2.lease("w1").task_id, q2.lease("w1").task_id}
        assert got == {a.task_id, b.task_id}

    def test_journal_compaction_bounds_size(self, tmp_path):
        path = str(tmp_path / "tasks.journal")
        q = TaskQueue(journal_path=path, journal_max_bytes=4096,
                      max_done=4)
        for i in range(40):
            e = q.submit(TaskConfig("PurgeTask", "ct_OFFLINE", [f"s{i}"]))
            q.lease("w0")
            q.complete(e.task_id, "w0")
        assert os.path.getsize(path) <= 4096 * 4  # compacted, not unbounded
        q2 = TaskQueue(journal_path=path)
        assert len(q2) >= 1  # reload still parses


# ---------------------------------------------------------------------------
# Generator cadence
# ---------------------------------------------------------------------------

class TestGeneratorCadence:
    def test_generator_feeds_queue_with_dedupe(self, tmp_path):
        state = ClusterState()
        cfg = make_config()
        cfg.task_configs = {"MergeRollupTask": {}}
        state.add_table(cfg, make_schema())
        for i in range(3):
            d = build_seg(tmp_path, f"g{i}", n=50, ts_base=i * 100, seed=i)
            m = load_segment(d).metadata
            state.upsert_segment(SegmentState(
                f"g{i}", "ct_OFFLINE", [], dir_path=d, num_docs=50,
                start_time=m.start_time, end_time=m.end_time))
        tm = TaskManager(state, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        out = tm.run_once()
        assert out["generated"] == 1
        assert len(tm.queue.list(PENDING)) == 1
        # second tick: the active task dedupes regeneration
        assert tm.run_once()["generated"] == 0

    def test_realtime_to_offline_generator(self, tmp_path):
        """Sealed (ONLINE) realtime segments batch into one
        RealtimeToOfflineSegmentsTask; CONSUMING segments never move;
        the active task dedupes regeneration."""
        state = ClusterState()
        cfg = make_config()
        cfg.task_configs = {"RealtimeToOfflineSegmentsTask": {}}
        state.add_table(cfg, make_schema())
        for i in range(3):
            d = build_seg(tmp_path, f"rt{i}", n=40, ts_base=i * 100, seed=i)
            m = load_segment(d).metadata
            state.upsert_segment(SegmentState(
                f"rt{i}", "ct_REALTIME", [], dir_path=d, num_docs=40,
                start_time=m.start_time, end_time=m.end_time))
        state.upsert_segment(SegmentState(
            "rt_consuming", "ct_REALTIME", [], dir_path="/nope",
            num_docs=0, status="CONSUMING"))
        tm = TaskManager(state, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        assert entry.task_type == "RealtimeToOfflineSegmentsTask"
        assert entry.table == "ct_REALTIME"
        assert sorted(entry.segments) == ["rt0", "rt1", "rt2"]
        assert "rt_consuming" not in entry.segments
        # second tick: the active task dedupes regeneration
        assert tm.run_once()["generated"] == 0
        # a segment sealing MID-FLIGHT must not spawn a superset task —
        # overlap (not just exact-set) dedupe, or the same realtime rows
        # would migrate into the OFFLINE table twice
        tm.queue.lease("w0")
        d3 = build_seg(tmp_path, "rt3", n=40, ts_base=300, seed=3)
        m3 = load_segment(d3).metadata
        state.upsert_segment(SegmentState(
            "rt3", "ct_REALTIME", [], dir_path=d3, num_docs=40,
            start_time=m3.start_time, end_time=m3.end_time))
        assert tm.run_once()["generated"] == 0

    def test_purge_generator(self, tmp_path):
        """PurgeTask generator scans ONLINE offline segments, carries
        the table's purgePredicate into task params, skips already
        rewritten (_purged) outputs, and requires a predicate at all."""
        state = ClusterState()
        cfg = make_config()
        cfg.task_configs = {"PurgeTask": {"purgePredicate": "m > 90"}}
        state.add_table(cfg, make_schema())
        for name in ("p0", "p1", "p0_purged"):
            d = build_seg(tmp_path, name, n=30, seed=3)
            state.upsert_segment(SegmentState(
                name, "ct_OFFLINE", [], dir_path=d, num_docs=30))
        tm = TaskManager(state, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        assert entry.task_type == "PurgeTask"
        assert entry.table == "ct_OFFLINE"
        assert sorted(entry.segments) == ["p0", "p1"]  # _purged skipped
        assert entry.params["purgePredicate"] == "m > 90"
        assert tm.run_once()["generated"] == 0  # active-task dedupe
        # a PurgeTask opt-in WITHOUT a predicate generates nothing
        state2 = ClusterState()
        cfg2 = make_config()
        cfg2.task_configs = {"PurgeTask": {}}
        state2.add_table(cfg2, make_schema())
        state2.upsert_segment(SegmentState(
            "q0", "ct_OFFLINE", [], dir_path="/nope", num_docs=10))
        tm2 = TaskManager(state2, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        assert tm2.run_once()["generated"] == 0

    def test_cross_type_overlap_dedupes(self, tmp_path):
        """A table opting into BOTH merge-rollup and purge must not get
        two concurrent tasks over the same segments: every executor
        consumes-and-retires its inputs, so a race would republish the
        rows twice. One tick emits one task; the other type waits."""
        state = ClusterState()
        cfg = make_config()
        cfg.task_configs = {"MergeRollupTask": {},
                            "PurgeTask": {"purgePredicate": "m > 90"}}
        state.add_table(cfg, make_schema())
        for i in range(3):
            d = build_seg(tmp_path, f"x{i}", n=50, ts_base=i * 100, seed=i)
            m = load_segment(d).metadata
            state.upsert_segment(SegmentState(
                f"x{i}", "ct_OFFLINE", [], dir_path=d, num_docs=50,
                start_time=m.start_time, end_time=m.end_time))
        tm = TaskManager(state, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        assert sorted(entry.segments) == ["x0", "x1", "x2"]
        assert tm.run_once()["generated"] == 0  # second type still waits

    def test_table_without_task_config_not_scanned(self, tmp_path):
        state = ClusterState()
        state.add_table(make_config(), make_schema())  # no task_configs
        for i in range(3):
            state.upsert_segment(SegmentState(
                f"h{i}", "ct_OFFLINE", [], dir_path="/nope", num_docs=50))
        tm = TaskManager(state, config=PinotConfiguration(overrides={
            "pinot.controller.task.generators.enabled": True}))
        assert tm.run_once()["generated"] == 0


# ---------------------------------------------------------------------------
# MiniCluster integration
# ---------------------------------------------------------------------------

def _mini_cluster(tmp_path, n_segments=2, minions=1, chaos=None,
                  result_cache=False, num_servers=2, seg_docs=60,
                  config=None):
    c = MiniCluster(num_servers=num_servers, minions=minions, chaos=chaos,
                    result_cache=result_cache, config=config)
    c.start()
    c.add_table("ct", time_column="ts", table_config=make_config(),
                schema=make_schema())
    names = []
    for i in range(n_segments):
        d = build_seg(tmp_path, f"seg_{i}", n=seg_docs, ts_base=i * 1000,
                      seed=i)
        c.add_segment("ct", load_segment(d), server_idx=i % num_servers)
        names.append(f"seg_{i}")
    return c, names


class TestMiniClusterFabric:
    def test_purge_task_end_to_end_smoke(self, tmp_path):
        """Tier-1 smoke path: MiniCluster(minions=1) runs one purge task
        end to end — lease over real TCP, sandboxed execute, deep-store
        upload, atomic swap, epoch move."""
        c, _names = _mini_cluster(tmp_path, n_segments=1, minions=1)
        try:
            before = c.query("SELECT COUNT(*) FROM ct")
            assert before.rows[0][0] == 60
            epoch0 = c.routing.get_route("ct").epoch()
            e = c.submit_task(TaskConfig(
                "PurgeTask", "ct_OFFLINE", ["seg_0"],
                {"purgePredicate": "ts < 30"}))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == COMPLETED, done
            assert done["result"]["purgedSegments"] == ["seg_0_purged"]
            after = c.query("SELECT COUNT(*), MIN(ts) FROM ct")
            assert after.rows[0] == (30, 30.0)
            rt = c.routing.get_route("ct")
            assert sorted(rt.offline.segments) == ["seg_0_purged"]
            assert rt.epoch() != epoch0  # swap moved the routing epoch
            # the worker's sandbox is cleaned after the commit (the
            # COMPLETED transition lands server-side just before the
            # worker's local cleanup, so poll briefly)
            sandbox = os.path.join(c.minions[0].work_dir, e["task_id"])
            deadline = time.time() + 5
            while os.path.exists(sandbox) and time.time() < deadline:
                time.sleep(0.02)
            assert not os.path.exists(sandbox)
        finally:
            c.stop()

    def test_merge_rollup_swap_cache_coherence(self, tmp_path):
        """After a minion merge-rollup swap: broker whole-result cache
        misses on the new epoch, server partial caches miss for the new
        segment, negative entries for the table are DROPPED, and warmup
        replays logged plans before the swapped segment serves."""
        c, names = _mini_cluster(tmp_path, n_segments=2, minions=1,
                                 result_cache=True)
        try:
            sql = "SELECT COUNT(*), SUM(m) FROM ct"
            r1 = c.query(sql)
            r2 = c.query(sql)
            assert r2.cache_hit is True and r2.rows == r1.rows
            # seed a negative entry: partition metadata prunes the plan
            # to zero (EQ on a partition value no segment holds)
            rt = c.routing.get_route("ct")
            for info in rt.offline.segments.values():
                info.partition_column = "ts"
                info.num_partitions = 4
                info.partition_id = 0
            neg = c.broker._negative_cache
            pruned = "SELECT COUNT(*) FROM ct WHERE ts = 3"  # 3 % 4 != 0
            c.query(pruned)
            assert len(neg) == 1
            # server partial caches + warmup fingerprint log are primed
            warm0 = [s.executor.warmup.entries_warmed for s in c.servers]
            e = c.submit_task(TaskConfig("MergeRollupTask", "ct_OFFLINE",
                                         names))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == COMPLETED, done
            # negative entries for the table were dropped at the swap
            assert len(neg) == 0
            # warmup replayed the logged plan on the NEW segment before
            # it was routed (both servers held inputs, both warm)
            warm1 = [s.executor.warmup.entries_warmed for s in c.servers]
            assert sum(warm1) > sum(warm0)
            # whole-result cache: the old-epoch entry is unaddressable —
            # the next query re-executes and STILL matches
            r3 = c.query(sql)
            assert r3.cache_hit is False
            assert r3.rows == r1.rows
            r4 = c.query(sql)
            assert r4.cache_hit is True  # new-epoch entry now cached
        finally:
            c.stop()

    def test_task_failure_retries_then_fails_terminally(self, tmp_path):
        c, _names = _mini_cluster(tmp_path, n_segments=1, minions=1)
        try:
            # an executor-level error (bad predicate) fails every attempt
            e = c.submit_task(TaskConfig(
                "PurgeTask", "ct_OFFLINE", ["seg_0"],
                {"purgePredicate": "nonexistent_column < 30"}))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == FAILED
            assert done["attempts"] == done["max_attempts"]
            # inputs untouched by the failed task
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 60
        finally:
            c.stop()

    def test_cancel_pending_task_via_queue(self, tmp_path):
        # the worker only leases merge tasks, so a purge task stays
        # PENDING until cancelled — exercising declared-task-type
        # filtering and the cancel path in one setup
        c, _names = _mini_cluster(
            tmp_path, n_segments=1, minions=1,
            config=PinotConfiguration(overrides={
                "pinot.minion.task.types": "MergeRollupTask"}))
        try:
            e = c.submit_task(TaskConfig(
                "PurgeTask", "ct_OFFLINE", ["seg_0"],
                {"purgePredicate": "ts < 30"}))
            time.sleep(0.2)  # give the (filtered) worker poll a chance
            assert c.task(e["task_id"])["state"] == PENDING
            assert c.task_manager.queue.cancel(e["task_id"]) == CANCELLED
            assert c.task(e["task_id"])["state"] == CANCELLED
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# Chaos: the fault-tolerant lifecycle under deterministic failures
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestFabricChaos:
    def _run_merge(self, tmp_path, tag, chaos=None, minions=2):
        (tmp_path / tag).mkdir(exist_ok=True)
        c, names = _mini_cluster(tmp_path / tag, n_segments=3,
                                 minions=minions, chaos=chaos)
        try:
            # pinned task id: output names derive from (inputs, task_id),
            # so runs are comparable segment-for-segment
            e = c.submit_task(TaskConfig("MergeRollupTask", "ct_OFFLINE",
                                         names, task_id="Task_merge_acc"))
            done = c.wait_task(e["task_id"], timeout_s=60)
            rows = c.query("SELECT COUNT(*), SUM(m) FROM ct").rows
            rt = c.routing.get_route("ct")
            segs = sorted(rt.offline.segments)
            state_segs = sorted(
                s.name for s in c.cluster_state.table_segments("ct_OFFLINE"))
            crashed = [w.instance_id for w in c.minions if w.crashed]
            workers = {w.instance_id: w.executed for w in c.minions}
            return {"state": done["state"], "rows": rows, "segs": segs,
                    "state_segs": state_segs, "crashed": crashed,
                    "workers": workers,
                    "decisions": (c.chaos.decisions()
                                  if c.chaos is not None else None)}
        finally:
            c.stop()

    def test_worker_killed_mid_task_releases_and_completes(self, tmp_path):
        """ISSUE 5 acceptance: a seeded-chaos kill of the first worker to
        lease the task; the lease expires, a second worker re-leases and
        completes with the EXACT segment set of a no-chaos run — no
        duplicated, lost, or stale segments — and the same seed replays
        identically."""
        tmp_path.mkdir(exist_ok=True)
        baseline = self._run_merge(tmp_path, "nochaos", chaos=None)
        assert baseline["state"] == COMPLETED

        def schedule():
            return FaultSchedule([
                ("minion.task.execute",
                 {"error": SimulatedCrash("chaos kill"), "times": 1,
                  "seed": 7})])

        a = self._run_merge(tmp_path, "chaos_a", chaos=schedule())
        b = self._run_merge(tmp_path, "chaos_b", chaos=schedule())
        for run in (a, b):
            assert run["state"] == COMPLETED
            assert len(run["crashed"]) == 1  # exactly one worker died
            # the SURVIVOR executed it (the corpse never reported back)
            survivor = [w for w in run["workers"]
                        if w not in run["crashed"]][0]
            assert run["workers"][survivor] == 1
            # exact same segment set + answers as the no-chaos run
            assert run["segs"] == baseline["segs"]
            assert run["state_segs"] == baseline["state_segs"]
            assert run["rows"] == baseline["rows"]
        # deterministic replay: same seed, same decision log
        assert a["decisions"] == b["decisions"]
        assert a["segs"] == b["segs"]

    def test_crash_between_upload_and_swap_is_idempotent(self, tmp_path):
        """The commit manifest makes crash-mid-commit idempotent: the
        swap request dies once AFTER outputs + manifest are durable; the
        re-leased attempt detects the manifest, skips re-execution, and
        replays only the swap."""
        c, names = _mini_cluster(tmp_path, n_segments=2, minions=1)
        try:
            failpoints.arm("controller.segment.replace",
                           error=FailpointError("controller crash"),
                           times=1)
            e = c.submit_task(TaskConfig("MergeRollupTask", "ct_OFFLINE",
                                         names))
            done = c.wait_task(e["task_id"], timeout_s=60)
            assert done["state"] == COMPLETED, done
            w = c.minions[0]
            assert w.executed == 1          # never re-executed
            assert w.manifest_resumes == 1  # resumed from the manifest
            rt = c.routing.get_route("ct")
            token = task_token(TaskConfig("MergeRollupTask", "ct_OFFLINE",
                                          names, task_id=e["task_id"]))
            assert sorted(rt.offline.segments) == [f"ct_merged_{token}"]
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 120
        finally:
            c.stop()

    def test_lease_renew_chaos_does_not_lose_tasks(self, tmp_path):
        """Heartbeat frames dropped by chaos: the worker keeps running
        (the lease TTL absorbs missed renewals) and the task completes."""
        sched = FaultSchedule([
            ("controller.task.lease.renew",
             {"error": ConnectionError("renew chaos"), "times": 2,
              "seed": 3})])
        c, names = _mini_cluster(tmp_path, n_segments=1, minions=1,
                                 chaos=sched)
        try:
            e = c.submit_task(TaskConfig(
                "PurgeTask", "ct_OFFLINE", ["seg_0"],
                {"purgePredicate": "ts < 10"}))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == COMPLETED, done
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 50
        finally:
            c.stop()


class TestNoDeepStoreDeployment:
    def test_sandbox_preserved_when_outputs_live_locally(self, tmp_path):
        """Single-box deployment (no deep store): the sandbox IS the
        committed segments' home — the worker must NOT clean it up, and
        the registered dir_path must stay loadable."""
        from pinot_tpu.controller.coordination import CoordinationServer
        from pinot_tpu.minion.worker import MinionWorker
        state = ClusterState()
        state.add_table(make_config(), make_schema())
        d = build_seg(tmp_path, "seg_0", n=40)
        m = load_segment(d).metadata
        state.upsert_segment(SegmentState(
            "seg_0", "ct_OFFLINE", [], dir_path=d, num_docs=40,
            start_time=m.start_time, end_time=m.end_time))
        conf = PinotConfiguration(overrides={
            "pinot.minion.poll.seconds": 0.05,
            "pinot.minion.heartbeat.seconds": 0.2})
        tm = TaskManager(state, config=conf)
        srv = CoordinationServer(state, task_manager=tm)  # NO deep store
        srv.start()
        w = MinionWorker("m0", srv.address,
                         work_dir=str(tmp_path / "w0"), config=conf)
        w.start()
        try:
            e = tm.submit(TaskConfig(
                "PurgeTask", "ct_OFFLINE", ["seg_0"],
                {"purgePredicate": "ts < 10"}))
            deadline = time.time() + 20
            while time.time() < deadline:
                if tm.queue.get(e.task_id).state == COMPLETED:
                    break
                time.sleep(0.05)
            assert tm.queue.get(e.task_id).state == COMPLETED
            (st,) = state.table_segments("ct_OFFLINE")
            assert st.name == "seg_0_purged"
            # the local build dir survived the commit and still loads
            assert os.path.isdir(st.dir_path)
            assert load_segment(st.dir_path).num_docs == 30
        finally:
            w.stop()
            srv.stop()
            tm.stop()


# ---------------------------------------------------------------------------
# Instance sweep liveness (ISSUE 6 satellite)
# ---------------------------------------------------------------------------

class TestInstanceLiveness:
    def test_instances_reports_minion_heartbeat_liveness(self, tmp_path):
        """/instances tags every heartbeating instance — minion workers
        alongside servers — with last-heartbeat age and live/stale
        status; statically wired instances read 'unknown'."""
        from pinot_tpu.controller.cluster_state import InstanceState
        from pinot_tpu.controller.http_api import ControllerHttpServer
        c, _names = _mini_cluster(tmp_path, n_segments=1, minions=1)
        srv = None
        try:
            c.cluster_state.register_instance(
                InstanceState("server_dead", tags=["minion"]))
            c.coordination._last_seen["server_dead"] = time.time() - 999
            c.cluster_state.register_instance(InstanceState("server_static"))
            # the worker's own poll-loop heartbeat (not just its one-time
            # registration) keeps the age fresh: wait past several
            # heartbeat intervals, the age must stay below the gap
            time.sleep(1.0)
            age = c.coordination.heartbeat_ages().get("minion_0")
            assert age is not None and age < 0.8, \
                f"minion heartbeat not refreshing (age={age})"
            srv = ControllerHttpServer(c.cluster_state,
                                       coordination=c.coordination)
            srv.start()
            url = f"http://{srv.host}:{srv.port}/instances"
            with urllib.request.urlopen(url, timeout=10) as r:
                insts = json.loads(r.read())["instances"]
            minion = insts["minion_0"]
            assert "minion" in minion["tags"]
            assert minion["liveness"] == "live"
            assert 0 <= minion["lastHeartbeatAgeSeconds"] < 15.0
            assert insts["server_dead"]["liveness"] == "stale"
            assert insts["server_dead"]["lastHeartbeatAgeSeconds"] > 15.0
            assert insts["server_static"]["liveness"] == "unknown"
            assert insts["server_static"]["lastHeartbeatAgeSeconds"] is None
            # a worker blocked inside a LONG task never reaches its
            # poll-loop heartbeat — its lease RPCs must prove liveness
            # instead (any worker-attributed task op bumps last-seen)
            from pinot_tpu.controller.coordination import CoordinationClient
            c.coordination._last_seen["minion_0"] = time.time() - 999
            probe = CoordinationClient(c.coordination.address)
            try:
                probe.request("task_renew", task_id="no-such-task",
                              worker="minion_0")
            except (RuntimeError, OSError):
                pass  # the renew itself may fail; the bump precedes it
            finally:
                probe.close()
            assert c.coordination.heartbeat_ages()["minion_0"] < 5.0
        finally:
            if srv is not None:
                srv.stop()
            c.stop()


# ---------------------------------------------------------------------------
# Controller HTTP surface
# ---------------------------------------------------------------------------

class TestTaskHttpApi:
    def test_task_routes(self, tmp_path):
        from pinot_tpu.controller.http_api import ControllerHttpServer
        state = ClusterState()
        state.add_table(make_config(), make_schema())
        tm = TaskManager(state, config=PinotConfiguration())
        srv = ControllerHttpServer(state, task_manager=tm)
        srv.start()
        base = f"http://{srv.host}:{srv.port}"

        def call(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(base + path, data=data,
                                         method=method)
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read())

        try:
            out = call("POST", "/tasks", {
                "taskType": "PurgeTask", "table": "ct_OFFLINE",
                "segments": ["s0"], "params": {"purgePredicate": "ts < 1"}})
            tid = out["task"]["task_id"]
            assert out["task"]["state"] == PENDING
            assert [t["task_id"] for t in
                    call("GET", "/tasks")["tasks"]] == [tid]
            assert call("GET", "/tasks?state=PENDING")["tasks"]
            assert call("GET", "/tasks?state=COMPLETED")["tasks"] == []
            assert call("GET", f"/tasks/{tid}")["task"]["task_id"] == tid
            assert call("POST", f"/tasks/{tid}/cancel")["state"] \
                == CANCELLED
        finally:
            srv.stop()
            tm.stop()


# ---------------------------------------------------------------------------
# Lease priorities + per-table fairness (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

class TestLeasePriorityAndFairness:
    def test_table_flood_cannot_starve_other_table(self):
        """Regression: 20 queued table-A tasks vs 2 table-B tasks — the
        lease rotation serves B by the second grant and again within the
        next turn, instead of draining A's FIFO backlog first."""
        q = TaskQueue()
        for i in range(20):
            q.submit(TaskConfig("PurgeTask", "tableA_OFFLINE", [f"a{i}"]))
        q.submit(TaskConfig("PurgeTask", "tableB_OFFLINE", ["b0"]))
        q.submit(TaskConfig("PurgeTask", "tableB_OFFLINE", ["b1"]))
        order = [q.lease(f"w{i}").table for i in range(6)]
        assert order[:4] == ["tableA_OFFLINE", "tableB_OFFLINE",
                             "tableA_OFFLINE", "tableB_OFFLINE"]
        # B exhausted: the rotation degrades to FIFO over A alone
        assert order[4:] == ["tableA_OFFLINE", "tableA_OFFLINE"]

    def test_fifo_within_one_table_unchanged(self):
        q = TaskQueue()
        ids = [q.submit(TaskConfig("PurgeTask", "t_OFFLINE",
                                   [f"s{i}"])).task_id for i in range(3)]
        assert [q.lease("w0").task_id for _ in range(3)] == ids

    def test_priority_beats_fifo_and_fairness(self):
        q = TaskQueue()
        q.submit(TaskConfig("PurgeTask", "t1_OFFLINE", ["x0"]))
        q.submit(TaskConfig("PurgeTask", "t1_OFFLINE", ["x1"]))
        hi = q.submit(TaskConfig("PurgeTask", "t2_OFFLINE", ["y0"],
                                 {"priority": 5}))
        # the priority-5 task leases first even though t1 is older AND
        # t2 would lose the FIFO tie-break
        assert q.lease("w0").task_id == hi.task_id

    def test_explicit_priority_param_on_submit(self):
        q = TaskQueue()
        q.submit(TaskConfig("PurgeTask", "t_OFFLINE", ["a"]))
        b = q.submit(TaskConfig("PurgeTask", "t_OFFLINE", ["b"]),
                     priority=3)
        assert q.lease("w0").task_id == b.task_id

    def test_priority_survives_journal_reload(self, tmp_path):
        path = str(tmp_path / "prio.journal")
        q = TaskQueue(journal_path=path)
        e = q.submit(TaskConfig("PurgeTask", "t_OFFLINE", ["a"],
                                {"priority": 7}))
        q2 = TaskQueue(journal_path=path)
        assert q2.get(e.task_id).priority == 7


# ---------------------------------------------------------------------------
# Worker-side executor pool (ISSUE 7 satellite, carried over from PR 5)
# ---------------------------------------------------------------------------

class _GateExecutor:
    """Test-only executor: blocks on a gate so concurrency is observable."""
    task_type = "GateTask"

    def __init__(self, gate, started):
        self.gate = gate
        self.started = started

    def execute(self, task, ctx):
        self.started.append(task.task_id)
        assert self.gate.wait(30), "gate never opened"
        return {"ok": True}


class TestExecutorPool:
    def _harness(self, tmp_path, overrides):
        from pinot_tpu.controller.coordination import CoordinationServer
        from pinot_tpu.minion.worker import MinionWorker
        state = ClusterState()
        conf = PinotConfiguration(overrides={
            "pinot.minion.poll.seconds": 0.02,
            "pinot.minion.heartbeat.seconds": 0.2,
            **overrides})
        tm = TaskManager(state, config=conf)
        srv = CoordinationServer(state, task_manager=tm)
        srv.start()
        w = MinionWorker("m0", srv.address,
                         work_dir=str(tmp_path / "pool_w0"),
                         task_types=["GateTask"], config=conf)
        w.start()
        return tm, srv, w

    def _run_gated(self, tmp_path, overrides, n_tasks, expect_parallel):
        import threading as _threading
        from pinot_tpu.controller.tasks import (_EXECUTORS,
                                                register_executor)
        gate = _threading.Event()
        started = []
        register_executor(_GateExecutor(gate, started))
        tm, srv, w = self._harness(tmp_path, overrides)
        try:
            entries = [tm.submit(TaskConfig("GateTask", "t_OFFLINE",
                                            [f"s{i}"]))
                       for i in range(n_tasks)]
            deadline = time.time() + 10
            while len(started) < expect_parallel and \
                    time.time() < deadline:
                time.sleep(0.02)
            assert len(started) == expect_parallel
            time.sleep(0.4)  # grace: no extra task may start past the cap
            assert len(started) == expect_parallel, \
                f"cap violated: {len(started)} tasks running"
            assert w.running_tasks() == expect_parallel
            gate.set()
            deadline = time.time() + 20
            while time.time() < deadline:
                states = {tm.queue.get(e.task_id).state for e in entries}
                if states == {COMPLETED}:
                    break
                time.sleep(0.05)
            assert {tm.queue.get(e.task_id).state
                    for e in entries} == {COMPLETED}
        finally:
            gate.set()
            w.stop()
            srv.stop()
            tm.stop()
            _EXECUTORS.pop("GateTask", None)

    def test_pool_runs_tasks_concurrently(self, tmp_path):
        """concurrency=2: two of three tasks run in parallel (each with
        its own lease heartbeat), the third waits for a slot, and all
        three complete once the gate opens."""
        self._run_gated(
            tmp_path, {"pinot.minion.executor.concurrency": 2},
            n_tasks=3, expect_parallel=2)

    def test_per_type_cap_below_pool_size(self, tmp_path):
        """pinot.minion.executor.concurrency.GateTask=1 holds the type
        to one in-flight task even though the pool has two slots."""
        self._run_gated(
            tmp_path, {"pinot.minion.executor.concurrency": 2,
                       "pinot.minion.executor.concurrency.GateTask": 1},
            n_tasks=2, expect_parallel=1)
