"""Multi-stage engine tests: planner, operators, distributed execution.

Pattern ref: pinot-query-runtime QueryRunnerTestBase — in-process workers
with real mailboxes, results compared against a numpy oracle.
"""
import numpy as np
import pytest

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.dispatcher import QueryDispatcher
from pinot_tpu.mse.logical import build_logical
from pinot_tpu.mse.operators import filter_block, hash_join, hash_partition
from pinot_tpu.mse.planner import plan_query
from pinot_tpu.mse.runtime import MseWorker
from pinot_tpu.mse.sql import parse_mse_sql
from pinot_tpu.query.expressions import func, ident, lit


# ---------------------------------------------------------------------------
# fixtures: synthetic star schema over 2 fake workers
# ---------------------------------------------------------------------------

def _tables():
    rng = np.random.default_rng(7)
    n = 2000
    return {
        "lineorder": {
            "lo_orderkey": np.arange(n, dtype=np.int64),
            "lo_partkey": rng.integers(0, 60, n).astype(np.int64),
            "lo_suppkey": rng.integers(0, 25, n).astype(np.int64),
            "lo_orderdate": rng.integers(0, 300, n).astype(np.int64),
            "lo_revenue": rng.integers(100, 10000, n).astype(np.int64),
            "lo_supplycost": rng.integers(50, 500, n).astype(np.int64),
            "lo_discount": rng.integers(0, 11, n).astype(np.int64),
            "lo_quantity": rng.integers(1, 50, n).astype(np.int64),
        },
        "dates": {
            "d_datekey": np.arange(300, dtype=np.int64),
            "d_year": (1992 + (np.arange(300) // 60)).astype(np.int64),
            "d_month": (1 + (np.arange(300) % 12)).astype(np.int64),
        },
        "part": {
            "p_partkey": np.arange(60, dtype=np.int64),
            "p_category": np.array(
                [f"MFGR#{i % 5}" for i in range(60)], object),
            "p_brand1": np.array(
                [f"MFGR#{i % 5}{i % 12}" for i in range(60)], object),
        },
        "supplier": {
            "s_suppkey": np.arange(25, dtype=np.int64),
            "s_region": np.array(
                ["AMERICA" if i % 2 else "ASIA" for i in range(25)], object),
        },
    }


@pytest.fixture(scope="module")
def mse():
    tables = _tables()

    def make_scan(shard, nshards):
        def scan(table, columns, filt):
            # contract: filt references PHYSICAL columns (it is evaluated
            # against the segment, not the projected output)
            t = tables[table]
            n = len(next(iter(t.values())))
            idx = np.arange(n) % nshards == shard
            b = Block(list(t), [t[c][idx] for c in t])
            if filt is not None:
                b = filter_block(b, filt)
            return b.select(columns)
        return scan

    workers = {}
    for i in range(2):
        w = MseWorker(f"server_{i}", make_scan(i, 2))
        w.start()
        workers[f"server_{i}"] = w
    catalog = {k: list(v.keys()) for k, v in tables.items()}
    disp = QueryDispatcher(workers, lambda: catalog,
                           lambda t: sorted(workers))
    yield disp, tables
    for w in workers.values():
        w.stop()
    disp.stop()


def _rows(resp):
    assert not resp.exceptions, resp.exceptions
    return resp.result_table.rows


# ---------------------------------------------------------------------------
# block serde
# ---------------------------------------------------------------------------

class TestBlockSerde:
    def test_roundtrip(self):
        b = Block(
            ["i", "f", "s", "o"],
            [np.array([1, 2, 3], np.int64),
             np.array([0.5, np.nan, 2.0]),
             np.array(["a", "b", "c"], object),
             np.array([None, 7, "x"], object)])
        b2 = Block.from_bytes(b.to_bytes())
        assert b2.names == b.names
        assert b2.arrays[0].tolist() == [1, 2, 3]
        assert b2.arrays[1][0] == 0.5 and np.isnan(b2.arrays[1][1])
        assert b2.arrays[2].tolist() == ["a", "b", "c"]
        assert b2.arrays[3].tolist() == [None, 7, "x"]

    def test_empty(self):
        b = Block.from_bytes(Block(["x"], [np.empty(0, np.int64)]).to_bytes())
        assert b.num_rows == 0


# ---------------------------------------------------------------------------
# sql parsing + logical planning
# ---------------------------------------------------------------------------

class TestMseSql:
    def test_parse_joins(self):
        q = parse_mse_sql(
            "SELECT a.x FROM t1 a JOIN t2 b ON a.k = b.k "
            "LEFT JOIN t3 c ON b.j = c.j WHERE a.x > 5")
        assert q.from_item.table == "t1" and q.from_item.alias == "a"
        assert [j.join_type for j in q.joins] == ["inner", "left"]

    def test_parse_subquery(self):
        q = parse_mse_sql(
            "SELECT s.y FROM (SELECT x AS y FROM t1) AS s LIMIT 5")
        assert q.from_item.subquery is not None
        assert q.from_item.alias == "s"

    def test_single_table_lowering(self):
        q = parse_mse_sql("SELECT COUNT(*) FROM t WHERE a = 3")
        assert q.is_single_table
        pq = q.to_single_stage()
        assert pq.table == "t"

    def test_plan_stages(self):
        q = parse_mse_sql(
            "SELECT d.d_year, SUM(lo.lo_revenue) FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "GROUP BY d.d_year")
        cat = {"lineorder": ["lo_orderdate", "lo_revenue"],
               "dates": ["d_datekey", "d_year"]}
        logical = build_logical(q, cat)
        plan = plan_query(logical, {}, lambda t: ["s0", "s1"], ["s0", "s1"])
        # root + agg + join + 2 leaf scans
        assert len(plan.stages) == 5

        def collect(op, out):
            out.add(op["op"])
            for k in ("child", "left", "right"):
                if isinstance(op.get(k), dict):
                    collect(op[k], out)
            return out

        ops = set()
        for s in plan.stages:
            collect(s.root, ops)
        assert {"join", "aggregate", "scan", "receive"} <= ops
        kinds = {s.out_kind for s in plan.stages if s.receiver_stage >= 0}
        assert "hash" in kinds and "singleton" in kinds


# ---------------------------------------------------------------------------
# operator units
# ---------------------------------------------------------------------------

class TestJoinOperator:
    def _blocks(self):
        left = Block(["l.k", "l.v"],
                     [np.array([1, 2, 2, 3, 5], np.int64),
                      np.array([10, 20, 21, 30, 50], np.int64)])
        right = Block(["r.k", "r.w"],
                      [np.array([2, 3, 3, 4], np.int64),
                       np.array([200, 300, 301, 400], np.int64)])
        return left, right

    def test_inner(self):
        left, right = self._blocks()
        out = hash_join(left, right, "inner", [ident("l.k")], [ident("r.k")],
                        None, left.names + right.names)
        got = sorted(out.rows())
        assert got == [(2, 20, 2, 200), (2, 21, 2, 200),
                       (3, 30, 3, 300), (3, 30, 3, 301)]

    def test_left(self):
        left, right = self._blocks()
        out = hash_join(left, right, "left", [ident("l.k")], [ident("r.k")],
                        None, left.names + right.names)
        unmatched = [r for r in out.rows() if r[2] is None]
        assert sorted(r[0] for r in unmatched) == [1, 5]
        assert out.num_rows == 6

    def test_full(self):
        left, right = self._blocks()
        out = hash_join(left, right, "full", [ident("l.k")], [ident("r.k")],
                        None, left.names + right.names)
        assert out.num_rows == 7  # 4 matches + 2 left-only + 1 right-only

    def test_semi_anti(self):
        left, right = self._blocks()
        semi = hash_join(left, right, "semi", [ident("l.k")], [ident("r.k")],
                         None, left.names)
        anti = hash_join(left, right, "anti", [ident("l.k")], [ident("r.k")],
                         None, left.names)
        assert sorted(semi.column("l.k").tolist()) == [2, 2, 3]
        assert sorted(anti.column("l.k").tolist()) == [1, 5]

    def test_residual(self):
        left, right = self._blocks()
        res = func("greater_than", ident("r.w"), lit(250))
        out = hash_join(left, right, "inner", [ident("l.k")], [ident("r.k")],
                        res, left.names + right.names)
        assert sorted(out.rows()) == [(3, 30, 3, 300), (3, 30, 3, 301)]

    def test_string_keys(self):
        left = Block(["a.s"], [np.array(["x", "y", "z"], object)])
        right = Block(["b.s", "b.n"],
                      [np.array(["y", "z", "z"], object),
                       np.array([1, 2, 3], np.int64)])
        out = hash_join(left, right, "inner", [ident("a.s")], [ident("b.s")],
                        None, left.names + right.names)
        assert sorted(out.rows()) == [("y", "y", 1), ("z", "z", 2),
                                      ("z", "z", 3)]


class TestHashPartition:
    def test_partition_consistency(self):
        # equal keys land on the same partition from different blocks
        b1 = Block(["k"], [np.array([1, 2, 3, 4, 5], np.int64)])
        b2 = Block(["k"], [np.array([5, 4, 3, 2, 1], np.int64)])
        p1 = hash_partition(b1, [ident("k")], 3)
        p2 = hash_partition(b2, [ident("k")], 3)
        loc1 = {int(v): i for i, p in enumerate(p1)
                for v in p.column("k")}
        loc2 = {int(v): i for i, p in enumerate(p2)
                for v in p.column("k")}
        assert loc1 == loc2

    def test_all_rows_kept(self):
        b = Block(["k", "s"], [np.arange(100, dtype=np.int64),
                               np.array([f"v{i}" for i in range(100)],
                                        object)])
        parts = hash_partition(b, [ident("k"), ident("s")], 4)
        assert sum(p.num_rows for p in parts) == 100


# ---------------------------------------------------------------------------
# end-to-end distributed queries vs numpy oracle
# ---------------------------------------------------------------------------

class TestDistributedQueries:
    def test_join_group_by(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT d.d_year, SUM(lo.lo_revenue) AS rev "
            "FROM lineorder lo JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "WHERE lo.lo_discount BETWEEN 1 AND 3 "
            "GROUP BY d.d_year ORDER BY d.d_year LIMIT 100"))
        lo, d = t["lineorder"], t["dates"]
        mask = (lo["lo_discount"] >= 1) & (lo["lo_discount"] <= 3)
        year = d["d_year"][lo["lo_orderdate"]]
        want = {}
        for y, r, m in zip(year, lo["lo_revenue"], mask):
            if m:
                want[int(y)] = want.get(int(y), 0) + int(r)
        assert [(int(a), int(b)) for a, b in rows] == \
            sorted(want.items())

    def test_ssb_q2_shape(self, mse):
        """SSB Q2.1: 3-way star join + group by + 2-key order."""
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT SUM(lo.lo_revenue) AS rev, d.d_year, p.p_brand1 "
            "FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "JOIN part p ON lo.lo_partkey = p.p_partkey "
            "JOIN supplier s ON lo.lo_suppkey = s.s_suppkey "
            "WHERE p.p_category = 'MFGR#2' AND s.s_region = 'AMERICA' "
            "GROUP BY d.d_year, p.p_brand1 "
            "ORDER BY d.d_year, p.p_brand1 LIMIT 1000"))
        lo, d, p, s = t["lineorder"], t["dates"], t["part"], t["supplier"]
        cat = p["p_category"][lo["lo_partkey"]]
        reg = s["s_region"][lo["lo_suppkey"]]
        mask = (cat == "MFGR#2") & (reg == "AMERICA")
        year = d["d_year"][lo["lo_orderdate"]]
        brand = p["p_brand1"][lo["lo_partkey"]]
        want = {}
        for m, y, b, r in zip(mask, year, brand, lo["lo_revenue"]):
            if m:
                want[(int(y), str(b))] = want.get((int(y), str(b)), 0) + int(r)
        want_rows = [(v, y, b) for (y, b), v in sorted(want.items())]
        assert [(int(a), int(b), str(c)) for a, b, c in rows] == want_rows
        assert len(rows) > 1

    def test_selection_join_limit(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT lo.lo_orderkey, d.d_year FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "ORDER BY lo.lo_orderkey LIMIT 7"))
        assert len(rows) == 7
        assert [int(r[0]) for r in rows] == list(range(7))

    def test_left_join_distributed(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT COUNT(*) AS c FROM dates d "
            "LEFT JOIN part p ON d.d_datekey = p.p_partkey"))
        # every date row appears exactly once (part keys 0..59 match 1:1)
        assert int(rows[0][0]) == 300

    def test_agg_no_group(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT SUM(lo.lo_revenue) AS s, COUNT(*) AS c, "
            "AVG(lo.lo_discount) AS a FROM lineorder lo "
            "JOIN supplier s ON lo.lo_suppkey = s.s_suppkey "
            "WHERE s.s_region = 'ASIA'"))
        lo, s = t["lineorder"], t["supplier"]
        mask = s["s_region"][lo["lo_suppkey"]] == "ASIA"
        assert int(rows[0][0]) == int(lo["lo_revenue"][mask].sum())
        assert int(rows[0][1]) == int(mask.sum())
        assert abs(float(rows[0][2]) -
                   float(lo["lo_discount"][mask].mean())) < 1e-9

    def test_having(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT lo.lo_suppkey, COUNT(*) AS c FROM lineorder lo "
            "GROUP BY lo.lo_suppkey HAVING COUNT(*) > 80 "
            "ORDER BY lo.lo_suppkey LIMIT 100"))
        lo = t["lineorder"]
        counts = np.bincount(lo["lo_suppkey"], minlength=25)
        want = [(int(k), int(c)) for k, c in enumerate(counts) if c > 80]
        assert [(int(a), int(b)) for a, b in rows] == want
        assert rows  # shape sanity: the threshold keeps some groups

    def test_subquery_from(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT sub.y, COUNT(*) AS c FROM "
            "(SELECT d_year AS y FROM dates WHERE d_month <= 6) AS sub "
            "GROUP BY sub.y ORDER BY sub.y LIMIT 10"))
        d = t["dates"]
        mask = d["d_month"] <= 6
        want = {}
        for y, m in zip(d["d_year"], mask):
            if m:
                want[int(y)] = want.get(int(y), 0) + 1
        assert [(int(a), int(b)) for a, b in rows] == sorted(want.items())

    def test_post_aggregation_expr(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT d.d_year, SUM(lo.lo_revenue) - SUM(lo.lo_supplycost) "
            "AS profit FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "GROUP BY d.d_year ORDER BY d.d_year LIMIT 10"))
        lo, d = t["lineorder"], t["dates"]
        year = d["d_year"][lo["lo_orderdate"]]
        want = {}
        for y, r, c in zip(year, lo["lo_revenue"], lo["lo_supplycost"]):
            want[int(y)] = want.get(int(y), 0) + int(r) - int(c)
        assert [(int(a), int(b)) for a, b in rows] == sorted(want.items())

    def test_cross_join(self, mse):
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT COUNT(*) AS c FROM part p CROSS JOIN supplier s"))
        assert int(rows[0][0]) == 60 * 25

    def test_error_propagates(self, mse):
        disp, _ = mse
        resp = disp.submit(
            "SELECT nosuch.col FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey")
        assert resp.exceptions

    def test_unknown_table(self, mse):
        disp, _ = mse
        resp = disp.submit("SELECT a.x FROM nope a JOIN dates d ON a.x = d.d_datekey")
        assert resp.exceptions


class TestReviewRegressions:
    def test_where_on_null_supplying_side_not_pushed(self, mse):
        """WHERE b.x = v after LEFT JOIN must eliminate unmatched rows,
        not convert them into NULL-padded matches (pushdown hazard)."""
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT COUNT(*) AS c FROM dates d "
            "LEFT JOIN part p ON d.d_datekey = p.p_partkey "
            "WHERE p.p_category = 'MFGR#2'"))
        p, d = t["part"], t["dates"]
        matched = np.isin(d["d_datekey"], p["p_partkey"])
        keys = d["d_datekey"][matched]
        want = int((p["p_category"][keys] == "MFGR#2").sum())
        assert int(rows[0][0]) == want

    def test_subquery_order_limit_sees_all_shards(self, mse):
        """An inner ORDER BY LIMIT must consider every worker's shard."""
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT sub.k FROM (SELECT lo_orderkey AS k FROM lineorder "
            "ORDER BY lo_orderkey DESC LIMIT 3) AS sub ORDER BY sub.k LIMIT 3"))
        n = len(t["lineorder"]["lo_orderkey"])
        assert [int(r[0]) for r in rows] == [n - 3, n - 2, n - 1]

    def test_join_on_aggregate_output(self, mse):
        """Join key from a derived-table aggregate (object dtype) must
        hash-partition identically to the int column on the other side."""
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT COUNT(*) AS c FROM lineorder lo "
            "JOIN (SELECT lo_suppkey AS sk, COUNT(*) AS n FROM lineorder "
            "GROUP BY lo_suppkey) AS sub ON lo.lo_suppkey = sub.sk"))
        # every row matches exactly its suppkey's group row
        assert int(rows[0][0]) == len(t["lineorder"]["lo_suppkey"])

    def test_scan_columns_pruned(self):
        from pinot_tpu.mse.logical import Scan
        q = parse_mse_sql(
            "SELECT d.d_year, SUM(lo.lo_revenue) FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "GROUP BY d.d_year")
        cat = {"lineorder": ["lo_orderdate", "lo_revenue", "lo_discount",
                             "lo_quantity"],
               "dates": ["d_datekey", "d_year", "d_month"]}
        plan = build_logical(q, cat)

        def scans(n, out):
            if isinstance(n, Scan):
                out.append(n)
            for c in n.inputs:
                scans(c, out)
            return out

        by_table = {s.table: s for s in scans(plan, [])}
        assert set(by_table["lineorder"].columns) == \
            {"lo_orderdate", "lo_revenue"}
        assert set(by_table["dates"].columns) == {"d_datekey", "d_year"}

    def test_deep_join_no_deadlock(self, mse):
        """Many receive-blocked stage instances must not starve (one
        thread per stage instance, not a bounded pool)."""
        disp, t = mse
        rows = _rows(disp.submit(
            "SELECT COUNT(*) AS c FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "JOIN part p ON lo.lo_partkey = p.p_partkey "
            "JOIN supplier s ON lo.lo_suppkey = s.s_suppkey "
            "JOIN dates d2 ON lo.lo_orderdate = d2.d_datekey "
            "JOIN part p2 ON lo.lo_partkey = p2.p_partkey "
            "JOIN supplier s2 ON lo.lo_suppkey = s2.s_suppkey"))
        assert int(rows[0][0]) == len(t["lineorder"]["lo_orderkey"])

    def test_desc_sort_large_longs(self, mse):
        from pinot_tpu.mse.operators import sort_block
        big = 9007199254740992  # 2^53
        b = Block(["v"], [np.array([big, big + 1, big - 1], np.int64)])
        out = sort_block(b, [ident("v")], [False], -1, 0)
        assert out.column("v").tolist() == [big + 1, big, big - 1]


# ---------------------------------------------------------------------------
# SSB Q2.1 across a real 2-server MiniCluster (segments + TCP + mailboxes)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ssb_cluster(tmp_path_factory):
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    tmp = tmp_path_factory.mktemp("ssb")
    tables = _tables()

    def build(name, cols, dims, metrics, num_segments=2):
        schema = Schema.from_dict({
            "schemaName": name,
            "dimensionFieldSpecs": [
                {"name": c, "dataType": dims[c]} for c in dims],
            "metricFieldSpecs": [
                {"name": c, "dataType": metrics[c]} for c in metrics],
        })
        tc = TableConfig.from_dict({"tableName": name, "tableType": "OFFLINE"})
        creator = SegmentCreator(tc, schema)
        n = len(next(iter(cols.values())))
        segs = []
        for i in range(num_segments):
            idx = np.arange(n) % num_segments == i
            part = {c: np.asarray(v)[idx] for c, v in cols.items()}
            d = str(tmp / f"{name}_{i}")
            creator.build(part, d, f"{name}_{i}")
            segs.append(load_segment(d))
        return segs

    c = MiniCluster(num_servers=2)
    lo_segs = build("lineorder", tables["lineorder"], {
        "lo_orderkey": "LONG", "lo_partkey": "LONG", "lo_suppkey": "LONG",
        "lo_orderdate": "LONG"}, {
        "lo_revenue": "LONG", "lo_supplycost": "LONG",
        "lo_discount": "LONG", "lo_quantity": "LONG"}, 4)
    d_segs = build("dates", tables["dates"], {
        "d_datekey": "LONG", "d_year": "LONG", "d_month": "LONG"}, {}, 1)
    p_segs = build("part", tables["part"], {
        "p_partkey": "LONG", "p_category": "STRING",
        "p_brand1": "STRING"}, {}, 1)
    s_segs = build("supplier", tables["supplier"], {
        "s_suppkey": "LONG", "s_region": "STRING"}, {}, 1)
    c.start(with_http=False)
    for t in ("lineorder", "dates", "part", "supplier"):
        c.add_table(t)
    for i, seg in enumerate(lo_segs):
        c.add_segment("lineorder", seg, server_idx=i % 2)
    c.add_segment("dates", d_segs[0], server_idx=0)
    c.add_segment("part", p_segs[0], server_idx=1)
    c.add_segment("supplier", s_segs[0], server_idx=0)
    yield c, tables
    c.stop()


class TestSsbMiniCluster:
    def test_ssb_q21(self, ssb_cluster):
        """SSB Q2.1 shape through the broker: parse fallback to MSE,
        leaf scans on real segments, TCP mailbox shuffle, parity vs numpy."""
        c, t = ssb_cluster
        resp = c.query(
            "SELECT SUM(lo.lo_revenue) AS rev, d.d_year, p.p_brand1 "
            "FROM lineorder lo "
            "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
            "JOIN part p ON lo.lo_partkey = p.p_partkey "
            "JOIN supplier s ON lo.lo_suppkey = s.s_suppkey "
            "WHERE p.p_category = 'MFGR#2' AND s.s_region = 'AMERICA' "
            "GROUP BY d.d_year, p.p_brand1 "
            "ORDER BY d.d_year, p.p_brand1 LIMIT 1000")
        assert not resp.exceptions, resp.exceptions
        lo, d, p, s = t["lineorder"], t["dates"], t["part"], t["supplier"]
        mask = (p["p_category"][lo["lo_partkey"]] == "MFGR#2") & \
               (s["s_region"][lo["lo_suppkey"]] == "AMERICA")
        year = d["d_year"][lo["lo_orderdate"]]
        brand = p["p_brand1"][lo["lo_partkey"]]
        want = {}
        for m, y, b, r in zip(mask, year, brand, lo["lo_revenue"]):
            if m:
                want[(int(y), str(b))] = want.get((int(y), str(b)), 0) + int(r)
        want_rows = [(v, y, b) for (y, b), v in sorted(want.items())]
        got = [(int(a), int(b), str(c_)) for a, b, c_ in
               resp.result_table.rows]
        assert got == want_rows
        assert len(got) > 1

    def test_single_stage_still_works(self, ssb_cluster):
        c, t = ssb_cluster
        resp = c.query("SELECT COUNT(*) FROM lineorder")
        assert not resp.exceptions
        assert resp.rows[0][0] == len(t["lineorder"]["lo_orderkey"])

    def test_mse_option_routes_single_table(self, ssb_cluster):
        c, t = ssb_cluster
        resp = c.query(
            "SELECT COUNT(*) AS c FROM lineorder lo WHERE lo.lo_discount = 5 "
            "OPTION(useMultistageEngine=true)")
        assert not resp.exceptions, resp.exceptions
        want = int((t["lineorder"]["lo_discount"] == 5).sum())
        assert int(resp.result_table.rows[0][0]) == want
