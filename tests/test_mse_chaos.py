"""MSE reliability under seeded chaos (ISSUE 7).

The multi-stage engine at the single-stage bar: end-to-end deadlines with
out-of-band cancel fan-out, worker-kill detection mid-shuffle, torn
mailbox frames as typed errors, leaf-stage output caching, and per-seed
exact replay of chaos schedules — mirroring tests/test_reliability.py
for the scatter path.
"""
import json
import threading
import time

import numpy as np
import pytest

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.dispatcher import QueryDispatcher
from pinot_tpu.mse.mailbox import (
    FLAG_EOS, MailboxAborted, MailboxError, MailboxService, MailboxTimeout)
from pinot_tpu.mse.operators import filter_block
from pinot_tpu.mse.runtime import MseWorker
from pinot_tpu.utils.failpoints import (
    FailpointError, FaultSchedule, SimulatedCrash, failpoints)

#: slack on top of a query budget for scheduler noise + cancel fan-out;
#: the armed chaos delays are always far above budget + EPS so a pass
#: proves the deadline fired, not that the chaos finished
EPS_S = 1.5


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoints.clear()


# ---------------------------------------------------------------------------
# mailbox primitives: hard wall, abort/poison, sender-death probe
# ---------------------------------------------------------------------------

class TestMailboxPrimitives:
    @pytest.fixture()
    def svc(self):
        s = MailboxService("inst_a")
        s.start()
        yield s
        s.stop()

    def test_deadline_wall_is_absolute(self, svc):
        t0 = time.time()
        with pytest.raises(MailboxTimeout):
            list(svc.receive_all("q1|1|0|0", num_senders=1,
                                 deadline=time.time() + 0.3))
        assert time.time() - t0 < 0.3 + EPS_S
        assert svc.queue_count() == 0

    def test_abort_wakes_blocked_receiver_and_leaves_no_queues(self, svc):
        got = []

        def rx():
            try:
                list(svc.receive_all("q2|1|0|0", num_senders=1,
                                     timeout=30.0))
            except MailboxError as e:
                got.append(e)

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        deadline = time.time() + 5
        while svc.queue_count("q2") == 0 and time.time() < deadline:
            time.sleep(0.01)
        svc.abort_query("q2", "cancelled by test")
        t.join(timeout=5)
        assert got and "cancelled by test" in str(got[0])
        # late frames from a still-running sender are dropped, later
        # receivers fail fast, and the queue map stays empty
        svc.send(svc.address, "q2|1|0|0", b"late", FLAG_EOS)
        assert svc.queue_count() == 0
        with pytest.raises(MailboxAborted):
            list(svc.receive_all("q2|1|0|0", num_senders=1, timeout=5.0))

    def test_dead_sender_detected_before_timeout(self, svc):
        # a listener that came up and went away: the probe sees a refused
        # connect and raises typed, long before the 30s budget
        peer = MailboxService("inst_b")
        peer.start()
        dead_addr = peer.address
        peer.stop()
        t0 = time.time()
        with pytest.raises(MailboxError, match="dead"):
            list(svc.receive_all("q3|1|0|0", num_senders=1, timeout=30.0,
                                 sender_addresses=[dead_addr]))
        assert time.time() - t0 < 5.0

    def test_send_retries_once_on_fresh_socket(self, svc):
        # plant a dead pooled socket for a live destination: the send
        # must transparently redial instead of failing the stage
        peer = MailboxService("inst_c")
        peer.start()
        try:
            svc.send(peer.address, "q4|1|0|0", b"x")  # pools a socket
            with svc._conn_lock:
                svc._conns[peer.address].close()  # stale pooled socket
            before = svc._metrics.meter("mse_mailbox_retries",
                                        labels={"instance": "inst_a"})
            for _ in range(3):  # close() may only surface on later sends
                svc.send(peer.address, "q4|1|0|0", b"y", FLAG_EOS)
            got = list(peer.receive_all("q4|1|0|0", num_senders=1,
                                        timeout=5.0))
            assert got and got[-1] == b"y"
            after = svc._metrics.meter("mse_mailbox_retries",
                                       labels={"instance": "inst_a"})
            assert after >= before
        finally:
            peer.stop()


# ---------------------------------------------------------------------------
# in-process engine harness (fresh per test: chaos kills workers)
# ---------------------------------------------------------------------------

def _tables(n=1200):
    rng = np.random.default_rng(5)
    return {
        "fact": {"k": rng.integers(0, 8, n).astype(np.int64),
                 "v": rng.integers(1, 100, n).astype(np.int64)},
        "dim": {"k": np.arange(8, dtype=np.int64),
                "name": np.array([f"g{i}" for i in range(8)], object)},
    }


JOIN_SQL = ("SELECT d.name, SUM(f.v) AS s FROM fact f "
            "JOIN dim d ON f.k = d.k GROUP BY d.name "
            "ORDER BY d.name LIMIT 100")


def _expected_join(tables):
    want = {}
    for k, v in zip(tables["fact"]["k"], tables["fact"]["v"]):
        name = str(tables["dim"]["name"][int(k)])
        want[name] = want.get(name, 0) + int(v)
    return sorted(want.items())


def _make_engine(tables, hosting):
    """Two MseWorkers with shard scans derived from each table's host
    list (a table hosted on one worker is scanned whole there)."""
    insts = ["server_0", "server_1"]

    def make_scan(inst):
        def scan(table, columns, filt):
            hosts = hosting[table]
            if inst not in hosts:
                return Block(columns,
                             [np.empty(0, object) for _ in columns])
            shard, nshards = hosts.index(inst), len(hosts)
            t = tables[table]
            n = len(next(iter(t.values())))
            idx = np.arange(n) % nshards == shard
            b = Block(list(t), [t[c][idx] for c in t])
            if filt is not None:
                b = filter_block(b, filt)
            return b.select(columns)
        return scan

    workers = {}
    for i in insts:
        w = MseWorker(i, make_scan(i))
        w.start()
        workers[i] = w
    catalog = {k: list(v) for k, v in tables.items()}
    disp = QueryDispatcher(workers, lambda: catalog,
                           lambda t: list(hosting[t]))
    return disp, workers


def _stop_engine(disp, workers):
    for w in workers.values():
        w.stop()
    disp.stop()


def _queues_drain(services, timeout_s=6.0) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if all(s.queue_count() == 0 for s in services):
            return True
        time.sleep(0.05)
    return False


@pytest.mark.chaos
class TestWorkerKillMidShuffle:
    def _run_workload(self, seed):
        """One seeded run: kill server_1's join-stage instance (stage 2)
        on the first query, then retry twice. Returns (outcomes,
        decision journal)."""
        tables = _tables()
        # fact/dim live on server_0 only, so a dead server_1 loses no
        # data — the retry can route around it and converge exactly
        hosting = {"fact": ["server_0"], "dim": ["server_0"]}
        sched = FaultSchedule([
            ("mse.worker.crash",
             {"error": SimulatedCrash("chaos kill"), "times": 1,
              "seed": seed,
              "where": {"instance": "server_1", "stage": 2}}),
        ])
        sched.arm()
        disp, workers = _make_engine(tables, hosting)
        try:
            outcomes = []
            for _ in range(3):
                resp = disp.submit(JOIN_SQL)
                outcomes.append((tuple(e["errorCode"]
                                       for e in resp.exceptions),
                                 resp.partial_result,
                                 [tuple(r) for r in resp.rows]))
            decisions = json.dumps(sched.decisions()[0][:1])
            mailboxes = [w.mailbox for w in workers.values()
                         if w.alive] + [disp.mailbox]
            assert _queues_drain(mailboxes), "orphaned mailbox queues"
            return outcomes, decisions, resp
        finally:
            _stop_engine(disp, workers)
            sched.disarm()

    def test_kill_converges_and_replays(self):
        t0 = time.time()
        out_a, dec_a, _ = self._run_workload(seed=77)
        # query 1 died with the worker: typed errorCode-250 partial,
        # returned quickly (death detected, not waited out)
        assert out_a[0][0] == (250,) and out_a[0][1] is True
        # queries 2+3 (the retry): dead worker routed around, exact rows
        want = [(n, s) for n, s in _expected_join(_tables())]
        assert out_a[1][0] == ()
        assert [(str(a), int(b)) for a, b in out_a[1][2]] == want
        assert out_a[1] == out_a[2]
        assert time.time() - t0 < 30.0
        # same seed, fresh cluster: identical outcomes and an identical
        # (byte-identical) decision journal
        out_b, dec_b, _ = self._run_workload(seed=77)
        assert out_a == out_b
        assert dec_a == dec_b


@pytest.mark.chaos
class TestDeadlineAndCancel:
    def test_deadline_miss_typed_250_within_budget(self):
        tables = _tables()
        disp, workers = _make_engine(
            tables, {"fact": ["server_0", "server_1"],
                     "dim": ["server_0", "server_1"]})
        try:
            with failpoints.armed("mse.stage.execute", delay=8.0,
                                  where={"instance": "server_0"}):
                t0 = time.time()
                resp = disp.submit(
                    JOIN_SQL[:-len(" LIMIT 100")]
                    + " LIMIT 100 OPTION(timeoutMs=400)")
                elapsed = time.time() - t0
            assert resp.exceptions, "deadline miss must surface"
            assert resp.exceptions[0]["errorCode"] == 250
            assert resp.partial_result is True
            # honest per-stage accounting rides in the message
            assert "budget" in resp.exceptions[0]["message"]
            assert elapsed < 0.4 + EPS_S, \
                f"took {elapsed:.2f}s for a 400ms budget"
            mailboxes = [w.mailbox for w in workers.values()] + \
                [disp.mailbox]
            assert _queues_drain(mailboxes, timeout_s=12.0), \
                "orphaned mailbox queues after a deadline miss"
        finally:
            _stop_engine(disp, workers)

    def test_client_cancel_fans_out(self):
        tables = _tables()
        disp, workers = _make_engine(
            tables, {"fact": ["server_0", "server_1"],
                     "dim": ["server_0", "server_1"]})
        try:
            done = []
            with failpoints.armed("mse.stage.execute", delay=8.0,
                                  where={"instance": "server_0"}):
                t = threading.Thread(
                    target=lambda: done.append(disp.submit(JOIN_SQL)),
                    daemon=True)
                t0 = time.time()
                t.start()
                deadline = time.time() + 5
                while not disp.inflight() and time.time() < deadline:
                    time.sleep(0.01)
                qids = disp.inflight()
                assert qids, "query never registered in flight"
                assert disp.cancel(qids[0]) is True
                t.join(timeout=10)
            assert done, "cancelled query never answered"
            resp = done[0]
            assert resp.exceptions and \
                resp.exceptions[0]["errorCode"] == 250
            assert resp.partial_result is True
            assert time.time() - t0 < 8.0, "cancel waited out the chaos"
            # an unknown id is a no-op, not an error
            assert disp.cancel("mse_nope_1") is False
        finally:
            _stop_engine(disp, workers)


# ---------------------------------------------------------------------------
# torn frames
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestTornFrame:
    def test_torn_mailbox_frame_is_typed_error_not_hang(self):
        tables = _tables()
        disp, workers = _make_engine(
            tables, {"fact": ["server_0", "server_1"],
                     "dim": ["server_0", "server_1"]})
        try:
            with failpoints.armed("mse.mailbox.send", torn=True,
                                  where={"instance": "server_0"}):
                t0 = time.time()
                resp = disp.submit(JOIN_SQL)
                elapsed = time.time() - t0
            assert resp.exceptions, "torn frame must surface"
            assert resp.exceptions[0]["errorCode"] == 250
            assert elapsed < 10.0, "torn frame degenerated into a wait"
            # typed all the way: the message names the decode failure
            # or the poisoned mailbox, never a bare timeout
            msg = resp.exceptions[0]["message"]
            assert "Mailbox" in msg or "undecodable" in msg or \
                "aborted" in msg
        finally:
            _stop_engine(disp, workers)


# ---------------------------------------------------------------------------
# per-seed exact replay on the broker dispatch edge
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestSeededReplay:
    def _run(self, seed):
        tables = _tables()
        sched = FaultSchedule([
            ("mse.dispatch.stage",
             {"error": FailpointError("chaos"), "probability": 0.15,
              "seed": seed, "where": {"instance": "server_1"}}),
        ])
        sched.arm()
        disp, workers = _make_engine(
            tables, {"fact": ["server_0", "server_1"],
                     "dim": ["server_0", "server_1"]})
        try:
            outcomes = []
            for _ in range(8):
                resp = disp.submit(JOIN_SQL)
                outcomes.append(bool(resp.exceptions))
            return outcomes, json.dumps(sched.decisions())
        finally:
            _stop_engine(disp, workers)
            sched.disarm()

    def test_same_seed_byte_identical_journal(self):
        out_a, dec_a = self._run(seed=4242)
        out_b, dec_b = self._run(seed=4242)
        assert dec_a == dec_b, "same seed must replay byte-identical"
        assert out_a == out_b
        assert any(out_a) and not all(out_a)
        out_c, dec_c = self._run(seed=9)
        assert dec_c != dec_a


# ---------------------------------------------------------------------------
# MiniCluster: tier-1 smoke under one seeded mailbox delay + stage cache
# ---------------------------------------------------------------------------

def _build_cluster(tmp_path, chaos=None, num_servers=2):
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    rng = np.random.default_rng(17)
    n = 600
    fact = {"k": rng.integers(0, 6, n).astype(np.int64),
            "v": rng.integers(1, 50, n).astype(np.int64)}
    dim = {"k": np.arange(6).astype(np.int64),
           "name": [f"n{i}" for i in range(6)]}

    fact_schema = Schema.from_dict({
        "schemaName": "fact",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    dim_schema = Schema.from_dict({
        "schemaName": "dim",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"},
                                {"name": "name", "dataType": "STRING"}]})
    c = MiniCluster(num_servers=num_servers, chaos=chaos)
    c.start()
    c.add_table("fact")
    c.add_table("dim")
    fc = SegmentCreator(
        TableConfig.from_dict({"tableName": "fact",
                               "tableType": "OFFLINE"}), fact_schema)
    dc = SegmentCreator(
        TableConfig.from_dict({"tableName": "dim",
                               "tableType": "OFFLINE"}), dim_schema)
    for i in range(2):
        idx = np.arange(n) % 2 == i
        d = str(tmp_path / f"fact_{i}")
        fc.build({k: np.asarray(v)[idx] for k, v in fact.items()},
                 d, f"fact_{i}")
        c.add_segment("fact", load_segment(d), server_idx=i % num_servers)
    d = str(tmp_path / "dim_0")
    dc.build({k: np.asarray(v) for k, v in dim.items()}, d, "dim_0")
    c.add_segment("dim", load_segment(d), server_idx=0)
    return c, fact, dim


CLUSTER_JOIN = ("SELECT d.name, SUM(f.v) AS s FROM fact f "
                "JOIN dim d ON f.k = d.k GROUP BY d.name "
                "ORDER BY d.name LIMIT 100")


def _cluster_expected(fact, dim):
    want = {}
    for k, v in zip(fact["k"], fact["v"]):
        want[dim["name"][int(k)]] = want.get(dim["name"][int(k)], 0) + int(v)
    return [(n, s) for n, s in sorted(want.items())]


@pytest.mark.chaos
class TestClusterChaosSmoke:
    def test_join_survives_seeded_mailbox_delay(self, tmp_path):
        """Tier-1 guard that the MSE chaos wiring itself can't rot: a
        MiniCluster join under one seeded mailbox delay still answers
        exactly, and the schedule records its decisions."""
        sched = FaultSchedule([
            ("mse.mailbox.send", {"delay": 0.05, "times": 2, "seed": 11}),
        ])
        c, fact, dim = _build_cluster(tmp_path, chaos=sched)
        try:
            resp = c.query(CLUSTER_JOIN)
            assert not resp.exceptions, resp.exceptions
            got = [(str(a), int(b)) for a, b in resp.result_table.rows]
            assert got == _cluster_expected(fact, dim)
            assert sched.failpoints[0].fired == 2
            assert sched.decisions()[0][:2] == [(True, 0.05), (True, 0.05)]
        finally:
            c.stop()


class TestStageOutputCache:
    def test_warm_hit_epoch_invalidation_no_partials(self, tmp_path):
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment
        from pinot_tpu.models.schema import Schema
        from pinot_tpu.models.table_config import TableConfig

        c, fact, dim = _build_cluster(tmp_path)
        try:
            caches = [s.mse_worker.stage_cache for s in c.servers]
            r1 = c.query(CLUSTER_JOIN)
            assert not r1.exceptions, r1.exceptions
            assert sum(len(x) for x in caches) > 0, \
                "leaf-stage outputs must populate the cache"
            hits0 = sum(x.stats.hits for x in caches)
            r2 = c.query(CLUSTER_JOIN)
            assert not r2.exceptions
            assert r2.result_table.rows == r1.result_table.rows
            assert sum(x.stats.hits for x in caches) > hits0, \
                "second run must serve leaf stages from cache"

            # epoch invalidation by construction: a new fact segment
            # changes the version set, so the key stops hitting and the
            # answer reflects the new rows
            schema = Schema.from_dict({
                "schemaName": "fact",
                "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
                "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
            creator = SegmentCreator(
                TableConfig.from_dict({"tableName": "fact",
                                       "tableType": "OFFLINE"}), schema)
            d = str(tmp_path / "fact_new")
            creator.build({"k": np.array([0], np.int64),
                           "v": np.array([10_000], np.int64)},
                          d, "fact_new")
            c.add_segment("fact", load_segment(d), server_idx=0)
            r3 = c.query(CLUSTER_JOIN)
            assert not r3.exceptions
            base = dict((str(a), int(b)) for a, b in r1.result_table.rows)
            got = dict((str(a), int(b)) for a, b in r3.result_table.rows)
            assert got["n0"] == base["n0"] + 10_000, \
                "post-swap answer must reflect the new segment"

            # never cache partials: a deadline-clipped run stores nothing
            sizes = [len(x) for x in caches]
            with failpoints.armed("mse.stage.execute", delay=5.0):
                miss = c.query(CLUSTER_JOIN + " OPTION(timeoutMs=250)")
            assert miss.exceptions and \
                miss.exceptions[0]["errorCode"] == 250
            assert [len(x) for x in caches] == sizes, \
                "a deadline-clipped stage must not populate the cache"
        finally:
            c.stop()

    def test_cancelled_query_leaves_zero_orphaned_queues(self, tmp_path):
        """Non-slow orphan guard: after a cancelled (deadline-missed)
        MSE query, every worker's and the broker's mailbox queue map
        drains to empty."""
        c, _fact, _dim = _build_cluster(tmp_path)
        try:
            with failpoints.armed("mse.stage.execute", delay=2.0):
                resp = c.query(CLUSTER_JOIN + " OPTION(timeoutMs=300)")
            assert resp.exceptions and \
                resp.exceptions[0]["errorCode"] == 250
            services = [s.mse_worker.mailbox for s in c.servers] + \
                [c.mse.mailbox]
            assert _queues_drain(services, timeout_s=8.0), \
                "cancelled query left orphaned mailbox queues"
        finally:
            c.stop()
