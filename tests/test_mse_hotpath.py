"""MSE hot-path parity (ISSUE 10): leaf stages through the unified
kernel factory, pipelined intermediate stages, stage hedging, shared L2
stage cache.

Pins the tentpole properties deterministically:

  * leaf SCAN batching — `filtered_doc_ids` (the MSE join-input path)
    rides the kernel factory: fingerprint-equal doc-id scans coalesce
    into one batched topn launch, bit-identical to per-query execution,
    with zero steady-state retraces (tier-1 guard); single-stage
    selection traffic shares the same key space
  * same-cols member grouping — a stacked batch with duplicate tables
    stacks one entry per UNIQUE column set (`dispatch_batch_dedup`),
    bit-identical to per-query execution
  * adaptive batch-window sizing — window.ms=auto converges to the
    floor under tight-loop arrivals, the ceiling under sparse ones, and
    lone callers stay on the inline path (no added p50)
  * pipelined intermediate stages — chunked frames + incremental folds
    produce the same rows as the full-barrier receive
  * stage hedging — a seeded straggling leaf stage is re-issued on a
    replica peer, the hedge wins within budget, rows are bit-identical
    to a no-hedge run, and the same-seed decision journal replays
    byte-identical (`mse.stage.hedge` failpoint site)
  * L2-shared stage cache — one replica's warm leaf output serves a
    COLD replica's first leaf stage through the cache server
"""
import json
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import jax

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import kernels
from pinot_tpu.ops.dispatch import KernelDispatcher, Launch
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import FaultSchedule, failpoints
from pinot_tpu.utils.metrics import get_registry

HOLD_S = 0.3


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def build_table(tmp_path, name, num_segments, docs, seed):
    schema = Schema(name, [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC)])
    tc = TableConfig(name, TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(seed)
    out = []
    for i in range(num_segments):
        cols = {"d": rng.integers(0, 10, docs).astype(np.int32),
                "m": rng.integers(0, 100, docs).astype(np.int32)}
        p = str(tmp_path / f"{name}_{i}")
        creator.build(cols, p, f"{name}_{i}")
        out.append(load_segment(p))
    return out


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mse_hot")
    return {
        "t1": build_table(tmp, "t1", 3, 3000, 1),
        "t2": build_table(tmp, "t2", 4, 2500, 2),
        "t3": build_table(tmp, "t3", 3, 3900, 3),
    }


def make_engine(**overrides):
    return TpuOperatorExecutor(config=PinotConfiguration(overrides=overrides))


def _filter(sql_where):
    return QueryContext.from_sql(
        f"SELECT COUNT(*) FROM x WHERE {sql_where}").filter


def run_concurrent(fn_futs, hold=HOLD_S):
    """Run thunks concurrently with the dispatch ring held on the first
    pop so batch composition is deterministic (test_dispatch.py trick)."""
    failpoints.arm("server.dispatch.before", delay=hold, times=2)
    try:
        with ThreadPoolExecutor(len(fn_futs)) as pool:
            futs = [pool.submit(f) for f in fn_futs]
            return [f.result() for f in futs]
    finally:
        failpoints.disarm("server.dispatch.before")


def ids_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        if x is None or y is None:
            assert x is None and y is None
        else:
            assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# leaf scans (filtered_doc_ids) through the kernel factory
# ---------------------------------------------------------------------------

class TestLeafScanFactory:
    def test_doc_ids_coalesce_bit_identical(self, tables):
        """Fingerprint-equal doc-id scans over DIFFERENT tables share a
        stacked topn launch, bit-identical to per-query execution."""
        eng = make_engine()
        jobs = [(tables[tn], _filter(f"d < {i + 2} AND m < 90"))
                for i, tn in enumerate(
                    ["t1", "t2", "t3", "t1", "t2", "t3"])]
        singles = [eng.filtered_doc_ids(s, f) for s, f in jobs]
        reg = eng._dispatcher._metrics
        m0 = reg.meter("dispatch_batch_cross_table")
        got = run_concurrent(
            [lambda s=s, f=f: eng.filtered_doc_ids(s, f)
             for s, f in jobs])
        for g, w in zip(got, singles):
            ids_equal(g, w)
        assert reg.meter("dispatch_batch_cross_table") > m0, \
            "leaf doc-id scans never formed a stacked batch"

    def test_doc_ids_property_random_literals(self, tables):
        """Property: ANY member->table assignment with ANY literal set,
        coalesced in ANY composition, equals per-query doc ids."""
        eng = make_engine()
        rng = np.random.default_rng(7)
        names = list(tables)
        for _trial in range(3):
            k = int(rng.integers(3, 8))
            picks = [names[j] for j in rng.integers(0, len(names), k)]
            bounds = rng.integers(0, 100, size=(k, 2))
            jobs = [(tables[tn],
                     _filter(f"m BETWEEN {min(a, b)} AND {max(a, b)} "
                             f"AND d < 8"))
                    for tn, (a, b) in zip(picks, bounds)]
            singles = [eng.filtered_doc_ids(s, f) for s, f in jobs]
            got = run_concurrent(
                [lambda s=s, f=f: eng.filtered_doc_ids(s, f)
                 for s, f in jobs])
            for g, w in zip(got, singles):
                ids_equal(g, w)

    def test_selection_topn_shares_factory(self, tables):
        """Single-stage selection traffic batches through the same topn
        factory (one launch for fingerprint-equal ORDER BY queries)."""
        eng = make_engine()
        jobs = [(tables["t1"], QueryContext.from_sql(
            f"SELECT d, m FROM t1 WHERE m > {i} ORDER BY m DESC LIMIT 5"))
            for i in range(4)]

        def rows_of(results):
            return [tuple(map(tuple, r.rows)) for r in results]

        singles = [rows_of(eng.execute(s, c)[0]) for s, c in jobs]
        got = run_concurrent(
            [lambda s=s, c=c: eng.execute(s, c) for s, c in jobs])
        assert all(not rem for _r, rem in got)
        assert [rows_of(r) for r, _rem in got] == singles

    def test_steady_state_zero_retrace_leaf_scans(self, tables):
        """Tier-1 guard: warmed MSE leaf doc-id traffic (singles +
        coalesced batches) compiles NOTHING."""
        eng = make_engine()

        def round_of(base):
            jobs = [(tables[tn], _filter(f"d < {base + i}"))
                    for i, tn in enumerate(
                        ["t1", "t2", "t3", "t1", "t2", "t3"])]
            run_concurrent(
                [lambda s=s, f=f: eng.filtered_doc_ids(s, f)
                 for s, f in jobs])

        for tn in tables:  # warm singles (stage + compile per table)
            eng.filtered_doc_ids(tables[tn], _filter("d < 1"))
        round_of(1)
        round_of(2)
        before = kernels.trace_count()
        round_of(3)
        round_of(4)
        for tn in tables:
            eng.filtered_doc_ids(tables[tn], _filter("d < 5"))
        assert kernels.trace_count() == before, \
            "steady-state leaf doc-id scans re-compiled a kernel"


def _leaf_agg_ctx(table, where, group=True):
    """The exact QueryContext shape _leaf_agg_pushdown builds: huge
    limit + numGroupsLimit, select = groups + aggs."""
    base = QueryContext.from_sql(
        f"SELECT {'d, ' if group else ''}SUM(m), COUNT(*) FROM {table} "
        f"WHERE {where}" + (" GROUP BY d" if group else ""))
    q = QueryContext(
        table=table, select=base.select, aliases=[None] * len(base.select),
        distinct=False, filter=base.filter, group_by=base.group_by,
        having=None, order_by=[], limit=1 << 31, offset=0,
        options={"numGroupsLimit": str(1 << 31)})
    q._extract_aggregations()
    return q


def _agg_values(results):
    out = []
    for r in results:
        if hasattr(r, "groups"):
            out.append(tuple(sorted(
                (k, tuple(float(v) for v in inters))
                for k, inters in r.groups.items())))
        else:
            out.append(tuple(float(v) for v in r.intermediates))
    return tuple(out)


class TestMeshLeafProperty:
    """The doc-sharded mesh leg: MSE leaf_agg pushdown contexts on a
    (segments x docs) mesh engine batch through vmap-inside-shard_map,
    bit-identical to per-query execution."""

    @pytest.fixture(scope="class")
    def mesh_engine(self):
        from pinot_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(jax.devices()[:4], doc_axis=2)
        return TpuOperatorExecutor(mesh=mesh,
                                   config=PinotConfiguration())

    def test_mse_leaf_property_random_literals_and_tables(
            self, tables, mesh_engine):
        eng = mesh_engine
        rng = np.random.default_rng(17)
        names = list(tables)
        for _trial in range(2):
            k = int(rng.integers(3, 7))
            picks = [names[j] for j in rng.integers(0, len(names), k)]
            bounds = rng.integers(0, 100, size=(k, 2))
            jobs = [(tables[tn], _leaf_agg_ctx(
                tn, f"m BETWEEN {min(a, b)} AND {max(a, b)}",
                group=False))
                for tn, (a, b) in zip(picks, bounds)]
            singles = [_agg_values(eng.execute(s, c)[0]) for s, c in jobs]
            got = run_concurrent(
                [lambda s=s, c=c: eng.execute(s, c) for s, c in jobs])
            assert all(not rem for _r, rem in got)
            assert [_agg_values(r) for r, _rem in got] == singles

    def test_single_device_leaf_agg_group_by_property(self, tables):
        """Same bar for the grouped leaf_agg pushdown shape on the
        default engine (the MiniCluster serving path)."""
        eng = make_engine()
        rng = np.random.default_rng(23)
        names = list(tables)
        jobs = [(tables[names[int(rng.integers(0, 3))]], _leaf_agg_ctx(
            "x", f"m BETWEEN {a} AND {a + 50}")) for a in
            rng.integers(0, 60, 5)]
        singles = [_agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        got = run_concurrent(
            [lambda s=s, c=c: eng.execute(s, c) for s, c in jobs])
        assert [_agg_values(r) for r, _rem in got] == singles


# ---------------------------------------------------------------------------
# same-cols member grouping (stacked-batch dedup)
# ---------------------------------------------------------------------------

class TestMemberDedup:
    def test_duplicate_tables_share_stack_entry_bit_identical(self, tables):
        """A stacked batch holding duplicate tables dedups the stack to
        one entry per unique column set — results bit-identical, and the
        dispatch_batch_dedup meter counts the spared stack entries."""
        eng = make_engine()

        def agg_values(results):
            return [tuple(float(v) for v in r.intermediates)
                    for r in results]

        jobs = [(tables[tn], QueryContext.from_sql(
            f"SELECT SUM(m), COUNT(*), MIN(m) FROM x WHERE m < {60 + i}"))
            for i, tn in enumerate(["t1", "t1", "t2", "t2", "t3", "t3"])]
        singles = [agg_values(eng.execute(s, c)[0]) for s, c in jobs]
        reg = eng._dispatcher._metrics
        d0 = reg.meter("dispatch_batch_dedup")
        got = run_concurrent(
            [lambda s=s, c=c: eng.execute(s, c) for s, c in jobs])
        assert all(not rem for _r, rem in got)
        assert [agg_values(r) for r, _rem in got] == singles
        assert reg.meter("dispatch_batch_dedup") > d0, \
            "duplicate-table stacked batch never deduped its stack"


# ---------------------------------------------------------------------------
# adaptive batch-window sizing (window.ms=auto)
# ---------------------------------------------------------------------------

class TestAdaptiveWindow:
    def _auto(self):
        return KernelDispatcher(config=PinotConfiguration(overrides={
            "pinot.server.dispatch.batch.window.ms": "auto"}))

    def test_static_default_unchanged(self):
        d = KernelDispatcher(config=PinotConfiguration())
        assert not d.window_auto
        assert d.current_window_s() == pytest.approx(0.002)

    def test_tight_loop_converges_to_floor(self):
        d = self._auto()
        with d._cv:
            for _ in range(64):
                d._note_arrival_locked()
        assert d.current_window_s() == pytest.approx(0.5 * 0.002)

    def test_sparse_arrivals_clamp_to_ceiling(self):
        d = self._auto()
        with d._cv:
            for _ in range(8):
                d._note_arrival_locked()
                d._last_arrival -= 10.0  # pretend 10s since last submit
            d._note_arrival_locked()
        assert d.current_window_s() == pytest.approx(4.0 * 0.002)

    def test_lone_caller_steady_state_inline_no_added_p50(self):
        """A lone caller in auto mode stays on the inline fast path:
        every submit resolves synchronously (no window wait, no ring
        thread), so steady-state p50 gains nothing."""
        d = self._auto()
        for i in range(16):
            fut = d.submit(Launch(call=lambda: np.full(3, 1.0),
                                  batch_key=("plan", 1)))
            assert fut.done(), "lone submit left the inline fast path"
            assert np.array_equal(fut.result(), np.full(3, 1.0))
        assert d._thread is None or not d._thread.is_alive()
        # and the learned window sits at the floor (tight loop)
        assert d.current_window_s() == pytest.approx(0.5 * 0.002)


# ---------------------------------------------------------------------------
# pipelined intermediate stages
# ---------------------------------------------------------------------------

def _mse_tables(n=1200):
    rng = np.random.default_rng(5)
    return {
        "fact": {"k": rng.integers(0, 8, n).astype(np.int64),
                 "v": rng.integers(1, 100, n).astype(np.int64)},
        "dim": {"k": np.arange(8, dtype=np.int64),
                "name": np.array([f"g{i}" for i in range(8)], object)},
    }


JOIN_SQL = ("SELECT d.name, SUM(f.v) AS s FROM fact f "
            "JOIN dim d ON f.k = d.k GROUP BY d.name "
            "ORDER BY d.name LIMIT 100")


def _expected_join(tables):
    want = {}
    for k, v in zip(tables["fact"]["k"], tables["fact"]["v"]):
        name = str(tables["dim"]["name"][int(k)])
        want[name] = want.get(name, 0) + int(v)
    return sorted(want.items())


def _make_engine(tables, hosting, worker_config=None,
                 replica_tables=(), **disp_kwargs):
    """Two MseWorkers with shard scans (test_mse_chaos harness) plus
    optional worker config / dispatcher kwargs. Tables named in
    `replica_tables` scan as FULL identical copies on every worker (the
    hedge-peer precondition) — routing still sends the leaf to
    `hosting[table]` only, so rows never double-count."""
    from pinot_tpu.mse.blocks import Block
    from pinot_tpu.mse.dispatcher import QueryDispatcher
    from pinot_tpu.mse.operators import filter_block
    from pinot_tpu.mse.runtime import MseWorker

    insts = ["server_0", "server_1"]

    def make_scan(inst):
        def scan(table, columns, filt):
            t = tables[table]
            n = len(next(iter(t.values())))
            if table in replica_tables:
                idx = np.ones(n, bool)
            else:
                hosts = hosting[table]
                if inst not in hosts:
                    return Block(columns,
                                 [np.empty(0, object) for _ in columns])
                shard, nshards = hosts.index(inst), len(hosts)
                idx = np.arange(n) % nshards == shard
            b = Block(list(t), [t[c][idx] for c in t])
            if filt is not None:
                b = filter_block(b, filt)
            return b.select(columns)
        return scan

    workers = {}
    for i in insts:
        w = MseWorker(i, make_scan(i), config=worker_config)
        w.start()
        workers[i] = w
    catalog = {k: list(v) for k, v in tables.items()}
    disp = QueryDispatcher(workers, lambda: catalog,
                           lambda t: list(hosting[t]), **disp_kwargs)
    return disp, workers


def _stop_engine(disp, workers):
    for w in workers.values():
        w.stop()
    disp.stop()


class TestPipelinedIntermediate:
    def _run(self, worker_config):
        tables = _mse_tables()
        hosting = {"fact": ["server_0", "server_1"],
                   "dim": ["server_0"]}
        disp, workers = _make_engine(tables, hosting,
                                     worker_config=worker_config)
        try:
            resp = disp.submit(JOIN_SQL)
            assert not resp.exceptions, resp.exceptions
            return [(str(a), int(b)) for a, b in resp.rows], tables
        finally:
            _stop_engine(disp, workers)

    def test_chunked_fold_equals_barrier(self):
        """Tiny chunk + watermark (dozens of frames per exchange) must
        produce exactly the barrier path's rows."""
        chunked = PinotConfiguration(overrides={
            "pinot.server.mse.pipeline.chunk.rows": 64,
            "pinot.server.mse.pipeline.watermark.rows": 150})
        barrier = PinotConfiguration(overrides={
            "pinot.server.mse.pipeline.enabled": False})
        rows_c, tables = self._run(chunked)
        rows_b, _ = self._run(barrier)
        assert rows_c == rows_b == _expected_join(tables)

    def test_watermark_bounds_fold_buffer(self):
        """_watermarked never buffers more than watermark_rows before a
        fold (plus the frame that crossed it)."""
        from pinot_tpu.mse.blocks import Block
        from pinot_tpu.mse.runtime import StageContext, _watermarked
        ctx = StageContext(
            query_id="q", plan=None, worker_id="w", worker_idx=0,
            mailbox=None, addresses={}, scan_fn=None,
            watermark_rows=120)
        chunks = [Block(["a"], [np.arange(50)]) for _ in range(7)]
        folds = list(_watermarked(ctx, iter(chunks)))
        assert sum(f.num_rows for f in folds) == 350
        assert len(folds) > 1, "watermark never triggered a fold"
        assert all(f.num_rows <= 120 + 50 for f in folds)

    def test_fold_operator_parity(self):
        """fold_* chunked results == their barrier twins on random data
        (incl. sketch and filtered aggs)."""
        from pinot_tpu.mse.blocks import Block
        from pinot_tpu.mse.operators import (
            aggregate_block, final_merge_block, fold_aggregate_chunks,
            fold_final_merge_chunks, partial_aggregate_block)
        from pinot_tpu.query.expressions import func, ident, lit
        rng = np.random.default_rng(3)
        n = 600
        block = Block(["a", "m"], [
            rng.integers(0, 7, n).astype(np.int64),
            rng.integers(1, 100, n).astype(np.int64)])
        aggs = [func("sum", ident("m")), func("count", ident("*")),
                func("min", ident("m")), func("avg", ident("m")),
                func("distinctcounthll", ident("a")),
                func("percentileest", ident("m"), lit(90))]
        groups = [ident("a")]
        schema = ["a"] + [f"x{i}" for i in range(len(aggs))]
        parts = [block.take(np.arange(i, n, 5)) for i in range(5)]

        def cells_equal(want, got):
            assert want.names == got.names
            for w, g in zip(want.arrays, got.arrays):
                assert len(w) == len(g)
                for x, y in zip(w, g):
                    # sketch merges (hll/percentile digests) are
                    # approx-stable under chunking, exact ints exact
                    assert float(x) == pytest.approx(float(y), rel=1e-9)

        want = aggregate_block(Block.concat(parts), groups, aggs, schema)
        got = fold_aggregate_chunks(iter(parts), groups, aggs, schema)
        cells_equal(want, got)

        partials = [partial_aggregate_block(p, groups, aggs, schema)
                    for p in parts]
        want = final_merge_block(Block.concat(partials), 1, aggs, schema)
        got = fold_final_merge_chunks(iter(partials), 1, aggs, schema)
        cells_equal(want, got)


# ---------------------------------------------------------------------------
# stage hedging
# ---------------------------------------------------------------------------

class TestHedgeBook:
    def test_clean_claim_wins_once(self):
        from pinot_tpu.mse.dispatcher import _HedgeBook
        b = _HedgeBook()
        b.start((2, 0), 0, "s0")
        b.start((2, 0), 1, "s1")
        granted, loser = b.claim((2, 0), 1, clean=True)
        assert granted and loser == (0, "s0")
        granted, loser = b.claim((2, 0), 0, clean=True)
        assert not granted

    def test_error_waits_for_live_twin(self):
        from pinot_tpu.mse.dispatcher import _HedgeBook
        b = _HedgeBook()
        b.start((2, 0), 0, "s0")
        b.start((2, 0), 1, "s1")
        # primary errors while the hedge is still running: denied
        granted, _ = b.claim((2, 0), 0, clean=False)
        assert not granted
        # hedge errors too: it is the last one standing — granted
        granted, _ = b.claim((2, 0), 1, clean=False)
        assert granted

    def test_unhedged_key_claims_trivially(self):
        from pinot_tpu.mse.dispatcher import _HedgeBook
        b = _HedgeBook()
        b.start((3, 1), 0, "s0")
        granted, loser = b.claim((3, 1), 0, clean=True)
        assert granted and loser is None


@pytest.mark.chaos
class TestStageHedging:
    SQL = ("SELECT f.k, SUM(f.v) AS s FROM fact f GROUP BY f.k "
           "ORDER BY f.k LIMIT 100")

    def _hedged_engine(self, tables):
        """Both workers scan identical full fact copies: server_0 is the
        one leaf worker, server_1 its hedge peer."""
        cfg = PinotConfiguration(overrides={
            "pinot.broker.mse.hedge.enabled": True,
            "pinot.broker.mse.hedge.delay.min.ms": 40,
            "pinot.broker.mse.hedge.delay.max.ms": 200})
        return _make_engine(
            tables, {"fact": ["server_0"], "dim": ["server_0"]},
            replica_tables=("fact",),
            config=cfg,
            hedge_peers_fn=lambda table, inst:
                ["server_1"] if inst == "server_0" else [])

    def _run_seeded(self, seed):
        tables = _mse_tables()
        sched = FaultSchedule([
            ("mse.stage.execute",
             {"delay": 2.0, "times": 1, "seed": seed,
              "where": {"instance": "server_0", "stage": 2}}),
            ("mse.stage.hedge", {"delay": 0.0, "seed": seed}),
        ])
        sched.arm()
        disp, workers = self._hedged_engine(tables)
        try:
            t0 = time.time()
            resp = disp.submit(self.SQL)
            elapsed = time.time() - t0
            rows = [(int(a), int(b)) for a, b in resp.rows]
            return (rows, tuple(e["errorCode"] for e in resp.exceptions),
                    elapsed, json.dumps(sched.decisions()),
                    get_registry("broker"))
        finally:
            _stop_engine(disp, workers)
            sched.disarm()

    def test_hedge_wins_within_budget_and_replays(self):
        tables = _mse_tables()
        # the no-chaos, no-hedge reference rows
        disp, workers = _make_engine(
            tables, {"fact": ["server_0"], "dim": ["server_0"]})
        try:
            ref = disp.submit(self.SQL)
            assert not ref.exceptions
            ref_rows = [(int(a), int(b)) for a, b in ref.rows]
        finally:
            _stop_engine(disp, workers)

        reg = get_registry("broker")
        issued0 = reg.meter("mse_stage_hedge_issued")
        won0 = reg.meter("mse_stage_hedge_won")
        rows_a, exc_a, elapsed_a, dec_a, _ = self._run_seeded(seed=11)
        # zero failed queries; the hedge answered well before the 2s
        # straggler finished
        assert exc_a == ()
        assert rows_a == ref_rows, "hedged rows differ from no-hedge run"
        assert elapsed_a < 1.8, \
            f"hedge did not win (query took {elapsed_a:.2f}s)"
        assert reg.meter("mse_stage_hedge_issued") > issued0
        assert reg.meter("mse_stage_hedge_won") > won0
        # same seed, fresh cluster: identical rows + byte-identical
        # decision journal
        rows_b, exc_b, _elapsed_b, dec_b, _ = self._run_seeded(seed=11)
        assert (rows_b, exc_b) == (rows_a, exc_a)
        assert dec_a == dec_b

    def test_hedge_loser_leaves_no_orphaned_queues(self):
        tables = _mse_tables()
        with failpoints.armed("mse.stage.execute", delay=1.2, times=1,
                              where={"instance": "server_0", "stage": 2}):
            disp, workers = self._hedged_engine(tables)
            try:
                resp = disp.submit(self.SQL)
                assert not resp.exceptions, resp.exceptions
                # the delayed primary eventually wakes, is cancelled,
                # and must not leave a queue behind
                deadline = time.time() + 5.0
                services = [w.mailbox for w in workers.values()] \
                    + [disp.mailbox]
                while time.time() < deadline:
                    if all(s.queue_count() == 0 for s in services):
                        break
                    time.sleep(0.05)
                assert all(s.queue_count() == 0 for s in services), \
                    "hedge loser left orphaned mailbox queues"
            finally:
                _stop_engine(disp, workers)


# ---------------------------------------------------------------------------
# L2-shared stage cache: a cold replica serves another replica's warm leaf
# ---------------------------------------------------------------------------

class TestStageCacheL2Sharing:
    def test_remote_key_stable_across_processes(self):
        from pinot_tpu.mse.stage_cache import remote_stage_key
        key = ((("t", (("seg_0", 123), ("seg_1", 456))),),
               '{"op":"scan"}')
        k1 = remote_stage_key(key)
        k2 = remote_stage_key(
            ((("t", (("seg_0", 123), ("seg_1", 456))),), '{"op":"scan"}'))
        assert k1 == k2 and k1.startswith("mse_stage:")
        assert remote_stage_key(
            ((("t", (("seg_0", 124), ("seg_1", 456))),),
             '{"op":"scan"}')) != k1

    def test_cold_replica_served_from_l2(self, tmp_path):
        """Warm the leaf on server_0, move the segment view to server_1
        (the rolling-restart cold replica): its first leaf stage answers
        from the shared L2 — asserted via the cross-replica hit meter —
        with identical rows."""
        from pinot_tpu.cluster.mini import MiniCluster

        rng = np.random.default_rng(9)
        n = 4000
        cols = {"d": rng.integers(0, 9, n).astype(np.int64),
                "v": rng.integers(1, 100, n).astype(np.int64)}
        schema = Schema.from_dict({
            "schemaName": "t",
            "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
            "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
        tc = TableConfig.from_dict(
            {"tableName": "t", "tableType": "OFFLINE"})
        creator = SegmentCreator(tc, schema)
        d = str(tmp_path / "seg")
        creator.build(cols, d, "t_0")
        seg = load_segment(d)

        c = MiniCluster(num_servers=2, cache_server=True)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", seg, server_idx=0)
            sql = ("SELECT t.d, SUM(t.v) AS s FROM t GROUP BY t.d "
                   "ORDER BY t.d LIMIT 100")
            warm = c.mse.submit(sql)
            assert not warm.exceptions, warm.exceptions
            want = [(int(a), int(b)) for a, b in warm.rows]
            # roll the table to the cold replica: same segment (same
            # content CRC version), fresh process-local caches
            c.servers[1].data_manager.table("t_OFFLINE").add_segment(seg)
            c.servers[0].data_manager.table(
                "t_OFFLINE", create=False).remove_segment("t_0")
            reg = get_registry("server")
            labels = {"instance": "server_1"}
            h0 = reg.meter("mse_stage_cache_remote_hits", labels=labels)
            cold = c.mse.submit(sql)
            assert not cold.exceptions, cold.exceptions
            assert [(int(a), int(b)) for a, b in cold.rows] == want
            assert reg.meter("mse_stage_cache_remote_hits",
                             labels=labels) > h0, \
                "cold replica's leaf stage did not hit the shared L2"
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# bench smoke (tier-1): the --mse driver incl. the throughput leg runs
# ---------------------------------------------------------------------------

class TestMseBenchSmoke:
    def test_mse_bench_smoke(self, tmp_path):
        import bench
        # tmp out_path: the smoke run must not clobber the committed
        # full-mode BENCH_mse.json
        bench.mse_main(smoke=True, out_path=str(tmp_path / "mse.json"))
        assert (tmp_path / "mse.json").exists()
