"""MSE leaf-stage aggregation pushdown: two-phase plans, intermediate
serde, and device-engine execution of leaf scans.

Ref: pinot-query-runtime runtime/operator/LeafStageTransferableBlockOperator
(leaf stages run on the single-stage executor — QueryRunner.java:258) and
AggregateOperator's intermediate/final split.
"""
import numpy as np
import pytest

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.operators import (
    aggregate_block, final_merge_block, partial_aggregate_block)
from pinot_tpu.query.expressions import func, ident, lit


# ---------------------------------------------------------------------------
# plan shape: single-table aggregate -> leaf_agg + final_agg
# ---------------------------------------------------------------------------

def _plan(sql, tables=("t",), cols=("a", "b", "m")):
    from pinot_tpu.mse.logical import build_logical
    from pinot_tpu.mse.planner import plan_query
    from pinot_tpu.mse.sql import parse_mse_sql
    q = parse_mse_sql(sql)
    catalog = {t: list(cols) for t in tables}
    logical = build_logical(q, catalog)
    return plan_query(logical, q.options, lambda t: ["s0", "s1"],
                      intermediate_workers=["s0", "s1"])


def _ops(plan):
    out = []

    def walk(op):
        out.append(op["op"])
        for k in ("child", "left", "right"):
            if isinstance(op.get(k), dict):
                walk(op[k])
    for s in plan.stages:
        if s.root:
            walk(s.root)
    return out


class TestTwoPhasePlan:
    def test_single_table_group_by_splits(self):
        p = _plan("SELECT t.a, SUM(t.m) FROM t GROUP BY t.a")
        ops = _ops(p)
        assert "leaf_agg" in ops and "final_agg" in ops
        assert "aggregate" not in ops
        # leaf stage hashes on the group column of its OUTPUT schema
        leaf = next(s for s in p.stages
                    if s.root and s.root["op"] == "leaf_agg")
        assert leaf.out_kind == "hash"
        assert leaf.out_keys == [["id", leaf.root["schema"][0]]]

    def test_single_table_global_agg_splits(self):
        p = _plan("SELECT SUM(t.m), COUNT(*) FROM t WHERE t.a > 3")
        ops = _ops(p)
        assert "leaf_agg" in ops and "final_agg" in ops

    def test_join_fed_aggregate_stays_one_phase(self):
        p = _plan("SELECT SUM(t.m) FROM t JOIN u ON t.a = u.a",
                  tables=("t", "u"), cols=("a", "b", "m"))
        ops = _ops(p)
        assert "aggregate" in ops
        assert "leaf_agg" not in ops


# ---------------------------------------------------------------------------
# partial/final operator parity vs one-phase aggregate_block
# ---------------------------------------------------------------------------

def _block(n=500, seed=3):
    rng = np.random.default_rng(seed)
    return Block(["a", "b", "m"], [
        rng.integers(0, 7, n).astype(np.int64),
        rng.integers(0, 4, n).astype(np.int64),
        rng.integers(1, 100, n).astype(np.int64)])


def _split(block, k=3):
    parts = []
    n = block.num_rows
    for i in range(k):
        idx = np.arange(n) % k == i
        parts.append(block.mask(idx))
    return parts


class TestPartialFinalParity:
    AGGS = [
        func("sum", ident("m")),
        func("count", ident("*")),
        func("min", ident("m")),
        func("avg", ident("m")),
        func("distinctcounthll", ident("a")),
        func("percentileest", ident("m"), lit(90)),
    ]

    def _names(self, k):
        return [f"agg{i}" for i in range(k)]

    def test_global_agg(self):
        block = _block()
        names = self._names(len(self.AGGS))
        want = aggregate_block(block, [], self.AGGS, names)
        partials = [partial_aggregate_block(p, [], self.AGGS, names)
                    for p in _split(block)]
        got = final_merge_block(Block.concat(partials), 0, self.AGGS, names)
        for w, g in zip(want.arrays, got.arrays):
            assert float(w[0]) == pytest.approx(float(g[0]), rel=1e-9)

    def test_group_by(self):
        block = _block()
        groups = [ident("a"), ident("b")]
        schema = ["a", "b"] + self._names(len(self.AGGS))
        want = aggregate_block(block, groups, self.AGGS, schema)
        partials = [partial_aggregate_block(p, groups, self.AGGS, schema)
                    for p in _split(block)]
        got = final_merge_block(Block.concat(partials), 2, self.AGGS, schema)

        def keyed(b):
            out = {}
            for row in zip(*[a.tolist() for a in b.arrays]):
                out[(int(row[0]), int(row[1]))] = [float(v) for v in row[2:]]
            return out
        kw, kg = keyed(want), keyed(got)
        assert set(kw) == set(kg)
        for k in kw:
            assert kw[k] == pytest.approx(kg[k], rel=1e-9)

    def test_partial_survives_wire(self):
        block = _block(80)
        names = self._names(len(self.AGGS))
        part = partial_aggregate_block(block, [ident("a")], self.AGGS,
                                       ["a"] + names)
        rt = Block.from_bytes(part.to_bytes())
        got = final_merge_block(rt, 1, self.AGGS, ["a"] + names)
        want = aggregate_block(block, [ident("a")], self.AGGS, ["a"] + names)

        def keyed(b):
            return {int(b.arrays[0][i]):
                    [float(a[i]) for a in b.arrays[1:]]
                    for i in range(b.num_rows)}
        kw, kg = keyed(want), keyed(got)
        assert set(kw) == set(kg)
        for k in kw:
            assert kw[k] == pytest.approx(kg[k], rel=1e-9)

    def test_empty_input_global(self):
        names = self._names(len(self.AGGS))
        part = partial_aggregate_block(_block(0), [], self.AGGS, names)
        got = final_merge_block(part, 0, self.AGGS, names)
        assert float(got.arrays[1][0]) == 0.0  # COUNT(*) over nothing


# ---------------------------------------------------------------------------
# device-engine leaf execution on a TPU-enabled MiniCluster
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpu_cluster(tmp_path_factory):
    from pinot_tpu.cluster.mini import MiniCluster
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment

    tmp = tmp_path_factory.mktemp("mse_leaf")
    rng = np.random.default_rng(11)
    n = 8000
    cols = {
        "d": rng.integers(0, 9, n).astype(np.int64),
        "q": rng.integers(1, 50, n).astype(np.int64),
        "price": rng.integers(100, 9999, n).astype(np.int64),
    }
    schema = Schema.from_dict({
        "schemaName": "sales",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"},
                                {"name": "q", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "price", "dataType": "LONG"}],
    })
    tc = TableConfig.from_dict({"tableName": "sales",
                                "tableType": "OFFLINE"})
    creator = SegmentCreator(tc, schema)
    c = MiniCluster(num_servers=2, use_tpu=True)
    c.start()
    c.add_table("sales")
    for i in range(4):
        idx = np.arange(n) % 4 == i
        part = {k: v[idx] for k, v in cols.items()}
        d = str(tmp / f"seg_{i}")
        creator.build(part, d, f"sales_{i}")
        c.add_segment("sales", load_segment(d), server_idx=i % 2)
    yield c, cols
    c.stop()


class TestLeafOnDevice:
    def test_leaf_agg_hits_engine(self, tpu_cluster):
        """The MSE leaf stage must execute on the device engine: after the
        query, the shared engine's HBM block cache holds staged columns."""
        c, cols = tpu_cluster
        resp = c.query(
            "SELECT s.d, SUM(s.price) AS rev FROM sales s "
            "WHERE s.q BETWEEN 10 AND 40 GROUP BY s.d "
            "ORDER BY s.d LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        mask = (cols["q"] >= 10) & (cols["q"] <= 40)
        want = {}
        for d, p in zip(cols["d"][mask], cols["price"][mask]):
            want[int(d)] = want.get(int(d), 0) + int(p)
        got = {int(r[0]): int(r[1]) for r in resp.result_table.rows}
        assert got == want
        staged = 0
        for s in c.servers:
            eng = s.executor._engine
            if eng is not None:
                staged += len(eng._block_cache)
        assert staged > 0, "leaf stage never staged blocks on the engine"

    def test_global_agg_on_device(self, tpu_cluster):
        c, cols = tpu_cluster
        resp = c.query(
            "SELECT COUNT(*) AS n, SUM(s.price) AS t FROM sales s "
            "WHERE s.d = 3")
        assert not resp.exceptions, resp.exceptions
        mask = cols["d"] == 3
        assert int(resp.result_table.rows[0][0]) == int(mask.sum())
        assert int(resp.result_table.rows[0][1]) == \
            int(cols["price"][mask].sum())

    def test_count_star_pushdown_maps(self):
        """COUNT(*) must not break the leaf rewrite (Identifier('*') is
        not a scan column)."""
        from pinot_tpu.mse.runtime import _substitute
        from pinot_tpu.query.expressions import Function, Identifier
        m = {"s.d": Identifier("d")}
        e = Function("count", (Identifier("*"),))
        assert _substitute(e, m) == e

    def test_distinct_through_mse(self, tpu_cluster):
        """SELECT DISTINCT lowers to an agg-less Aggregate; the leaf must
        dedup through the single-stage DISTINCT path, not crash."""
        c, cols = tpu_cluster
        resp = c.query(
            "SELECT DISTINCT s.d FROM sales s ORDER BY s.d LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        got = sorted(int(r[0]) for r in resp.result_table.rows)
        assert got == sorted(set(int(v) for v in cols["d"]))


class TestLeafScanOnDevice:
    def test_join_input_scan_hits_engine(self, tpu_cluster):
        """A filtered leaf SCAN feeding a join must push its filter through
        the device top-K kernel (VERDICT r4 weak #4): after the join query
        the shared engine's cache holds staged filter columns."""
        c, cols = tpu_cluster
        for s in c.servers:
            eng = s.executor._shared_engine()
            eng._block_cache.clear()
            eng._block_bytes.clear()
            eng._cache_bytes = 0
        resp = c.query(
            "SELECT a.d, COUNT(*) AS n FROM sales a "
            "JOIN sales b ON a.d = b.d "
            "WHERE a.q BETWEEN 10 AND 12 AND b.q BETWEEN 10 AND 12 "
            "GROUP BY a.d ORDER BY a.d LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        staged = sum(len(s.executor._shared_engine()._block_cache)
                     for s in c.servers)
        assert staged > 0, "leaf scan did not stage device blocks"
        # correctness vs numpy
        mask = (cols["q"] >= 10) & (cols["q"] <= 12)
        import collections
        per_d = collections.Counter(int(d) for d in cols["d"][mask])
        want = {d: n * n for d, n in per_d.items()}
        got = {int(r[0]): int(r[1]) for r in resp.result_table.rows}
        assert got == want
