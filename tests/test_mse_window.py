"""MSE window functions + set operators.

Ref semantics: pinot-query-runtime runtime/operator/WindowAggregateOperator
(rank/value/aggregate window families, default RANGE frame with peers) and
SetOperator.java (UNION/INTERSECT/EXCEPT incl. ALL multiset semantics).
"""
import numpy as np
import pytest

from pinot_tpu.mse.blocks import Block
from pinot_tpu.mse.operators import set_op_block, window_block
from pinot_tpu.query.expressions import func, ident, lit
from test_mse import mse  # noqa: F401 — shared distributed-harness fixture


def _over(inner, partition=(), order=()):
    return func("over", inner, func("__partition", *partition),
                func("__orderby", *order))


class TestWindowBlock:
    def _block(self):
        return Block(["g", "v"], [
            np.array([1, 1, 1, 2, 2, 3], np.int64),
            np.array([10, 20, 20, 5, 7, 9], np.int64)])

    def test_row_number(self):
        b = self._block()
        out = window_block(
            b, [ident("g")], [ident("v")], [True],
            [_over(func("row_number"))], ["g", "v", "rn"])
        assert out.arrays[2].tolist() == [1, 2, 3, 1, 2, 1]

    def test_rank_vs_dense_rank_with_ties(self):
        b = self._block()
        over_r = _over(func("rank"))
        over_d = _over(func("dense_rank"))
        out = window_block(
            b, [ident("g")], [ident("v")], [True], [over_r, over_d],
            ["g", "v", "r", "d"])
        assert out.arrays[2].tolist() == [1, 2, 2, 1, 2, 1]
        assert out.arrays[3].tolist() == [1, 2, 2, 1, 2, 1]

    def test_running_sum_includes_peers(self):
        """RANGE frame: tied order keys aggregate together."""
        b = self._block()
        out = window_block(
            b, [ident("g")], [ident("v")], [True],
            [_over(func("sum", ident("v")))], ["g", "v", "s"])
        # g=1 sorted v=[10,20,20]: run=[10,50,50] (peers share the frame)
        assert out.arrays[2].tolist() == [10.0, 50.0, 50.0, 5.0, 12.0, 9.0]

    def test_partition_total_without_order(self):
        b = self._block()
        out = window_block(
            b, [ident("g")], [], [],
            [_over(func("sum", ident("v"))), _over(func("count", ident("*"))),
             _over(func("min", ident("v"))), _over(func("max", ident("v"))),
             _over(func("avg", ident("v")))],
            ["g", "v", "s", "c", "mn", "mx", "a"])
        assert out.arrays[2].tolist() == [50.0, 50, 50, 12, 12, 9]
        assert out.arrays[3].tolist() == [3, 3, 3, 2, 2, 1]
        assert out.arrays[4].tolist() == [10, 10, 10, 5, 5, 9]
        assert out.arrays[5].tolist() == [20, 20, 20, 7, 7, 9]
        assert out.arrays[6].tolist() == pytest.approx(
            [50 / 3, 50 / 3, 50 / 3, 6, 6, 9])

    def test_global_window_no_partition(self):
        b = self._block()
        out = window_block(
            b, [], [ident("v")], [True],
            [_over(func("rank"))], ["g", "v", "r"])
        # global ranks of v=[10,20,20,5,7,9] -> [4,5,5,1,2,3]
        assert out.arrays[2].tolist() == [4, 5, 5, 1, 2, 3]

    def test_lag_lead(self):
        b = self._block()
        out = window_block(
            b, [ident("g")], [ident("v")], [True],
            [_over(func("lag", ident("v"))),
             _over(func("lead", ident("v"), lit(1), lit(-1)))],
            ["g", "v", "lg", "ld"])
        assert out.arrays[2].tolist() == [None, 10, 20, None, 5, None]
        assert out.arrays[3].tolist() == [20, 20, -1, 7, -1, -1]

    def test_first_last_value_frame(self):
        b = self._block()
        out = window_block(
            b, [ident("g")], [ident("v")], [True],
            [_over(func("first_value", ident("v"))),
             _over(func("last_value", ident("v")))],
            ["g", "v", "f", "l"])
        assert out.arrays[2].tolist() == [10, 10, 10, 5, 5, 9]
        # last_value default frame ends at the CURRENT peer group
        assert out.arrays[3].tolist() == [10, 20, 20, 5, 7, 9]

    def test_ntile(self):
        b = Block(["v"], [np.arange(6, dtype=np.int64)])
        out = window_block(
            b, [], [ident("v")], [True],
            [_over(func("ntile", lit(3)))], ["v", "t"])
        assert out.arrays[1].tolist() == [1, 1, 2, 2, 3, 3]

    def test_desc_order(self):
        b = self._block()
        out = window_block(
            b, [ident("g")], [ident("v")], [False],
            [_over(func("row_number"))], ["g", "v", "rn"])
        assert out.arrays[2].tolist() == [3, 1, 2, 2, 1, 1]

    def test_empty_block(self):
        b = Block(["g", "v"], [np.empty(0, np.int64), np.empty(0, np.int64)])
        out = window_block(b, [ident("g")], [], [],
                           [_over(func("sum", ident("v")))], ["g", "v", "s"])
        assert out.num_rows == 0 and len(out.arrays) == 3


class TestCompoundParsing:
    def test_trailing_clauses_hoist_to_compound(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT a FROM t UNION SELECT a FROM u "
                          "ORDER BY a LIMIT 5")
        assert q.op == "union" and not q.all
        assert q.limit == 5 and len(q.order_by) == 1
        assert q.right.limit is None and not q.right.order_by

    def test_parenthesized_operand_keeps_its_clauses(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT a FROM t UNION ALL "
                          "(SELECT a FROM u ORDER BY a LIMIT 1)")
        assert q.op == "union" and q.all
        assert q.limit is None and not q.order_by
        assert q.right.limit == 1 and len(q.right.order_by) == 1

    def test_intersect_binds_tighter(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT a FROM t UNION SELECT a FROM u "
                          "INTERSECT SELECT a FROM v")
        assert q.op == "union"
        assert q.right.op == "intersect"

    def test_compound_order_after_parenthesized_operand(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT a FROM t UNION (SELECT a FROM u) "
                          "ORDER BY a LIMIT 5")
        assert q.op == "union"
        assert q.limit == 5 and len(q.order_by) == 1
        assert q.right.limit is None and not q.right.order_by

    def test_intersect_only_compound_trailing_clauses(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT a FROM t INTERSECT (SELECT a FROM u) "
                          "ORDER BY a LIMIT 5")
        assert q.op == "intersect"
        assert q.limit == 5 and len(q.order_by) == 1

    def test_duplicate_output_names_setop(self, mse):
        """Hash exchange must key on column POSITION: duplicate output
        names would alias to one column and split equal rows."""
        disp, t = mse
        resp = disp.submit(
            "SELECT lo.lo_suppkey, lo.lo_suppkey FROM lineorder lo "
            "WHERE lo.lo_suppkey < 3 "
            "INTERSECT "
            "SELECT lo.lo_suppkey, lo.lo_suppkey FROM lineorder lo "
            "WHERE lo.lo_suppkey < 5 LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        got = sorted((int(a), int(b)) for a, b in resp.result_table.rows)
        assert got == [(0, 0), (1, 1), (2, 2)]

    def test_order_by_window_not_single_table(self):
        from pinot_tpu.mse.sql import parse_mse_sql
        q = parse_mse_sql("SELECT x.a FROM t x "
                          "ORDER BY ROW_NUMBER() OVER (ORDER BY x.a)")
        assert not q.is_single_table


class TestSetOpBlock:
    def _sides(self):
        left = Block(["a", "b"], [
            np.array([1, 1, 2, 3, 3, 3], np.int64),
            np.array([1, 1, 2, 3, 3, 3], np.int64)])
        right = Block(["x", "y"], [
            np.array([1, 3, 3, 4], np.int64),
            np.array([1, 3, 3, 4], np.int64)])
        return left, right

    def _rows(self, b):
        return sorted(tuple(int(v) for v in r) for r in zip(
            *[a.tolist() for a in b.arrays]))

    def test_union_distinct_and_all(self):
        left, right = self._sides()
        u = set_op_block(left, right, "union", False, ["a", "b"])
        assert self._rows(u) == [(1, 1), (2, 2), (3, 3), (4, 4)]
        ua = set_op_block(left, right, "union", True, ["a", "b"])
        assert len(self._rows(ua)) == 10

    def test_intersect(self):
        left, right = self._sides()
        i = set_op_block(left, right, "intersect", False, ["a", "b"])
        assert self._rows(i) == [(1, 1), (3, 3)]
        ia = set_op_block(left, right, "intersect", True, ["a", "b"])
        # multiset min counts: 1x1 appears min(2,1)=1, 3x3 min(3,2)=2
        assert self._rows(ia) == [(1, 1), (3, 3), (3, 3)]

    def test_except(self):
        left, right = self._sides()
        e = set_op_block(left, right, "except", False, ["a", "b"])
        assert self._rows(e) == [(2, 2)]
        ea = set_op_block(left, right, "except", True, ["a", "b"])
        # multiset difference: 1 appears 2-1=1, 3 appears 3-2=1
        assert self._rows(ea) == [(1, 1), (2, 2), (3, 3)]

    def test_empty_sides(self):
        left, right = self._sides()
        empty = Block(["x", "y"], [np.empty(0, np.int64),
                                   np.empty(0, np.int64)])
        assert self._rows(set_op_block(
            left, empty, "except", False, ["a", "b"])) == \
            [(1, 1), (2, 2), (3, 3)]
        assert self._rows(set_op_block(
            empty.rename(["a", "b"]), right, "intersect", False,
            ["a", "b"])) == []


# ---------------------------------------------------------------------------
# end-to-end through the distributed MSE harness
# ---------------------------------------------------------------------------

class TestDistributedWindowSetOps:
    def test_window_sql(self, mse):
        disp, t = mse
        resp = disp.submit(
            "SELECT lo.lo_suppkey, lo.lo_revenue, "
            "RANK() OVER (PARTITION BY lo.lo_suppkey "
            "ORDER BY lo.lo_revenue DESC) AS r "
            "FROM lineorder lo WHERE lo.lo_orderkey < 50 "
            "ORDER BY lo.lo_suppkey, r, lo.lo_revenue LIMIT 2000")
        assert not resp.exceptions, resp.exceptions
        lo = t["lineorder"]
        sel = lo["lo_orderkey"] < 50
        rows = list(zip(lo["lo_suppkey"][sel], lo["lo_revenue"][sel]))
        want = []
        for sk, rev in rows:
            rank = 1 + sum(1 for s2, r2 in rows if s2 == sk and r2 > rev)
            want.append((int(sk), int(rev), rank))
        want.sort()
        got = sorted((int(a), int(b), int(c))
                     for a, b, c in resp.result_table.rows)
        assert got == want

    def test_window_sum_over_group_output(self, mse):
        disp, t = mse
        resp = disp.submit(
            "SELECT lo.lo_suppkey, SUM(lo.lo_revenue) AS rev, "
            "SUM(SUM(lo.lo_revenue)) OVER () AS total "
            "FROM lineorder lo GROUP BY lo.lo_suppkey "
            "ORDER BY lo.lo_suppkey LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        lo = t["lineorder"]
        total = int(lo["lo_revenue"].sum())
        for _sk, _rev, tot in resp.result_table.rows:
            assert int(tot) == total

    def test_union_sql(self, mse):
        disp, t = mse
        resp = disp.submit(
            "SELECT lo.lo_suppkey FROM lineorder lo WHERE lo.lo_suppkey < 4 "
            "UNION "
            "SELECT lo.lo_suppkey FROM lineorder lo "
            "WHERE lo.lo_suppkey BETWEEN 2 AND 6 "
            "ORDER BY lo_suppkey LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        got = [int(r[0]) for r in resp.result_table.rows]
        assert got == [0, 1, 2, 3, 4, 5, 6]

    def test_intersect_except_sql(self, mse):
        disp, t = mse
        resp = disp.submit(
            "SELECT lo.lo_suppkey FROM lineorder lo WHERE lo.lo_suppkey < 4 "
            "INTERSECT "
            "SELECT lo.lo_suppkey FROM lineorder lo "
            "WHERE lo.lo_suppkey BETWEEN 2 AND 6 LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        assert sorted(int(r[0]) for r in resp.result_table.rows) == [2, 3]
        resp = disp.submit(
            "SELECT lo.lo_suppkey FROM lineorder lo WHERE lo.lo_suppkey < 4 "
            "EXCEPT "
            "SELECT lo.lo_suppkey FROM lineorder lo "
            "WHERE lo.lo_suppkey BETWEEN 2 AND 6 LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        assert sorted(int(r[0]) for r in resp.result_table.rows) == [0, 1]


def _over_framed(inner, partition=(), order=(), lo="up", hi=0):
    return func("over", inner, func("__partition", *partition),
                func("__orderby", *order),
                func("__frame", lit("rows"), lit(lo), lit(hi)))


class TestRowsFrames:
    """Explicit ROWS BETWEEN frames (VERDICT r4 weak #5; ref
    runtime/operator/WindowAggregateOperator + operator/window/)."""

    def _block(self):
        return Block(["g", "v"], [
            np.array([1, 1, 1, 1, 2, 2], np.int64),
            np.array([10, 20, 30, 40, 5, 7], np.int64)])

    def _run(self, over, name="w"):
        b = self._block()
        out = window_block(b, [ident("g")], [ident("v")], [True], [over],
                           ["g", "v", name])
        return out.arrays[2].tolist()

    def test_sliding_sum_2_preceding_current(self):
        over = _over_framed(func("sum", ident("v")), lo=-2, hi=0)
        assert self._run(over) == [10.0, 30.0, 60.0, 90.0, 5.0, 12.0]

    def test_sum_current_to_unbounded_following(self):
        over = _over_framed(func("sum", ident("v")), lo=0, hi="uf")
        assert self._run(over) == [100.0, 90.0, 70.0, 40.0, 12.0, 7.0]

    def test_min_following_window(self):
        over = _over_framed(func("min", ident("v")), lo=1, hi=2)
        # rows after current within partition; empty at partition end
        assert self._run(over) == [20.0, 30.0, 40.0, None, 7.0, None]

    def test_max_unbounded_preceding_to_1_preceding(self):
        over = _over_framed(func("max", ident("v")), lo="up", hi=-1)
        assert self._run(over) == [None, 10.0, 20.0, 30.0, None, 5.0]

    def test_count_and_values(self):
        over_c = _over_framed(func("count", ident("v")), lo=-1, hi=1)
        assert self._run(over_c) == [2, 3, 3, 2, 2, 2]
        over_f = _over_framed(func("first_value", ident("v")), lo=-1, hi=1)
        assert self._run(over_f) == [10, 10, 20, 30, 5, 5]
        over_l = _over_framed(func("last_value", ident("v")), lo=-1, hi=1)
        assert self._run(over_l) == [20, 30, 40, 40, 7, 7]


class TestRowsFramesSql:
    def test_sql_rows_between(self, mse):
        disp, tables = mse
        resp = disp.submit(
            "SELECT lo_suppkey, lo_orderkey, SUM(lo_revenue) OVER ("
            "PARTITION BY lo_suppkey ORDER BY lo_orderkey "
            "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s "
            "FROM lineorder ORDER BY lo_suppkey, lo_orderkey LIMIT 5000")
        assert not resp.exceptions, resp.exceptions
        rows = resp.result_table.rows
        # verify against numpy per partition
        import collections
        byd = collections.defaultdict(list)
        t = tables["lineorder"]
        for d, k, p in zip(t["lo_suppkey"], t["lo_orderkey"], t["lo_revenue"]):
            byd[int(d)].append((int(k), int(p)))
        want = {}
        for d, kps in byd.items():
            kps.sort()
            want[d] = [(k, float(p + (kps[i - 1][1] if i else 0)))
                       for i, (k, p) in enumerate(kps)]
        got = collections.defaultdict(list)
        for d, k, s in rows:
            got[int(d)].append((int(k), float(s)))
        for d in want:
            assert got[d] == want[d], d
