"""Real SQL through the engine on a multi-device (segments x docs) mesh.

The conftest forces an 8-device virtual CPU platform; the engine here gets
an explicit 4x2 mesh so column blocks shard over BOTH axes and the kernel
runs under shard_map with psum/pmin/pmax collectives over `docs`
(SURVEY §2.6 rows 6-7). Every query asserts parity against the host
(numpy) executor — the BaseQueriesTest pattern, multichip edition.
"""
import numpy as np
import pytest

import jax

from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.query.executor import QueryExecutor
from tests.queries.harness import (
    build_segments, synthetic_columns, synthetic_schema,
    synthetic_table_config)

NUM_DOCS = 700  # deliberately not a power of two: padding must mask right


@pytest.fixture(scope="module")
def mesh_harness(tmp_path_factory):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    tmp = tmp_path_factory.mktemp("multichip")
    data = [synthetic_columns(NUM_DOCS, seed=31 + i) for i in range(6)]
    segs = build_segments(tmp, synthetic_schema(), synthetic_table_config(),
                          data)
    mesh = make_mesh(jax.devices()[:8], doc_axis=2)
    engine = TpuOperatorExecutor(mesh=mesh)
    device = QueryExecutor(segs, use_tpu=True, engine=engine)
    host = QueryExecutor(segs, use_tpu=False)
    return device, host, engine


def _parity(device, host, sql):
    dr = device.execute(sql)
    hr = host.execute(sql)
    assert not dr.exceptions and not hr.exceptions
    assert len(dr.rows) == len(hr.rows), (dr.rows, hr.rows)
    for a, b in zip(dr.rows, hr.rows):
        for x, y in zip(a, b):
            if isinstance(x, float) or isinstance(y, float):
                assert abs(float(x) - float(y)) <= \
                    1e-5 * max(1.0, abs(float(y))), (dr.rows, hr.rows)
            else:
                assert x == y, (dr.rows, hr.rows)
    return dr


class TestMultichipSql:
    def test_sum_count_filter(self, mesh_harness):
        device, host, engine = mesh_harness
        r = _parity(device, host,
                    "SELECT SUM(intCol), COUNT(*) FROM testTable "
                    "WHERE intCol BETWEEN 100 AND 700")
        assert r.rows

    def test_group_by(self, mesh_harness):
        device, host, _ = mesh_harness
        _parity(device, host,
                "SELECT groupCol, SUM(floatCol), COUNT(*) "
                "FROM testTable GROUP BY groupCol ORDER BY groupCol LIMIT 50")

    def test_min_max(self, mesh_harness):
        """min/max combine over the docs axis via pmin/pmax, not psum."""
        device, host, _ = mesh_harness
        _parity(device, host,
                "SELECT MIN(intCol), MAX(intCol), AVG(intCol) "
                "FROM testTable WHERE intCol > 300")

    def test_in_filter_lut(self, mesh_harness):
        device, host, _ = mesh_harness
        _parity(device, host,
                "SELECT COUNT(*), SUM(intCol) FROM testTable "
                "WHERE stringCol IN ('s1', 's3', 's7')")

    def test_expression_aggregate(self, mesh_harness):
        device, host, _ = mesh_harness
        _parity(device, host,
                "SELECT SUM(intCol * floatCol) FROM testTable "
                "WHERE intCol < 900 AND rawIntCol > 10")

    def test_engine_actually_offloaded(self, mesh_harness):
        """The queries above must run the DEVICE path (no silent host
        fallback): the engine's block cache fills with sharded arrays."""
        device, host, engine = mesh_harness
        device.execute("SELECT SUM(doubleCol) FROM testTable")
        assert engine._block_cache, "device path never staged a block"
        from jax.sharding import NamedSharding
        any_block = next(iter(engine._block_cache.values()))[1]
        sh = any_block.sharding
        assert isinstance(sh, NamedSharding)
        assert dict(zip(sh.mesh.axis_names, sh.mesh.devices.shape)) == \
            {"segments": 4, "docs": 2}
        # blocks shard over BOTH axes: 8 addressable shards
        assert len(any_block.addressable_shards) == 8
        d0 = any_block.addressable_shards[0].data.shape
        assert d0[0] * 4 == any_block.shape[0]
        assert d0[1] * 2 == any_block.shape[1]
