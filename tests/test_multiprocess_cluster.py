"""Multi-process cluster integration: real OS processes per role.

Ref: pinot-integration-test-base ClusterTest.java:92 starts real ZK +
controller + brokers + servers; ChaosMonkeyIntegrationTest kills
components. Here: 1 controller + 1 broker + 2 server PROCESSES wired
through the coordination service (controller/coordination.py), segments
uploaded and served with replication 2, a server killed with SIGKILL, and
the broker's failure detector + replica failover keeps answers correct —
VERDICT r4 missing #1 / next-round task 2.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.controller.coordination import CoordinationClient
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.segment.creator import SegmentCreator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "pinot_tpu.tools.admin", *args],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def _wait(predicate, timeout=30.0, interval=0.2, desc="condition"):
    deadline = time.time() + timeout
    last_err = None
    while time.time() < deadline:
        try:
            if predicate():
                return
        except Exception as e:  # noqa: BLE001 — keep polling
            last_err = e
        time.sleep(interval)
    raise AssertionError(f"timeout waiting for {desc}: {last_err}")


def _non_broker_instances(client) -> list:
    """Registered instances minus brokers — since the cluster-health
    sweep made every role register (ISSUE 14), brokers appear in the
    instance registry too; segment-placement assertions count the
    server/minion population only."""
    return [i for i in client.get_state()["instances"].values()
            if "broker" not in (i.get("tags") or [])]


def _post_query(port: int, sql: str) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/query/sql",
        data=json.dumps({"sql": sql}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_cluster_of_processes_with_server_kill(tmp_path):
    coord_port = _free_port()
    http_port = _free_port()
    state_dir = str(tmp_path / "state")
    coordinator = f"127.0.0.1:{coord_port}"

    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", state_dir,
             "--port", str(coord_port)])
        _wait(lambda: _coord_up(coordinator), desc="controller up")

        for i in range(2):
            procs[f"server_{i}"] = _spawn(
                ["StartServer", "--instance-id", f"server_{i}",
                 "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(http_port)])

        client = CoordinationClient(coordinator)
        _wait(lambda: len(_non_broker_instances(client)) == 2,
              desc="2 servers registered")

        # table + segments (replication 2: every segment on both servers)
        schema = Schema("events", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("val", DataType.INT, FieldType.METRIC),
        ])
        cfg = TableConfig(name="events")
        cfg.retention.replication = 2
        client.add_table(cfg, schema)

        rng = np.random.default_rng(5)
        creator = SegmentCreator(cfg, schema)
        total = 0
        vsum = 0
        for i in range(2):
            n = 20_000
            ids = np.arange(n, dtype=np.int64) + i * n
            vals = rng.integers(0, 1000, size=n)
            total += n
            vsum += int(vals.sum())
            out = str(tmp_path / f"seg_{i}")
            creator.build({"id": ids, "val": vals}, out, f"events_{i}")
            r = client.upload_segment("events", out)
            assert len(r["segment"]["instances"]) == 2

        sql = "SELECT COUNT(*), SUM(val) FROM events"

        def answered():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0][0] == total and not \
                resp.get("exceptions")
        _wait(answered, desc="broker answers over both servers")
        resp = _post_query(http_port, sql)
        assert resp["resultTable"]["rows"][0] == [total, vsum]

        # ---- chaos: kill one server process hard --------------------------
        victim = procs.pop("server_1")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)

        # the broker must fail over to the surviving replica; the first
        # query may pay the detection cost but answers must stay CORRECT
        def survives():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == [total, vsum] \
                and not resp.get("exceptions")
        _wait(survives, timeout=60, desc="failover to surviving replica")

        # and repeatedly (the failure detector now routes around the corpse)
        for _ in range(3):
            resp = _post_query(http_port, sql)
            assert resp["resultTable"]["rows"][0] == [total, vsum]
            assert not resp.get("exceptions")
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")


def _coord_up(address: str) -> bool:
    c = CoordinationClient(address, timeout=2)
    try:
        c.get_state()
        return True
    finally:
        c.close()


def test_minion_process_runs_merge_task(tmp_path):
    """The fourth role as a real OS process: a minion leases a
    merge-rollup task from the controller's queue over the coordination
    channel, builds the merged segment in its sandbox, uploads it to the
    deep store, and commits via the atomic segment replace — after which
    the server reconciles (unloads the inputs, downloads + loads the
    merged segment) and the broker keeps answering identically."""
    coord_port = _free_port()
    http_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"
    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", str(tmp_path / "state"),
             "--port", str(coord_port),
             "--deep-store", f"file://{tmp_path}/store"])
        _wait(lambda: _coord_up(coordinator), desc="controller up")
        procs["server"] = _spawn(
            ["StartServer", "--instance-id", "s0",
             "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(http_port)])
        procs["minion"] = _spawn(
            ["StartMinion", "--instance-id", "m0",
             "--coordinator", coordinator])

        client = CoordinationClient(coordinator)
        # the server registers as assignable; the minion registers
        # tagged and must NOT receive segments
        _wait(lambda: len(_non_broker_instances(client)) == 2,
              desc="server + minion registered")

        from pinot_tpu.segment.fs import SegmentDeepStore
        schema = Schema("mt", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        cfg = TableConfig(name="mt")
        client.add_table(cfg, schema)
        store = SegmentDeepStore(str(tmp_path / "store"))
        total = 0
        vsum = 0
        for i in range(2):
            n = 5000
            ids = np.arange(n, dtype=np.int64) + i * n
            vals = (ids * 3).astype(np.int64)
            total += n
            vsum += int(vals.sum())
            out = str(tmp_path / f"seg_{i}")
            SegmentCreator(cfg, schema).build(
                {"id": ids, "v": vals}, out, f"mt_{i}")
            r = client.upload_segment_to_store("mt", out, store)
            assert r["segment"]["instances"] == ["s0"]

        sql = "SELECT COUNT(*), SUM(v) FROM mt"
        expect = [total, float(vsum)]

        def answered():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect and \
                not resp.get("exceptions")
        _wait(answered, desc="broker answers before the merge")

        r = client.request("task_submit", task={
            "taskType": "MergeRollupTask", "table": "mt_OFFLINE",
            "segments": ["mt_0", "mt_1"]})
        task_id = r["task"]["task_id"]

        def task_done():
            t = client.request("task_get", task_id=task_id)["task"]
            assert t["state"] not in ("FAILED", "CANCELLED"), t
            return t["state"] == "COMPLETED"
        _wait(task_done, timeout=60, desc="minion completed the merge")

        segs = client.get_state()["segments"]["mt_OFFLINE"]
        assert len(segs) == 1
        (name, st), = segs.items()
        assert name.startswith("mt_merged_")
        assert st["num_docs"] == total
        assert st["dir_path"].startswith("file://")

        # the swap reconciles through the watch machinery: the server
        # downloads the merged segment, unloads the inputs, and the
        # broker's rebuilt route answers identically
        def still_answers():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect and \
                not resp.get("exceptions") \
                and resp.get("numSegmentsProcessed") == 1
        _wait(still_answers, timeout=60,
              desc="merged segment serves after the swap")
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")


def test_server_restart_recovers_from_deep_store(tmp_path):
    """Segments live in the deep store (PinotFS URI), not a shared build
    dir: a restarted server re-downloads and serves them — killing a
    server loses nothing (ref PeerDownloadLLCRealtimeClusterIntegrationTest
    / deep-store-backed serving)."""
    from pinot_tpu.segment.fs import SegmentDeepStore

    coord_port = _free_port()
    http_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"
    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", str(tmp_path / "state"),
             "--port", str(coord_port)])
        _wait(lambda: _coord_up(coordinator), desc="controller up")
        procs["server"] = _spawn(
            ["StartServer", "--instance-id", "s0",
             "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(http_port)])
        client = CoordinationClient(coordinator)
        _wait(lambda: len(_non_broker_instances(client)) == 1,
              desc="server registered")

        schema = Schema("ds", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        cfg = TableConfig(name="ds")
        client.add_table(cfg, schema)

        store = SegmentDeepStore(str(tmp_path / "store"))
        build_dir = str(tmp_path / "build" / "seg0")
        vals = np.arange(5000)
        SegmentCreator(cfg, schema).build(
            {"id": vals, "v": vals * 3}, build_dir, "ds_0")
        r = client.upload_segment_to_store("ds", build_dir, store)
        assert r["segment"]["dir_path"].startswith("file://")
        # the original build dir is GONE — only the store copy exists
        import shutil
        shutil.rmtree(build_dir)

        sql = "SELECT COUNT(*), SUM(v) FROM ds"
        expect = [5000, float(vals.sum() * 3)]

        def answered():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect and \
                not resp.get("exceptions")
        _wait(answered, desc="served from deep-store download")

        # kill the server hard; restart a fresh process with the same id
        victim = procs.pop("server")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        procs["server2"] = _spawn(
            ["StartServer", "--instance-id", "s0",
             "--coordinator", coordinator])
        _wait(answered, timeout=60,
              desc="restarted server recovered from deep store")
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")


def test_multiprocess_upsert_restart_recovers_snapshot(tmp_path):
    """Server restart mid-stream on an UPSERT table (ISSUE 11 satellite):
    the restarted process resumes from the persisted offset + the
    validDocIds snapshots inside the deep-store tars — committed rows are
    NOT replayed (the committed segment set is unchanged across the
    restart) and upsert last-wins visibility converges exactly."""
    from pinot_tpu.ingest.tcp_stream import StreamProducer, StreamServer
    from pinot_tpu.models.table_config import (IngestionConfig,
                                               StreamIngestionConfig,
                                               UpsertConfig)
    from pinot_tpu.models import TableType

    coord_port = _free_port()
    http_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"
    stream = StreamServer()
    stream.start()
    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", str(tmp_path / "state"),
             "--port", str(coord_port),
             "--deep-store", f"file://{tmp_path}/store"])
        _wait(lambda: _coord_up(coordinator), desc="controller up")
        procs["server"] = _spawn(
            ["StartServer", "--instance-id", "us0",
             "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(http_port)])

        client = CoordinationClient(coordinator)
        _wait(lambda: len(_non_broker_instances(client)) == 1,
              desc="server registered")

        prod = StreamProducer(stream.address)
        prod.create_topic("upserts")
        schema = Schema("ups", [
            FieldSpec("pk", DataType.INT, FieldType.DIMENSION),
            FieldSpec("ver", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        schema.primary_key_columns = ["pk"]
        cfg = TableConfig(name="ups", table_type=TableType.REALTIME)
        cfg.upsert = UpsertConfig(mode="FULL", comparison_column="ver")
        cfg.ingestion = IngestionConfig(stream=StreamIngestionConfig(
            stream_type="tcp", topic="upserts",
            properties={"bootstrap": stream.address,
                        "flushThresholdRows": "60",
                        "flushThresholdTimeMs": "3600000"}))
        client.add_table(cfg, schema)

        # 120 events over 40 pks (ver 1..3): two sealed segments of 60
        # docs; visible = 40 rows at the LAST version
        for ver in (1, 2, 3):
            for pk in range(40):
                prod.publish("upserts", {"pk": pk, "ver": ver,
                                         "v": pk * 10 + ver})
        sql = "SELECT COUNT(*), SUM(v) FROM ups"
        expect1 = [40, float(sum(pk * 10 + 3 for pk in range(40)))]

        def caught_up():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect1 and \
                not resp.get("exceptions")
        _wait(caught_up, timeout=60, desc="upsert rows via broker")

        def committed_segments():
            segs = client.get_state()["segments"].get("ups_REALTIME", {})
            return {n for n, s in segs.items() if s["status"] == "ONLINE"}
        _wait(lambda: len(committed_segments()) >= 2, timeout=30,
              desc="two sealed upsert segments")
        sealed_before = committed_segments()

        # kill mid-stream, publish a newer version for half the pks
        victim = procs.pop("server")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        for pk in range(20):
            prod.publish("upserts", {"pk": pk, "ver": 4, "v": pk * 10 + 4})

        # restart with the SAME instance id: reconcile loads the sealed
        # tars (validDocIds snapshots inside), the realtime manager
        # re-registers them into the upsert metadata, and consumption
        # resumes from the persisted end_offset
        procs["server_b"] = _spawn(
            ["StartServer", "--instance-id", "us0",
             "--coordinator", coordinator])
        expect2 = [40, float(sum(pk * 10 + 4 for pk in range(20))
                             + sum(pk * 10 + 3 for pk in range(20, 40)))]

        def recovered():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect2 and \
                not resp.get("exceptions")
        _wait(recovered, timeout=60,
              desc="restarted server converged last-wins")

        # no replay of committed rows: every pre-kill sealed segment is
        # still there UNchanged (re-consumption would have re-sealed
        # duplicate seqs / new names over the same offsets)
        assert sealed_before <= committed_segments()
    finally:
        stream.stop()
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")


def test_multiprocess_realtime_replicas_over_tcp_stream(tmp_path):
    """Two server PROCESSES consume the same TCP stream partition; the
    controller's completion FSM elects exactly one committer per segment;
    committed segments land in the deep store; killing a replica leaves
    correct answers (ref LLCRealtimeClusterIntegrationTest +
    SegmentCompletionIntegrationTest, promoted to real processes)."""
    from pinot_tpu.ingest.tcp_stream import StreamProducer, StreamServer
    from pinot_tpu.models.table_config import (IngestionConfig,
                                               StreamIngestionConfig)
    from pinot_tpu.models import TableType

    coord_port = _free_port()
    http_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"
    stream = StreamServer()
    stream.start()
    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", str(tmp_path / "state"),
             "--port", str(coord_port),
             "--deep-store", f"file://{tmp_path}/store"])
        _wait(lambda: _coord_up(coordinator), desc="controller up")
        for i in range(2):
            procs[f"server_{i}"] = _spawn(
                ["StartServer", "--instance-id", f"rs{i}",
                 "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(http_port)])

        client = CoordinationClient(coordinator)
        _wait(lambda: len(_non_broker_instances(client)) == 2,
              desc="servers registered")

        prod = StreamProducer(stream.address)
        prod.create_topic("events")
        schema = Schema("rte", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        cfg = TableConfig(name="rte", table_type=TableType.REALTIME)
        cfg.ingestion = IngestionConfig(stream=StreamIngestionConfig(
            stream_type="tcp", topic="events",
            properties={"bootstrap": stream.address,
                        "flushThresholdRows": "100",
                        "flushThresholdTimeMs": "3600000"}))
        client.add_table(cfg, schema)

        for i in range(250):
            prod.publish("events", {"id": i, "v": i})

        sql = "SELECT COUNT(*), SUM(id) FROM rte"
        expect = [250, float(sum(range(250)))]

        def caught_up():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect and \
                not resp.get("exceptions")
        _wait(caught_up, timeout=60, desc="realtime rows via broker")

        # exactly-one-committer held across PROCESSES: committed segments
        # exist with BOTH replicas registered (the KEEP replica's report
        # may lag a beat behind the committer's, so poll)
        def both_replicas_sealed():
            segs = client.get_state()["segments"].get("rte_REALTIME", {})
            online = [s for s in segs.values()
                      if s["status"] == "ONLINE"]
            return len(online) >= 2 and all(
                set(s["instances"]) == {"rs0", "rs1"} for s in online)
        _wait(both_replicas_sealed, timeout=30,
              desc="both replicas sealed committed segments")

        # chaos: kill one replica; the survivor keeps serving AND consuming
        victim = procs.pop("server_1")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        for i in range(250, 400):
            prod.publish("events", {"id": i, "v": i})
        expect2 = [400, float(sum(range(400)))]

        def still_correct():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect2 and \
                not resp.get("exceptions")
        _wait(still_correct, timeout=60,
              desc="survivor consumes + serves after replica kill")

        # restart the killed replica: it must resume from the persisted
        # checkpoint (end_offset + seq), NOT replay the stream from 0 —
        # counts stay exact with both replicas live again
        procs["server_1b"] = _spawn(
            ["StartServer", "--instance-id", "rs1",
             "--coordinator", coordinator])
        for i in range(400, 450):
            prod.publish("events", {"id": i, "v": i})
        expect3 = [450, float(sum(range(450)))]

        def resumed_exact():
            resp = _post_query(http_port, sql)
            rows = (resp.get("resultTable") or {}).get("rows")
            return bool(rows) and rows[0] == expect3 and \
                not resp.get("exceptions")
        _wait(resumed_exact, timeout=60,
              desc="restarted replica resumed from checkpoint")
    finally:
        stream.stop()
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_cluster_health_sweep_and_server_kill(tmp_path):
    """Fleet health plane acceptance (ISSUE 14): GET /cluster/health on
    a real multi-role cluster reports every role live; SIGKILLing a
    server flips its verdict to degraded within a couple of sweep
    intervals, with ZERO controller errors (the sweep degrades, never
    throws)."""
    coord_port = _free_port()
    http_port = _free_port()
    coordinator = f"127.0.0.1:{coord_port}"
    fast_sweep = {
        "PINOT_TPU_CLUSTER_HEALTH_INTERVAL_SECONDS": "0.5",
        "PINOT_TPU_CLUSTER_HEALTH_SCRAPE_TIMEOUT_SECONDS": "1.0",
    }
    procs = {}
    try:
        procs["controller"] = _spawn(
            ["StartController", "--state-dir", str(tmp_path / "state"),
             "--port", str(coord_port), "--http-port", str(http_port)],
            extra_env=fast_sweep)
        _wait(lambda: _coord_up(coordinator), desc="controller up")
        for i in range(2):
            procs[f"server_{i}"] = _spawn(
                ["StartServer", "--instance-id", f"server_{i}",
                 "--coordinator", coordinator])
        procs["broker"] = _spawn(
            ["StartBroker", "--coordinator", coordinator,
             "--http-port", str(_free_port())])

        # every role converges to live: controller self-target + two
        # servers (DebugHttpServer admin_url) + the broker's HTTP edge
        def all_live():
            h = _get_json(http_port, "/cluster/health")
            inst = h["instances"]
            roles = {e["role"] for e in inst.values()}
            return (len(inst) >= 4
                    and {"controller", "server", "broker"} <= roles
                    and h["instancesDegraded"] == 0
                    and all(e["verdict"] == "live"
                            for e in inst.values()))
        _wait(all_live, timeout=60, desc="every role live in the sweep")

        # fleet metrics roll up: per-family counters summed across
        # instances, per-instance gauges preserved
        m = _get_json(http_port, "/cluster/metrics")
        assert m["instances"], m
        assert any(k.startswith("metrics_history_samples")
                   for k in m["counters"]), sorted(m["counters"])[:10]

        # ---- SIGKILL one server: verdict flips, controller survives ---
        victim = procs.pop("server_1")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        t_kill = time.time()

        def victim_degraded():
            h = _get_json(http_port, "/cluster/health")
            e = h["instances"].get("server_1")
            return e is not None and e["verdict"] == "degraded" \
                and not e.get("reachable", True)
        _wait(victim_degraded, timeout=20,
              desc="killed server verdicted degraded")
        # promptness: a dead admin port refuses instantly, so the flip
        # lands within a few 0.5s sweep intervals, not the liveness TTL
        assert time.time() - t_kill < 15.0
        # zero controller errors: the process is alive and still serves
        # a parseable cluster verdict naming the survivor live
        assert procs["controller"].poll() is None
        h = _get_json(http_port, "/cluster/health")
        assert h["instances"]["server_0"]["verdict"] == "live"
        assert h["verdict"] == "degraded"
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
            if out:
                print(f"--- {name} ---\n{out[-2000:]}")
