"""Device-offload widening: big-int (epoch millis) filters via split
planes, FILTER-clause aggregations as per-slot masks, >65536-group
group-bys — all parity-checked against the host executor, with x64 OFF
(the production TPU default) where it matters.
"""
import numpy as np
import pytest

import jax

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.executor import QueryExecutor
from tests.queries.harness import build_segments

N = 5000
MS0 = 1_690_000_000_000  # epoch millis base (~2^40.6)


@pytest.fixture(scope="module")
def time_segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("toff")
    schema = Schema("testTable", [
        FieldSpec("tsMillis", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("dim", DataType.INT, FieldType.DIMENSION),
        FieldSpec("dim2", DataType.INT, FieldType.DIMENSION),
        FieldSpec("val", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("testTable", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["tsMillis"]
    rng = np.random.default_rng(5)
    cols = []
    for i in range(2):
        ts = MS0 + rng.integers(0, 90 * 24 * 3600 * 1000, N)
        # plant exact boundary values so strict-vs-nonstrict differs
        ts[: N // 10] = MS0 + 1000
        cols.append({
            "tsMillis": ts.astype(np.int64),
            "dim": rng.integers(0, 300, N).astype(np.int32),
            "dim2": rng.integers(0, 300, N).astype(np.int32),
            "val": rng.integers(0, 1000, N).astype(np.int32),
        })
    return build_segments(tmp, schema, tc, cols)


def _parity(segs, sql, engine=None, expect_offload=True):
    cpu = QueryExecutor(segs, use_tpu=False)
    eng = engine if engine is not None else TpuOperatorExecutor()
    tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
    a, b = cpu.execute(sql), tpu.execute(sql)
    assert not a.exceptions and not b.exceptions, (a.exceptions, b.exceptions)
    assert len(a.rows) == len(b.rows), (sql, a.rows, b.rows)
    for ra, rb in zip(a.rows, b.rows):
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                assert abs(float(x) - float(y)) <= \
                    1e-4 * max(1.0, abs(float(y))), (sql, a.rows, b.rows)
            else:
                assert x == y, (sql, a.rows, b.rows)
    if expect_offload:
        assert eng._block_cache, f"query fell back to host: {sql}"
    return b


class TestBigIntFilters:
    """Epoch-millis columns filter on device with x64 OFF (split planes)."""

    def test_between_exact_bounds(self, time_segs):
        with jax.enable_x64(False):
            lo, hi = MS0 + 1000, MS0 + 40 * 24 * 3600 * 1000
            r = _parity(time_segs,
                        f"SELECT COUNT(*), SUM(val) FROM testTable "
                        f"WHERE tsMillis BETWEEN {lo} AND {hi}")
            assert int(r.rows[0][0]) > 0

    def test_strict_gt_on_boundary(self, time_segs):
        with jax.enable_x64(False):
            b = MS0 + 1000  # planted boundary value
            gt = _parity(time_segs,
                         f"SELECT COUNT(*) FROM testTable WHERE tsMillis > {b}")
            ge = _parity(time_segs,
                         f"SELECT COUNT(*) FROM testTable WHERE tsMillis >= {b}")
            assert int(ge.rows[0][0]) - int(gt.rows[0][0]) >= N // 10

    def test_equals_and_combined(self, time_segs):
        with jax.enable_x64(False):
            b = MS0 + 1000
            _parity(time_segs,
                    f"SELECT COUNT(*), SUM(val) FROM testTable "
                    f"WHERE tsMillis = {b} AND dim < 150")

    def test_split_planes_staged(self, time_segs):
        with jax.enable_x64(False):
            eng = TpuOperatorExecutor()
            _parity(time_segs,
                    f"SELECT COUNT(*) FROM testTable WHERE tsMillis > {MS0}",
                    engine=eng)
            kinds = {k[1] for k in eng._block_cache}
            assert "valhi" in kinds and "vallo" in kinds


class TestFilterAggs:
    """FILTER (WHERE ...) aggregations offload as per-slot masks."""

    def test_filtered_sum_count(self, time_segs):
        _parity(time_segs,
                "SELECT SUM(val) FILTER (WHERE dim < 100) AS a, "
                "COUNT(*) FILTER (WHERE dim >= 200) AS b, "
                "SUM(val) AS total FROM testTable")

    def test_filtered_with_main_filter(self, time_segs):
        _parity(time_segs,
                "SELECT COUNT(*) FILTER (WHERE dim2 < 50) AS c, COUNT(*) "
                "FROM testTable WHERE dim BETWEEN 10 AND 250")

    def test_filtered_group_by(self, time_segs):
        _parity(time_segs,
                "SELECT dim, SUM(val) FILTER (WHERE dim2 < 150), COUNT(*) "
                "FROM testTable GROUP BY dim ORDER BY dim LIMIT 500")

    def test_same_filter_deduped(self, time_segs):
        eng = TpuOperatorExecutor()
        _parity(time_segs,
                "SELECT SUM(val) FILTER (WHERE dim < 100), "
                "COUNT(*) FILTER (WHERE dim < 100) FROM testTable",
                engine=eng)


class TestBigIntReviewRegressions:
    def test_aggregate_over_split_plane_column_falls_back(self, time_segs):
        """MIN/MAX over a vrange64-filtered big-int column must fall back
        to the host (no 'val:' block exists), not crash."""
        with jax.enable_x64(False):
            b = MS0 + 1000
            _parity(time_segs,
                    f"SELECT MIN(tsMillis), MAX(tsMillis) FROM testTable "
                    f"WHERE tsMillis > {b}", expect_offload=False)

    def test_epoch_nanos_falls_back(self, tmp_path):
        """Values >= 2^55 would wrap the i32 hi plane: host fallback."""
        schema = Schema("t", [
            FieldSpec("tsNanos", DataType.LONG, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig("t", TableType.OFFLINE)
        tc.indexing.no_dictionary_columns = ["tsNanos"]
        rng = np.random.default_rng(1)
        base = 1_690_000_000_000_000_000  # ~2^60.6
        cols = {"tsNanos": (base + rng.integers(0, 10**12, 500)
                            ).astype(np.int64),
                "v": rng.integers(0, 100, 500).astype(np.int32)}
        segs = build_segments(tmp_path, schema, tc, [cols])
        with jax.enable_x64(False):
            eng = TpuOperatorExecutor()
            _parity(segs,
                    f"SELECT COUNT(*), SUM(v) FROM t WHERE tsNanos > {base}",
                    engine=eng, expect_offload=False)
            kinds = {k[1] for k in eng._block_cache}
            assert "valhi" not in kinds

    def test_infinite_literal_falls_back(self, time_segs):
        with jax.enable_x64(False):
            _parity(time_segs,
                    "SELECT COUNT(*) FROM testTable WHERE tsMillis < 1e400",
                    expect_offload=False)


class TestLargeGroupBy:
    def test_90k_groups(self, time_segs):
        """dim x dim2 = 300*300 = 90000 combined keys — above the old
        65536 device cap; parity incl. group values."""
        eng = TpuOperatorExecutor()
        r = _parity(time_segs,
                    "SELECT dim, dim2, COUNT(*), SUM(val) FROM testTable "
                    "GROUP BY dim, dim2 ORDER BY dim, dim2 LIMIT 200",
                    engine=eng)
        assert len(r.rows) == 200
