"""Metrics, tracing, resource accounting (SURVEY.md §5 aux subsystems)."""
import threading
import time

import numpy as np
import pytest

from pinot_tpu.utils.accounting import (
    QueryCancelledError, ResourceAccountant)
from pinot_tpu.utils.metrics import MetricsRegistry, get_registry
from pinot_tpu.utils import tracing


class TestMetrics:
    def test_meters_gauges_timers(self):
        m = MetricsRegistry("test")
        m.add_meter("queries", labels={"table": "t"})
        m.add_meter("queries", 2, labels={"table": "t"})
        m.set_gauge("segments", 5)
        with m.time("exec"):
            pass
        assert m.meter("queries", {"table": "t"}) == 3
        assert m.gauge("segments") == 5
        assert m.timer("exec").count == 1

    def test_prometheus_text(self):
        m = MetricsRegistry("test")
        m.add_meter("q", labels={"table": "a"})
        m.set_gauge("g", 1.5)
        m.add_timing("t", 12.0)
        text = m.prometheus_text()
        assert 'pinot_tpu_test_q{table="a"} 1' in text
        assert "pinot_tpu_test_g 1.5" in text
        assert "pinot_tpu_test_t_count 1" in text

    def test_registry_singletons(self):
        assert get_registry("broker") is get_registry("broker")

    def test_thread_safety(self):
        m = MetricsRegistry("test")

        def work():
            for _ in range(1000):
                m.add_meter("n")

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert m.meter("n") == 8000


class TestTracing:
    def test_scope_tree(self):
        with tracing.RequestTrace(7) as rt:
            with tracing.Scope("A", x=1):
                with tracing.Scope("B") as b:
                    b.set(rows=10)
            with tracing.Scope("C"):
                pass
        d = rt.to_dict()
        assert d["operator"] == "BrokerRequest"
        assert [c["operator"] for c in d["children"]] == ["A", "C"]
        assert d["children"][0]["children"][0]["rows"] == 10
        assert d["children"][0]["durationMs"] >= 0

    def test_inactive_scopes_are_noops(self):
        assert not tracing.active()
        with tracing.Scope("orphan"):
            pass  # no crash, records nothing

    def test_trace_option_end_to_end(self, tmp_path):
        from pinot_tpu.query.executor import QueryExecutor
        from tests.queries.harness import (
            build_segments, synthetic_columns, synthetic_schema,
            synthetic_table_config)
        segs = build_segments(tmp_path, synthetic_schema(),
                              synthetic_table_config(),
                              [synthetic_columns(500, 1)])
        ex = QueryExecutor(segs, use_tpu=False)
        r = ex.execute("SELECT COUNT(*) FROM testTable OPTION(trace=true)")
        assert r.trace is not None
        ops = [c["operator"] for c in r.trace["children"]]
        assert "SegmentExecutor" in ops and "BrokerReduce" in ops
        assert r.to_dict()["traceInfo"]["operator"] == "BrokerRequest"
        r2 = ex.execute("SELECT COUNT(*) FROM testTable")
        assert r2.trace is None


class TestAccounting:
    def test_usage_tracking(self):
        acc = ResourceAccountant()
        acc.setup_worker("q1")
        # enough CPU work to straddle a thread-CPU clock tick even on
        # coarse-jiffy VMs (a 100k-iteration loop occasionally fit
        # inside one tick under load -> measured delta 0, flaky assert)
        t0 = time.thread_time_ns()
        n = 100_000
        while time.thread_time_ns() - t0 < 30_000_000:  # >=30ms CPU
            _ = sum(i * i for i in range(n))
        acc.record_allocation(1024)
        acc.clear_worker()
        u = acc.usage("q1")
        assert u.cpu_ns > 0
        assert u.bytes_allocated == 1024
        assert acc.finish_query("q1") is not None
        assert acc.usage("q1") is None

    def test_cooperative_cancellation(self):
        acc = ResourceAccountant()
        acc.setup_worker("q2")
        acc.check_cancelled()  # fine
        assert acc.cancel("q2")
        with pytest.raises(QueryCancelledError):
            acc.check_cancelled()
        acc.clear_worker()

    def test_timeout_kill(self):
        acc = ResourceAccountant(query_timeout_s=0.01)
        acc.setup_worker("q3")
        acc.clear_worker()
        time.sleep(0.05)
        killed = acc.watch_once()
        assert killed == ["q3"]

    def test_memory_pressure_kills_most_expensive(self):
        acc = ResourceAccountant(memory_limit_bytes=100)
        for qid, alloc in (("small", 10), ("big", 10_000)):
            acc.setup_worker(qid)
            acc.record_allocation(alloc)
            acc.clear_worker()
        killed = acc.watch_once(rss_bytes=200)
        assert killed == ["big"]
        # small survives
        assert not acc.usage("small").cancelled
